"""Priority lanes: admission classification for the serving core.

The admission pool alone is fair-queued — which is exactly the problem
at dashboard scale: eight slots all held by SF100 scans leave a 5 ms
TopN waiting out the queue timeout behind them.  Lanes split admission
into separate slot pools (`ResilienceState.lanes`):

  * **interactive** — TopN / timeseries / metadata queries and small
    groupBys: the dashboard traffic whose p95 the serving core exists
    to protect.
  * **heavy** — scans, searches, and groupBys whose in-scope row count
    exceeds `SessionConfig.lane_heavy_rows`: work that holds a slot for
    seconds-to-minutes and must not be able to occupy interactive
    capacity.

Classification reads only metadata (the query type and the
interval/zone-map-pruned segment row count) — never dispatches.  Each
lane carries its own queue depth, observed-load Retry-After, and
`sdol_lane_*` metrics; the server rejects per lane with 503 naming the
lane so clients can tell "the cluster is full" from "my scan class is
full".
"""

from __future__ import annotations

from ..models import query as Q

LANE_INTERACTIVE = "interactive"
LANE_HEAVY = "heavy"

LANES = (LANE_INTERACTIVE, LANE_HEAVY)

# query types answered from catalog metadata: never heavy
_METADATA_TYPES = (
    Q.TimeBoundaryQuery,
    Q.DataSourceMetadataQuery,
    Q.SegmentMetadataQuery,
)


def _rows_in_scope(q, ds) -> int:
    """Rows the query would scan after interval/zone-map pruning — the
    same metadata-only scoping the engine performs before dispatch."""
    from ..exec.engine import segments_in_scope

    try:
        return sum(s.num_rows for s in segments_in_scope(q, ds))
    except Exception:  # fault-ok: lane routing must never fail a query
        return ds.num_rows if ds is not None else 0


def classify_native(q, ds, config) -> str:
    """Lane of one decoded native QuerySpec.  TopN/timeseries/search and
    metadata queries are interactive by type (the dashboard shapes);
    scans and groupBys go heavy past the configured row threshold."""
    if isinstance(q, _METADATA_TYPES):
        return LANE_INTERACTIVE
    if isinstance(q, (Q.TopNQuery, Q.TimeseriesQuery)):
        return LANE_INTERACTIVE
    threshold = int(getattr(config, "lane_heavy_rows", 4 << 20))
    if threshold <= 0:
        return LANE_INTERACTIVE
    if isinstance(q, (Q.ScanQuery, Q.SearchQuery, Q.GroupByQuery)):
        if ds is not None and _rows_in_scope(q, ds) > threshold:
            return LANE_HEAVY
    return LANE_INTERACTIVE


def classify_rewrite(rw, catalog, config) -> str:
    """Lane of a planned SQL rewrite — the same policy as
    `classify_native`, applied to the rewrite's device query.  Exact-
    distinct shapes classify by their inner rewrite (that is what
    scans)."""
    if rw.exact_distinct is not None:
        return classify_rewrite(rw.exact_distinct.inner, catalog, config)
    ds = catalog.get(rw.datasource)
    return classify_native(rw.query, ds, config)
