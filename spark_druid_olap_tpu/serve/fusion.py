"""Micro-batch query fusion: amortize the device dispatch across
concurrent compatible queries.

The re-anchor numbers frame the problem: SF1 TPU p50 is 102 ms against
a 66 ms device round trip — the dispatch floor IS the latency budget,
and at dashboard scale the workload is many small concurrent queries
over the same hot datasource.  Computation-pushdown economics
(arXiv:2312.15405) say to amortize the boundary across queries:

  * The FIRST query to arrive for a (datasource, segment-set signature)
    becomes the batch LEADER: it holds the batch open for
    `SessionConfig.fusion_window_ms`, collecting compatible queries
    (GroupBy-family, same signature) up to `fusion_max_batch`.
  * The leader executes the whole batch as ONE fused device program
    (`Engine.execute_fused`): the union of the members' in-scope
    segments moves host->device once, every member's partial aggregation
    runs inside the same dispatch, one fetch returns all states.
  * Results demultiplex per member: each waiter receives its own
    finalized frame, host partial state (the delta-aware result cache
    stores it), and QueryMetrics stamped with ITS query_id and the batch
    size (`fused_batch`) — serving-discipline GL1702.

Compatibility is the segment-set signature (`lowering.schema_signature`:
name + dictionary content + segment uids).  An append between enqueue
and dispatch bumps the signature; the leader detects the mismatch at
dispatch time and INVALIDATES the batch — every member re-executes
individually on its own thread, against the current snapshot and under
its own deadline/partial scopes (fused execution cannot honor N
different deadline budgets, so an invalidated batch must not be run by
the leader on the members' behalf).

A batch of one (no concurrency materialized inside the window) is also
re-routed to the member's serial path: the fused program brings only
demux overhead when there is nothing to amortize.
"""

from __future__ import annotations

import itertools
import threading
import time
from typing import Dict, List, Optional, Tuple

from collections import deque

from ..obs import SPAN_FUSED_BATCH, current_query_id, prof, span, span_event
from ..utils.log import get_logger

log = get_logger("serve.fusion")

# a member blocked on its batch leader must never hang the request
# thread forever if the leader dies mid-delivery; past this it falls
# back to its own serial execution
_MEMBER_WAIT_S = 300.0


def shared_row_plan(inners) -> tuple:
    """Common-subexpression dedup over member lowerings (ROADMAP 1(a)).

    Dashboard members of one fused batch routinely share the expensive
    row-pipeline prefixes: the FILTER MASK (intervals + filter over the
    same virtual columns) and the GROUP-ID pipeline (same dimensions +
    granularity).  Without dedup the fused program re-traces both per
    member — N identical filter evaluations over the same segment
    columns in one kernel.

    Returns one `(mask_group, gid_group)` pair per member, where each
    group id is the index of the FIRST member with an identical
    sub-lowering signature: inside the fused program, later members
    reuse that member's computed mask / gid for each segment instead of
    recomputing it (engine._segment_partials threads a per-segment memo
    through `GroupByLowering.row_arrays`).  Signatures come from the
    canonical wire JSON of the rewritten inner GroupBy — the same
    identity the program cache keys on — so two members share a group
    ONLY when the traced subexpression is value-identical."""
    import json as _json

    def _sig(val):
        return _json.dumps(val, sort_keys=True, default=str)

    mask_groups: Dict[tuple, int] = {}
    gid_groups: Dict[tuple, int] = {}
    plan = []
    for i, q in enumerate(inners):
        d = q.to_druid()
        vsig = _sig(d.get("virtualColumns") or [])
        isig = _sig(d.get("intervals"))
        msig = (vsig, _sig(d.get("filter")), isig)
        # intervals belong in the gid signature too: a time-bucketed
        # dimension's codes_fn closes over the query's interval span
        # (bucket origin + cardinality), so two members with identical
        # dimensions but shifted intervals compute DIFFERENT gids —
        # sharing them returned silently wrong aggregates for the
        # second member (review finding, regression-tested)
        gsig = (
            vsig,
            _sig(d.get("dimensions") or []),
            _sig(d.get("granularity")),
            isig,
        )
        plan.append(
            (
                mask_groups.setdefault(msig, i),
                gid_groups.setdefault(gsig, i),
            )
        )
    return tuple(plan)

# delivery verdicts
_OK = "ok"
_RETRY = "retry"  # re-execute individually on the member's own thread


class _Member:
    __slots__ = ("query", "query_id", "event", "verdict", "payload")

    def __init__(self, query, query_id: str):
        self.query = query
        self.query_id = query_id
        self.event = threading.Event()
        self.verdict: Optional[str] = None
        self.payload = None

    def deliver(self, verdict: str, payload=None) -> None:
        self.verdict = verdict
        self.payload = payload
        self.event.set()


class _Batch:
    __slots__ = ("batch_id", "signature", "members", "closed", "engine")

    def __init__(self, batch_id: int, signature, engine=None):
        self.batch_id = batch_id
        self.signature = signature
        self.members: List[_Member] = []
        self.closed = False
        # executing backend (None = the context's local engine); the
        # signature carries a backend label so a mesh-routed query and a
        # single-device one never land in the same batch
        self.engine = engine


class FusionScheduler:
    """Leader-based micro-batcher over one context's local engine.

    `execute` returns `(df, state, metrics)` when the query ran fused,
    or None when the caller must execute it on the normal serial path
    (fusion disabled, batch of one, batch invalidated by a concurrent
    append, or the fused dispatch failed)."""

    def __init__(
        self,
        window_ms: float = 0.0,
        max_batch: int = 16,
        adaptive: bool = False,
        max_window_ms: float = 0.0,
    ):
        self.window_ms = float(window_ms)
        self.max_batch = max(2, int(max_batch))
        # adaptive window (ROADMAP 1(b)): arm the hold window from the
        # OBSERVED arrival rate — an idle queue pays no wait at all (the
        # static window taxes every solo query the full window for
        # nothing), a burst holds up to max_window_ms so more members
        # amortize the dispatch.  The decision is recorded as a
        # `fusion_window` span event on the leader's trace.
        self.adaptive = bool(adaptive)
        self.max_window_ms = (
            float(max_window_ms) if max_window_ms else 4.0 * float(window_ms)
        )
        self._arrivals: deque = deque(maxlen=64)
        self.window_decisions: Dict[str, int] = {}
        self._lock = threading.Lock()
        self._open: Dict[Tuple, _Batch] = {}
        self._ids = itertools.count(1)
        # observability: fused batches executed / member outcomes
        self.batches_fused = 0
        self.members_fused = 0
        self.invalidated = 0

    @property
    def enabled(self) -> bool:
        return self.window_ms > 0

    def _decide_window_ms(self, now: float) -> Tuple[float, str, int]:
        """(window_ms, mode, recent_arrivals) for a leader arriving at
        `now` — BEFORE its own arrival is recorded, so the decision
        reads only the queue's recent history.  idle: no arrival within
        8 windows -> no wait; burst: >=3 arrivals within 2 windows ->
        hold up to max_window_ms; base: the configured window."""
        if not self.adaptive:
            return self.window_ms, "static", 0
        horizon = 8.0 * self.window_ms / 1e3
        burst_horizon = 2.0 * self.window_ms / 1e3
        with self._lock:
            recent = [t for t in self._arrivals if now - t <= horizon]
        if not recent:
            return 0.0, "idle", 0
        burst = sum(1 for t in recent if now - t <= burst_horizon)
        if burst >= 3:
            return (
                min(self.max_window_ms, 2.0 * self.window_ms),
                "burst",
                len(recent),
            )
        return self.window_ms, "base", len(recent)

    def _note_arrival(self, now: float) -> None:
        with self._lock:
            self._arrivals.append(now)

    def execute(self, ctx, q, ds, engine=None):
        """Join (or lead) the micro-batch for `q` over the `ds`
        snapshot.  Returns (df, state, metrics) or None (serial path).
        `engine` is the executing backend (None = ctx.engine); distinct
        backends hash to distinct signatures, so a batch is always
        dispatched by the engine every one of its members routed to."""
        if not self.enabled:
            return None
        from ..exec.lowering import schema_signature

        if engine is None or engine is ctx.engine:
            engine, backend = None, "device"
        else:
            backend = "mesh"
        now = time.monotonic()
        window_ms, mode, n_recent = self._decide_window_ms(now)
        self._note_arrival(now)
        sig = (ds.name, backend, schema_signature(ds))
        me = _Member(q, current_query_id())
        with self._lock:
            batch = self._open.get(sig)
            if (
                batch is None
                or batch.closed
                or len(batch.members) >= self.max_batch
            ):
                batch = _Batch(next(self._ids), sig, engine=engine)
                self._open[sig] = batch
                leader = True
            else:
                leader = False
            batch.members.append(me)
        if leader:
            # record the arrival-rate decision (ROADMAP 1(b)): the span
            # event says what the scheduler chose and why, so "why did
            # my solo query not wait" / "why did the burst hold longer"
            # reads off the trace
            with self._lock:
                self.window_decisions[mode] = (
                    self.window_decisions.get(mode, 0) + 1
                )
            span_event(
                "fusion_window",
                window_ms=round(window_ms, 3),
                mode=mode,
                recent_arrivals=n_recent,
            )
            self._lead(ctx, batch, ds, window_ms=window_ms)
        else:
            if not me.event.wait(_MEMBER_WAIT_S):
                log.warning(
                    "fused-batch member timed out waiting for its "
                    "leader; executing serially"
                )
                return None
        if me.verdict != _OK:
            return None
        df, state, m = me.payload
        # receipt attribution: every member's scope records the batch
        # size it rode (the leader's was stamped inside execute_fused)
        prof.note_fusion(len(batch.members))
        if not leader:
            # a NON-leader member's trace records that this query rode a
            # fused batch (the leader's trace already holds the real
            # fused_batch span around the execution — a second marker
            # there would double-count batches per trace); the batch id
            # + member query ids link the two traces
            with span(
                SPAN_FUSED_BATCH,
                batch=batch.batch_id,
                members=len(batch.members),
            ):
                span_event(
                    "fused_members",
                    query_ids=",".join(
                        x.query_id for x in batch.members
                    ),
                )
        return df, state, m

    def _lead(self, ctx, batch: _Batch, ds, window_ms: Optional[float] = None) -> None:
        """Leader protocol: hold the window open (the adaptive decision
        when one was made), close the batch, and either execute it fused
        or invalidate it (every member then re-executes individually on
        its own thread)."""
        from ..exec.lowering import schema_signature

        hold_ms = self.window_ms if window_ms is None else window_ms
        if hold_ms > 0:
            time.sleep(hold_ms / 1e3)
        with self._lock:
            batch.closed = True
            if self._open.get(batch.signature) is batch:
                del self._open[batch.signature]
            members = list(batch.members)
        # canonical member order: thread arrival order varies per wave,
        # and the fused program cache keys on the member sequence — an
        # order-sensitive key would recompile the SAME dashboard set on
        # every permutation (members are independent, so order is free)
        import json as _json

        members.sort(
            key=lambda m: _json.dumps(
                m.query.to_druid(), sort_keys=True, default=str
            )
        )
        try:
            if len(members) == 1:
                # nothing joined: the fused program would only add demux
                # overhead — run the member's normal serial path
                members[0].deliver(_RETRY)
                return
            current = ctx.catalog.get(ds.name)
            if current is None or (
                (ds.name, batch.signature[1], schema_signature(current))
                != batch.signature
            ):
                # an append/compaction published a new segment set
                # between enqueue and dispatch: the batch's snapshot is
                # stale — split it, each member re-executes against the
                # CURRENT snapshot under its own scopes
                with self._lock:
                    self.invalidated += 1
                log.info(
                    "fused batch %d invalidated by a segment-set version "
                    "bump on %r; %d members re-execute individually",
                    batch.batch_id, ds.name, len(members),
                )
                for m in members:
                    m.deliver(_RETRY)
                return
            with span(
                SPAN_FUSED_BATCH,
                batch=batch.batch_id,
                members=len(members),
            ):
                span_event(
                    "fused_members",
                    query_ids=",".join(m.query_id for m in members),
                )
                results = (batch.engine or ctx.engine).execute_fused(
                    [m.query for m in members],
                    current,
                    query_ids=[m.query_id for m in members],
                )
            with self._lock:
                self.batches_fused += 1
                self.members_fused += len(members)
            for m, payload in zip(members, results):
                m.deliver(_OK, payload)
        except Exception as err:
            # ANY fused-path failure (transient device fault, deadline,
            # compile error) re-routes every member to its own serial
            # execution — the serial path owns retries, breaker
            # accounting, and partial-result semantics per query, which
            # a shared fused dispatch cannot honor per member
            log.warning(
                "fused batch %d failed (%s: %s); %d members re-execute "
                "individually",
                batch.batch_id, type(err).__name__, err, len(members),
            )
            for m in members:
                if not m.event.is_set():
                    m.deliver(_RETRY)
        finally:
            # defensive: no member may ever be left waiting
            for m in members:
                if not m.event.is_set():
                    m.deliver(_RETRY)

    def to_dict(self) -> dict:
        with self._lock:
            return {
                "enabled": self.enabled,
                "window_ms": self.window_ms,
                "adaptive": self.adaptive,
                "max_window_ms": self.max_window_ms,
                "window_decisions": dict(self.window_decisions),
                "max_batch": self.max_batch,
                "batches_fused": self.batches_fused,
                "members_fused": self.members_fused,
                "invalidated": self.invalidated,
            }
