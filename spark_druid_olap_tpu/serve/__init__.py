"""Async serving core (ISSUE 8): the layer between the HTTP server /
api surface and the execution engines, built for thousands of small
concurrent dashboard queries over a few hot datasources.

Three cooperating pieces:

  * `serve.fusion` — micro-batch query fusion: compatible concurrent
    queries (same datasource + segment-set signature) queue for a
    configurable few-ms window and execute as ONE fused device program
    (`Engine.execute_fused`), amortizing the per-dispatch round trip N
    ways; results demultiplex per query with individually-stamped
    QueryMetrics and a `fused_batch` span linking member query ids.
  * `serve.lanes` — priority lanes on admission: cheap TopN/timeseries
    dashboard queries take an interactive slot pool an SF100-scale scan
    cannot starve; each lane has its own depth, Retry-After, and
    `sdol_lane_*` metrics (the pools live on `ResilienceState.lanes`).
  * `serve.result_cache` — a result cache keyed on the monotonic
    per-datasource version (catalog/cache.py), upgraded to DELTA-AWARE
    reuse: on a streamed append the cache serves `(cached historical
    partial) ⊕ (fresh delta partials)` instead of invalidating, so
    identical dashboard refreshes never reach the device and appends
    only cost the delta.

`ServingCore` (serve/core.py) owns all three for one TPUOlapContext.
"""

from .core import ServingCore  # noqa: F401
from .fusion import FusionScheduler  # noqa: F401
from .lanes import LANE_HEAVY, LANE_INTERACTIVE, classify_native  # noqa: F401
from .result_cache import ResultCache  # noqa: F401
