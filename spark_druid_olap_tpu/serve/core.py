"""ServingCore: one context's async serving machinery.

Owns the fusion scheduler, the delta-aware result cache, and the lane
classification for SQL text (native queries classify from their decoded
QuerySpec directly; SQL classifies from the planned rewrite, through
the plan cache so repeated dashboard statements pay planning once).

The api layer calls in at three points:

  * `cached_result(rw, ds)` — version-exact hit, or a delta-aware
    refresh that scans ONLY freshly-appended segments and merges them
    with the cached historical partial state;
  * `fused_execute(q, ds)` — micro-batch fusion for GroupBy-family
    rewrites (None = caller runs the serial path);
  * `store_result(rw, ds, df, state)` — publish one computed answer
    (frame + optional mergeable state) at the snapshot's version.

The server calls `lane_for_sql` / `serve.lanes.classify_native` to
route admission through `ResilienceState.lanes`.
"""

from __future__ import annotations

import time
from typing import Optional

from ..obs import current_query_id, get_registry, prof, record_query_metrics
from ..utils.log import get_logger
from .fusion import FusionScheduler
from .lanes import LANE_INTERACTIVE, classify_rewrite
from .result_cache import ResultCache

log = get_logger("serve.core")


class ServingCore:
    def __init__(self, ctx):
        self.ctx = ctx
        cfg = ctx.config
        self.fusion = FusionScheduler(
            window_ms=getattr(cfg, "fusion_window_ms", 0.0),
            max_batch=getattr(cfg, "fusion_max_batch", 16),
            adaptive=getattr(cfg, "fusion_adaptive_window", False),
            max_window_ms=getattr(cfg, "fusion_window_max_ms", 0.0),
        )
        self.result_cache = ResultCache(
            entries=getattr(cfg, "result_cache_entries", 64),
            delta_reuse=getattr(cfg, "result_cache_delta_reuse", True),
        )
        # cross-request decoded-QuerySpec plan cache on the wire path
        # (ROADMAP 1(c)): native queries re-decode JSON per request even
        # though dashboards POST the identical body every refresh — key
        # on the context-stripped body and skip `query_from_druid`
        # entirely on a hit, shaving the fast lane's floor.  Decode is a
        # pure function of the body (no catalog input), so entries never
        # need invalidation.
        from ..utils.lru import CountBudgetCache

        self.wire_plan_cache = CountBudgetCache(256)

    # -- wire plan cache (ROADMAP 1(c)) --------------------------------------

    def decode_native(self, body: dict):
        """Decode one native-query body into its QuerySpec through the
        body-hash plan cache.  `sdol_plan_cache_total{outcome}` makes
        the fast-lane floor shave visible in `/status/profile`."""
        import hashlib
        import json as _json

        from ..models.wire import query_from_druid

        ctr = get_registry().counter(
            "sdol_plan_cache_total",
            "decoded-QuerySpec plan cache on the wire path, by outcome",
            labels=("outcome",),
        )
        try:
            # context carries per-request noise (queryId, timeout, ...)
            # the SERVER consumes outside the decode — strip exactly
            # those keys so every dashboard refresh of the same query
            # hits.  Everything else in context STAYS in the key:
            # skipEmptyBuckets/outputName shape the decoded timeseries
            # spec (models/wire.py), and unknown keys are kept
            # conservatively (a miss is cheap; a false hit serves the
            # wrong QuerySpec).
            noise = ("queryId", "timeout", "progressive", "partialResults")
            qctx = body.get("context")
            canon_body = {k: v for k, v in body.items() if k != "context"}
            if isinstance(qctx, dict):
                kept = {k: v for k, v in qctx.items() if k not in noise}
                if kept:
                    canon_body["context"] = kept
            canon = _json.dumps(canon_body, sort_keys=True)
        except (TypeError, ValueError):
            ctr.labels(outcome="uncacheable").inc()
            return query_from_druid(body)
        key = hashlib.sha1(canon.encode()).digest()
        hit = self.wire_plan_cache.get(key)
        if hit is not None:
            ctr.labels(outcome="hit").inc()
            return hit
        q = query_from_druid(body)  # decode errors keep their 400 path
        self.wire_plan_cache[key] = q
        ctr.labels(outcome="miss").inc()
        return q

    # -- result cache --------------------------------------------------------

    def cached_result(self, rw, ds, key, allow_delta: bool = True):
        """Serve `rw` from the cache: a version-exact hit (zero device
        dispatch), or — when an append bumped the version but retired
        nothing — a delta-aware refresh merging the cached historical
        partial with partials over ONLY the fresh segments.  Returns the
        final frame (post-processed) or None.  `allow_delta=False` skips
        the refresh (the breaker-open path must not dispatch to a sick
        device just to freshen a cache entry)."""
        return self._cached(
            rw.query, ds, key, allow_delta,
            post=lambda df: self.ctx._post_process(rw, ds, df),
        )

    def native_key(self, q, ds):
        """Result-cache key of one wire-native QuerySpec, or None when
        it isn't cacheable (non-aggregate types, wire subtotals — their
        expansion runs through the SQL machinery).  Same shape contract
        as api._result_key: dictionary signature in, segment uids OUT
        (entries carry version + covered uids for delta reuse)."""
        import json as _json

        from ..exec.lowering import _dict_signature
        from ..models import query as Q

        if not isinstance(
            q, (Q.GroupByQuery, Q.TimeseriesQuery, Q.TopNQuery)
        ):
            return None
        if isinstance(q, Q.GroupByQuery) and q.subtotals:
            return None
        return (
            "native",
            _json.dumps(q.to_druid(), sort_keys=True, default=str),
            ds.name,
            _dict_signature(ds),
            repr(self.ctx.config),
        )

    def cached_native(self, q, ds, allow_delta: bool = True, key=None):
        """The native wire route's cache lookup: dashboards POSTing the
        same QuerySpec each refresh never reach the device (exact hit),
        and after an append pay only the delta.  None on a miss or for
        uncacheable types.  `key` lets the caller reuse one computed
        key across lookup and store (native_key JSON-serializes the
        spec — once per request, not three times)."""
        key = key if key is not None else self.native_key(q, ds)
        if key is None:
            return None
        return self._cached(q, ds, key, allow_delta, post=None)

    def _cached(self, q, ds, key, allow_delta, post):
        cfg = self.ctx.config
        if key is None or cfg.result_cache_entries <= 0:
            return None
        version = ds.version
        hit = self.result_cache.get(key, version)
        if hit is not None:
            self._stamp_hit_metrics(q, "result-cache")
            return hit
        # delta_reuse reads the LIVE session config (a SET flips it
        # mid-session), not the construction-time snapshot
        if not (
            allow_delta
            and getattr(cfg, "result_cache_delta_reuse", True)
        ):
            return None
        entry = self.result_cache.reusable_entry(
            key, version, (s.uid for s in ds.segments)
        )
        if entry is None:
            self.result_cache.note_miss()
            return None
        try:
            out = self._delta_refresh(q, ds, key, entry, post)
        except Exception:
            # a failed refresh must cost nothing but the attempt: the
            # caller falls through to normal (full) execution
            log.warning(
                "delta-aware cache refresh failed; executing in full",
                exc_info=True,
            )
            out = None
        if out is None:
            self.result_cache.note_miss()
        return out

    def _delta_refresh(self, q, ds, key, entry, post=None):
        """(cached historical partial) ⊕ (fresh delta partials): scan
        only the segments the entry has not covered, merge states,
        re-finalize (+ the surface's host post-processing), re-cache at
        the new version.  Returns None when the delta scan was
        deadline-truncated — the caller then misses into the full
        execution path, which owns partial-answer semantics."""
        from ..resilience import current_partial

        t0 = time.perf_counter()
        engine = self.ctx.engine
        fresh_uids = frozenset(
            s.uid for s in ds.segments if s.uid not in entry.uids
        )
        delta_state, delta_rows = engine.groupby_partials_host(
            q, ds, within_uids=fresh_uids
        )
        pc = current_partial()
        if pc is not None and pc.triggered:
            # the deadline expired mid-delta-scan: the segment loop
            # returned TRUNCATED partials without raising (that is the
            # anytime-answer contract) — merging them would cache an
            # incomplete frame as the exact answer at the new version
            log.warning(
                "delta-aware refresh deadline-truncated; missing into "
                "full execution"
            )
            return None
        merged = engine.merge_groupby_states(
            q, ds, entry.state, delta_state
        )
        df = engine.finalize_groupby_state(q, ds, merged)
        if post is not None:
            df = post(df)
        self.result_cache.put(
            key, df,
            version=ds.version,
            uids=frozenset(s.uid for s in ds.segments),
            state=merged,
        )
        self.result_cache.note_delta_hit(entry)
        m = self._stamp_hit_metrics(q, "result-cache-delta")
        m.rows_scanned = delta_rows
        m.delta_rows_seen = delta_rows
        m.total_ms = (time.perf_counter() - t0) * 1e3
        log.info(
            "delta-aware cache refresh on %r: %d fresh segments / %d "
            "rows merged onto the cached historical partial",
            ds.name, len(fresh_uids), delta_rows,
        )
        return df.copy()

    def _stamp_hit_metrics(self, q, strategy: str):
        """QueryMetrics for a cache-served answer (wire-style query_type
        so the hit lands on the same metric series as executed
        siblings), stamped as the context's most-recent metrics."""
        from ..exec.metrics import QueryMetrics

        try:
            qt = q.to_druid().get("queryType", type(q).__name__)
        except Exception:  # fault-ok: metrics labeling must not fail a hit
            qt = type(q).__name__
        m = QueryMetrics(
            query_type=qt,
            strategy=strategy,
            executor="device",
            query_id=current_query_id(),
        )
        self.ctx._last_engine_metrics = m
        record_query_metrics(m, "ok")
        # cost-receipt cache attribution (obs/prof.py): the receipt's
        # result_cache outcome — "hit" (zero dispatch) vs "delta"
        prof.note_result_cache(
            "delta" if strategy == "result-cache-delta" else "hit"
        )
        return m

    def store_result(self, rw, ds, key, df, state=None) -> None:
        """Publish one computed answer at the executed snapshot's OWN
        stamped version (never the live catalog's — an append racing
        this write must read as a version mismatch, not as freshness the
        answer does not have)."""
        if key is None or self.ctx.config.result_cache_entries <= 0:
            return
        self.result_cache.put(
            key, df,
            version=ds.version,
            uids=frozenset(s.uid for s in ds.segments),
            state=state,
        )

    def store_native(self, q, ds, df, state=None, key=None) -> None:
        """Publish one native answer — with the partial-hygiene guard
        here (the SQL surface's equivalent guard lives in
        execute_rewrite): a deadline-truncated frame must never be
        served back as the exact answer.  No-ops when the session's
        cache is off (the capacity floor of 1 must not retain a latent
        entry a later config flip would serve)."""
        from ..resilience import current_partial

        if self.ctx.config.result_cache_entries <= 0:
            return
        key = key if key is not None else self.native_key(q, ds)
        if key is None:
            return
        pc = current_partial()
        if pc is not None and pc.triggered:
            return
        self.result_cache.put(
            key, df,
            version=ds.version,
            uids=frozenset(s.uid for s in ds.segments),
            state=state,
        )

    # -- fusion --------------------------------------------------------------

    def fused_execute(self, q, ds, engine=None) -> Optional[tuple]:
        """Micro-batch fusion entry: (df, state, metrics) or None.
        `engine` selects the executing backend (None = the context's
        local engine; the mesh's DistributedEngine batches through its
        unified SPMD arena) — backends never share a batch."""
        if not self.fusion.enabled:
            return None
        return self.fusion.execute(self.ctx, q, ds, engine=engine)

    # -- lanes ---------------------------------------------------------------

    def lane_for_sql(self, sql_text: str) -> str:
        """Admission lane of one SQL statement, from its planned rewrite
        (through the plan cache, so repeated dashboard statements pay
        planning once — and ctx.sql then hits the same entry).  Anything
        unplannable (commands, fallback-bound shapes, parse errors)
        classifies interactive; real errors resurface on the execution
        path with their proper taxonomy."""
        ctx = self.ctx
        try:
            from ..sql.commands import parse_command

            if parse_command(sql_text) is not None:
                return LANE_INTERACTIVE
            key = ctx._plan_cache_key(sql_text)
            cached = ctx._plan_cache.get(key)
            if cached is not None:
                rw, _lp = cached
            else:
                from ..sql.parser import parse_sql

                lp, explain, _ = parse_sql(sql_text, views=ctx.views)
                if explain:
                    return LANE_INTERACTIVE
                rw = ctx._planner().plan(lp)
                ctx._plan_cache[key] = (rw, lp)
            return classify_rewrite(rw, ctx.catalog, ctx.config)
        except Exception:  # fault-ok: lane routing must never fail a query
            return LANE_INTERACTIVE

    def to_dict(self) -> dict:
        return {
            "fusion": self.fusion.to_dict(),
            "result_cache": self.result_cache.to_dict(),
            "wire_plan_cache_entries": len(self.wire_plan_cache),
        }
