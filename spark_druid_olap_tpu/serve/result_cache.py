"""Version-keyed result cache with delta-aware reuse (ISSUE 8).

The api's seed result cache keyed entries on the full segment-set
signature: correct, but every streamed append was a FULL invalidation —
a dashboard refreshing each second against a datasource appending each
second never hit.  This cache exploits the partial-aggregate-state
algebra instead (cf. arXiv:2603.26698: every aggregate state in the
engine is mergeable):

  * Entries key on the query identity + the DICTIONARY signature (never
    the segment uids) and carry the monotonic per-datasource `version`
    (catalog/cache.py — the hook PR 6 installed) plus the exact segment
    uid set the cached answer covered.
  * A version-exact lookup is a plain hit: the final frame serves with
    ZERO device dispatch.
  * A version-bumped lookup whose entry still covers a SUBSET of the
    live segment set (an append published new segments, none retired)
    reuses delta-aware: the engine scans ONLY the fresh segments,
    merges `(cached historical partial) ⊕ (fresh delta partials)`, and
    the refreshed entry re-caches at the new version — the append cost
    the delta, not the history.
  * A retired uid (compaction), a dictionary extension (the key
    changes), or a missing partial state (the answer came off the
    sparse/adaptive/mesh/fallback paths, which hold no dense state) is
    a full miss.

Writes go through `put(...)` with a REQUIRED keyword `version` — the
serving-discipline lint pass (GL1701) rejects result-cache writes that
do not carry the datasource version, because an unversioned entry is
exactly the stale-dashboard bug this cache exists to prevent.
"""

from __future__ import annotations

import threading
from typing import Dict, FrozenSet, Optional

from ..utils.log import get_logger
from ..utils.lru import CountBudgetCache

log = get_logger("serve.result_cache")

# partial states larger than this are not retained (a cached [G, M]
# state is HOST RAM held per entry; a huge-G state would let 64 cached
# dashboards pin gigabytes) — the entry degrades to frame-only
_STATE_BYTES_MAX = 32 << 20


def _state_nbytes(state) -> int:
    if state is None:
        return 0
    total = 0
    for k in ("sums", "mins", "maxs"):
        total += int(getattr(state[k], "nbytes", 0))
    for v in state.get("sketches", {}).values():
        total += int(getattr(v, "nbytes", 0))
    return total


class CacheEntry:
    __slots__ = ("df", "state", "version", "uids", "hits", "delta_hits")

    def __init__(self, df, state, version: int, uids: FrozenSet):
        self.df = df
        self.state = state
        self.version = int(version)
        self.uids = frozenset(uids)
        self.hits = 0
        self.delta_hits = 0


class ResultCache:
    """LRU result cache of final frames + mergeable partial states."""

    def __init__(self, entries: int = 64, delta_reuse: bool = True):
        self.entries = max(int(entries), 0)
        self.delta_reuse = bool(delta_reuse)
        self._cache = CountBudgetCache(max(self.entries, 1))
        self._lock = threading.Lock()
        self.hits = 0
        self.delta_hits = 0
        self.misses = 0

    @property
    def enabled(self) -> bool:
        # capacity is a CACHE property; whether lookups happen at all is
        # the session config's live decision (the api layer gates on
        # `config.result_cache_entries > 0` per query, so flipping the
        # config mid-session enables/disables without a rebuild)
        return self._cache.budget_entries > 0

    def _count(self, outcome: str) -> None:
        from ..obs import get_registry

        with self._lock:
            if outcome == "hit":
                self.hits += 1
            elif outcome == "delta":
                self.delta_hits += 1
            else:
                self.misses += 1
        get_registry().counter(
            "sdol_result_cache_total",
            "result-cache lookups by outcome (hit = zero device "
            "dispatch; delta = cached historical ⊕ fresh delta)",
            labels=("outcome",),
        ).labels(outcome=outcome).inc()

    def get(self, key, version: int):
        """Version-exact hit: the cached final frame, or None.  Counts
        only genuine hits — the miss (and the delta outcome) is counted
        by the caller once it knows which path served."""
        if not self.enabled:
            return None
        entry: Optional[CacheEntry] = self._cache.get(key)
        if entry is None or entry.version != int(version):
            return None
        entry.hits += 1
        self._count("hit")
        return entry.df.copy()

    def reusable_entry(self, key, version: int, current_uids) -> Optional[
        CacheEntry
    ]:
        """The entry a delta-aware refresh can extend: present, stale by
        version, holding a partial state, and covering a strict SUBSET
        of the live segment uids (segments were appended, none retired).
        None otherwise."""
        if not self.enabled:
            return None
        entry: Optional[CacheEntry] = self._cache.get(key)
        if entry is None or entry.state is None:
            return None
        if entry.version == int(version):
            return None  # exact hit path should have served already
        current_uids = frozenset(current_uids)
        if not entry.uids < current_uids:
            return None  # retired/replaced segments: full miss
        return entry

    def note_delta_hit(self, entry: CacheEntry) -> None:
        entry.delta_hits += 1
        self._count("delta")

    def note_miss(self) -> None:
        if self.enabled:
            self._count("miss")

    def put(self, key, df, *, version: int, uids, state=None) -> None:
        """Publish one cached answer.  `version` (keyword-REQUIRED: the
        serving-discipline contract, GL1701) is the datasource snapshot
        version the answer was computed against; `uids` the snapshot's
        full segment uid set; `state` the merged host partial state when
        the execution path produced one (enables delta-aware reuse)."""
        if not self.enabled:
            return
        if state is not None and _state_nbytes(state) > _STATE_BYTES_MAX:
            log.info(
                "partial state too large to retain (%d B); caching the "
                "frame only", _state_nbytes(state),
            )
            state = None
        self._cache[key] = CacheEntry(
            df.copy(), state, version=version, uids=uids
        )

    def resize(self, entries: int) -> None:
        """`SET result_cache_entries` hook: re-budget and evict down (a
        0 budget releases every entry and disables the cache)."""
        self.entries = max(int(entries), 0)
        self._cache.resize(self.entries)

    def clear(self) -> None:
        self._cache.clear()

    def __len__(self) -> int:
        return len(self._cache)

    def to_dict(self) -> dict:
        with self._lock:
            return {
                "entries": len(self._cache),
                "capacity": self.entries,
                "delta_reuse": self.delta_reuse,
                "hits": self.hits,
                "delta_hits": self.delta_hits,
                "misses": self.misses,
            }
