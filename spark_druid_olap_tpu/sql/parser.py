"""SQL parser + analyzer: SQL text -> logical plan.

Recursive descent over the lexer's tokens.  The grammar covers the OLAP
subset the reference accelerates (SURVEY.md §2/§4 `[U]`: aggregate SELECTs
with filters, time predicates, GROUP BY (+CUBE/ROLLUP/GROUPING SETS), HAVING,
ORDER BY/LIMIT, star joins) plus `EXPLAIN REWRITE <sql>` — the analog of the
reference's `EXPLAIN DRUID REWRITE` parser extension.

The analyzer (bottom of file) splits SELECT items into grouping outputs,
aggregate calls, and post-aggregate expressions (AggRef substitution), then
assembles the logical plan tree the planner consumes.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..plan import expr as E
from ..plan import logical as L
from .lexer import Token, tokenize

AGG_FNS = {"sum", "count", "avg", "min", "max", "approx_count_distinct"}

#: functions that only exist with an OVER clause (ranking / offset family);
#: aggregate functions become window calls when OVER follows them
WINDOW_FNS = {
    "row_number", "rank", "dense_rank", "ntile",
    "lag", "lead", "first_value", "last_value",
    "percent_rank", "cume_dist", "nth_value",
}
#: aggregates legal inside OVER (sketches/quantiles are not)
WINDOW_AGG_FNS = {"sum", "count", "avg", "min", "max"}


class ParseError(Exception):
    pass


@dataclasses.dataclass(frozen=True)
class AggCall(E.Expr):
    """Parser-level aggregate call; the analyzer lifts these out of SELECT
    expressions into Aggregate.agg_exprs and replaces them with AggRefs."""

    fn: str
    arg: Optional[E.Expr]
    distinct: bool = False
    filter: Optional[E.Expr] = None
    args: tuple = ()  # extra literal args (APPROX_QUANTILE's fraction, k)

    def __str__(self):
        # feeds the analyzer's dedup key: every distinguishing field must
        # appear, or two different aggregates collapse into one AggRef
        inner = "*" if self.arg is None else str(self.arg)
        extra = "".join(f", {a}" for a in self.args)
        return f"{self.fn}({'DISTINCT ' if self.distinct else ''}{inner}{extra})"


@dataclasses.dataclass(frozen=True)
class GroupingCall(E.Expr):
    """SQL GROUPING(col): 1 when `col` is rolled away in the current
    grouping set, else 0.  The analyzer desugars it to a bit test over the
    __grouping_id column the grouping-set machinery already emits."""

    col: E.Expr

    def __str__(self):
        return f"grouping({self.col})"


@dataclasses.dataclass(frozen=True)
class WindowCall(E.Expr):
    """Parser-level `fn(...) OVER (...)`; the analyzer lifts these into
    `L.Window` specs and replaces them with hidden-column Col refs.  Field
    layout mirrors `L.WindowExpr` (flat Expr tuples, so the generic
    dataclass walkers — _strip_qualifiers, _contains_agg, columns() —
    traverse the spec without special cases)."""

    fn: str
    arg: Optional[E.Expr]
    args: tuple = ()  # literal extras: NTILE n, LAG/LEAD offset + default
    filter: Optional[E.Expr] = None
    partition: Tuple[E.Expr, ...] = ()
    order_exprs: Tuple[E.Expr, ...] = ()
    order_asc: Tuple[bool, ...] = ()
    frame: Optional[tuple] = None

    def __str__(self):
        inner = "*" if self.arg is None else str(self.arg)
        extra = "".join(f", {a}" for a in self.args)
        pb = " PARTITION BY " + ", ".join(map(str, self.partition)) if self.partition else ""
        ob = (
            " ORDER BY "
            + ", ".join(
                f"{e}{'' if a else ' DESC'}"
                for e, a in zip(self.order_exprs, self.order_asc)
            )
            if self.order_exprs
            else ""
        )
        fr = f" ROWS {self.frame}" if self.frame is not None else ""
        return f"{self.fn}({inner}{extra}) OVER ({pb}{ob}{fr})".strip()


@dataclasses.dataclass
class SelectStmt:
    items: List[Tuple[Optional[str], E.Expr]]  # (alias, expr)
    table: Any  # str | JoinClause | Subquery
    where: Optional[E.Expr]
    group_by: List[E.Expr]
    group_mode: str  # "plain" | "cube" | "rollup" | "sets"
    grouping_sets: List[List[E.Expr]]
    having: Optional[E.Expr]
    order_by: List[Tuple[E.Expr, bool]]
    limit: Optional[int]
    offset: int
    explain: bool = False
    distinct: bool = False


@dataclasses.dataclass
class UnionStmt:
    """Set-operation chain (UNION [ALL] / INTERSECT [ALL] / EXCEPT [ALL]);
    `ops[i]` connects branches[i] and branches[i+1].  Kept flat at parse
    time; `parse_sql` folds it into a logical tree with SQL precedence
    (INTERSECT binds tighter than UNION/EXCEPT, both left-associative).
    Trailing ORDER BY / LIMIT from the last branch apply to the combined
    result (column names come from the first branch)."""

    branches: List[SelectStmt]
    ops: List[str]
    order_by: List[Tuple[E.Expr, bool]]
    limit: Optional[int]
    offset: int
    explain: bool = False


@dataclasses.dataclass
class Subquery:
    """A derived table: FROM (SELECT ...) alias.  The planner cannot push
    nested queries down (the reference fell back to Spark for them too), so
    these execute on the host fallback interpreter — but they parse and
    plan like any other relation."""

    stmt: "SelectStmt"
    alias: str
    aliases: tuple = ()  # inner-visible alias->table items (parse time)


@dataclasses.dataclass
class JoinClause:
    left: Any  # str | JoinClause (Subquery is rejected in join position)
    right: str
    right_alias: Optional[str]
    on: List[Tuple[str, str]]  # (left col, right col) qualified names
    how: str


class Parser:
    def __init__(self, sql: str, views: Optional[Dict[str, str]] = None):
        self.toks = tokenize(sql)
        self.i = 0
        self.views = views or {}  # view name -> defining SELECT text
        self.aliases: Dict[str, str] = {}  # alias -> table
        # alias names registered by the CURRENT select's FROM clause —
        # needed for correlation scoping: an alias that exists in both the
        # inner and an outer scope resolves INNER (SQL: innermost wins),
        # which a dict-diff against the outer scope cannot see when the
        # two registrations are identical (review-confirmed wrong-answer)
        self._scopes: List[set] = []
        self._last_scope: set = set()

    # -- token helpers -------------------------------------------------------

    def peek(self) -> Token:
        return self.toks[self.i]

    def next(self) -> Token:
        t = self.toks[self.i]
        self.i += 1
        return t

    def accept_kw(self, *kws: str) -> Optional[str]:
        t = self.peek()
        if t.kind == "KW" and t.value.lower() in kws:
            self.next()
            return t.value.lower()
        return None

    def expect_kw(self, kw: str):
        if not self.accept_kw(kw):
            raise ParseError(f"expected {kw.upper()} at {self.peek().value!r}")

    def accept_op(self, op: str) -> bool:
        t = self.peek()
        if t.kind == "OP" and t.value == op:
            self.next()
            return True
        return False

    def expect_op(self, op: str):
        if not self.accept_op(op):
            raise ParseError(f"expected {op!r} at {self.peek().value!r}")

    def expect_ident(self) -> str:
        t = self.peek()
        if t.kind == "IDENT":
            self.next()
            return t.value
        if t.kind == "KW":  # permissive: keywords as idents where unambiguous
            self.next()
            return t.value
        raise ParseError(f"expected identifier at {t.value!r}")

    # -- statement -----------------------------------------------------------

    def parse(self):
        explain = False
        if self.accept_kw("explain"):
            self.accept_kw("rewrite")  # EXPLAIN [REWRITE]
            explain = True
        stmt = self.select()
        stmt.explain = explain
        branches = [stmt]
        ops: List[str] = []
        while True:
            kw = self.accept_kw("union", "intersect", "except")
            if kw is None:
                break
            if kw == "union":
                # UNION DISTINCT == plain UNION
                mod = self.accept_kw("all", "distinct")
                ops.append("union_all" if mod == "all" else "union")
            else:
                ops.append(kw + ("_all" if self.accept_kw("all") else ""))
            branches.append(self.select())
        if self.accept_op(";"):
            pass
        if self.peek().kind != "EOF":
            raise ParseError(f"trailing input at {self.peek().value!r}")
        if len(branches) == 1:
            return stmt
        # the trailing ORDER BY / LIMIT the last branch parsed belong to
        # the whole set operation (SQL forbids them before UNION et al.)
        last = branches[-1]
        out = UnionStmt(
            branches=branches,
            ops=ops,
            order_by=last.order_by,
            limit=last.limit,
            offset=last.offset,
            explain=explain,
        )
        last.order_by, last.limit, last.offset = [], None, 0
        for b in branches[:-1]:
            # standard SQL forbids these before UNION; applying them
            # per-branch would silently change row counts
            if b.order_by or b.limit is not None or b.offset:
                raise ParseError(
                    "ORDER BY/LIMIT/OFFSET is only valid after the last "
                    "set-operation branch"
                )
        for b in branches:
            if len(b.items) != len(branches[0].items):
                raise ParseError(
                    "set-operation branches have different column counts"
                )
            if any(
                isinstance(e, E.Col) and e.name == "*" for _, e in b.items
            ):
                raise ParseError("SELECT * in a set operation unsupported")
        return out

    def select(self) -> SelectStmt:
        self._scopes.append(set())
        try:
            return self._select_body()
        finally:
            self._last_scope = self._scopes.pop()

    def _select_body(self) -> SelectStmt:
        self.expect_kw("select")
        distinct = bool(self.accept_kw("distinct"))
        items: List[Tuple[Optional[str], E.Expr]] = []
        while True:
            if self.accept_op("*"):
                items.append((None, E.Col("*")))
            else:
                e = self.expr()
                alias = None
                if self.accept_kw("as"):
                    alias = self.expect_ident()
                elif self.peek().kind == "IDENT":
                    alias = self.expect_ident()
                items.append((alias, e))
            if not self.accept_op(","):
                break
        self.expect_kw("from")
        table = self.table_ref()
        where = self.expr() if self.accept_kw("where") else None
        group_by: List[E.Expr] = []
        group_mode = "plain"
        grouping_sets: List[List[E.Expr]] = []
        if self.accept_kw("group"):
            self.expect_kw("by")
            if self.accept_kw("cube"):
                group_mode = "cube"
                self.expect_op("(")
                group_by = self._expr_list()
                self.expect_op(")")
            elif self.accept_kw("rollup"):
                group_mode = "rollup"
                self.expect_op("(")
                group_by = self._expr_list()
                self.expect_op(")")
            elif self.accept_kw("grouping"):
                self.expect_kw("sets")
                group_mode = "sets"
                self.expect_op("(")
                while True:
                    self.expect_op("(")
                    s = self._expr_list() if not self.accept_op(")") else []
                    if s:
                        self.expect_op(")")
                    grouping_sets.append(s)
                    for e in s:
                        if not any(_expr_eq(e, g) for g in group_by):
                            group_by.append(e)
                    if not self.accept_op(","):
                        break
                self.expect_op(")")
            else:
                group_by = self._expr_list()
        having = self.expr() if self.accept_kw("having") else None
        order_by: List[Tuple[E.Expr, bool]] = []
        if self.accept_kw("order"):
            self.expect_kw("by")
            while True:
                e = self.expr()
                asc = True
                if self.accept_kw("desc"):
                    asc = False
                elif self.accept_kw("asc"):
                    asc = True
                order_by.append((e, asc))
                if not self.accept_op(","):
                    break
        limit = None
        offset = 0
        if self.accept_kw("limit"):
            limit = int(self.next().value)
        if self.accept_kw("offset"):
            offset = int(self.next().value)
        return self._bind_correlation(
            SelectStmt(
                items, table, where, group_by, group_mode, grouping_sets,
                having, order_by, limit, offset, distinct=distinct,
            )
        )

    def _bind_correlation(self, stmt: SelectStmt) -> SelectStmt:
        """Post-parse correlation marking.  SELECT items parse BEFORE the
        FROM clause registers aliases, so a subquery in the select list
        cannot know at its own parse time which qualifiers are outer —
        re-scan every expression-position subquery node now that this
        statement's full alias scope (self.aliases: this FROM plus any
        enclosing scopes mid-parse) is known."""
        import dataclasses as _dc

        visible = dict(self.aliases)

        def refs_of(node) -> tuple:
            inner_vis = dict(node.aliases or ())
            found = set(node.outer_refs or ())
            exprs = [e for _, e in node.stmt.items]
            exprs += node.stmt.group_by
            exprs += [e for e, _ in node.stmt.order_by]
            exprs += [
                x for x in (node.stmt.where, node.stmt.having)
                if x is not None
            ]
            for e in exprs:
                for c in e.columns():
                    if "." in c:
                        q = c.split(".", 1)[0]
                        if q not in inner_vis and q in visible:
                            found.add(c)
            return tuple(sorted(found))

        def _mark(e):
            if isinstance(
                e, (E.InSubquery, E.ExistsSubquery, E.ScalarSubquery)
            ):
                refs = refs_of(e)
                if refs != tuple(e.outer_refs or ()):
                    return _dc.replace(e, outer_refs=refs or None)
            return e

        def fix(e):
            return E.map_expr(e, _mark)

        return _dc.replace(
            stmt,
            items=[(n, fix(e)) for n, e in stmt.items],
            where=fix(stmt.where) if stmt.where is not None else None,
            having=fix(stmt.having) if stmt.having is not None else None,
            group_by=[fix(e) for e in stmt.group_by],
            order_by=[(fix(e), a) for e, a in stmt.order_by],
        )

    def _expr_list(self) -> List[E.Expr]:
        out = [self.expr()]
        while self.accept_op(","):
            out.append(self.expr())
        return out

    def _parse_subselect(self):
        """Parse a nested (SELECT ...) with alias isolation: the inner
        FROM's aliases must not leak into or clobber the outer scope.
        QUALIFIED references to OUTER tables inside the inner statement
        are correlation — collected and returned so the subquery node can
        carry them (the host fallback evaluates correlated subqueries per
        distinct outer binding); unqualified names still resolve inner
        only.  Returns (stmt, inner-visible alias items, outer_refs)."""
        saved = dict(self.aliases)
        inner = self.select()
        after = dict(self.aliases)
        self.aliases = saved
        # the inner statement's OWN aliases (from its FROM clause, via the
        # scope stack): a name registered by BOTH scopes resolves INNER —
        # a dict diff would miss identical registrations (same table, same
        # alias) and misread a self-reference as correlation
        inner_vis = {k: after[k] for k in self._last_scope if k in after}
        outer_refs = set()
        for _, e in list(inner.items) + [
            (None, x) for x in inner.group_by
        ] + [(None, x) for x, _ in inner.order_by] + [
            (None, x)
            for x in (inner.where, inner.having)
            if x is not None
        ]:
            for c in e.columns():
                if "." in c:
                    q = c.split(".", 1)[0]
                    if q not in inner_vis and q in saved:
                        outer_refs.add(c)
        return inner, tuple(sorted(inner_vis.items())), tuple(
            sorted(outer_refs)
        )

    def table_ref(self):
        if self.accept_op("("):
            # derived table: FROM (SELECT ...) [AS] alias — correlation is
            # not valid SQL here (that would be LATERAL)
            inner, inner_vis, outer_refs = self._parse_subselect()
            if outer_refs:
                raise ParseError(
                    "derived tables cannot reference outer aliases "
                    f"({', '.join(outer_refs)}): LATERAL is unsupported"
                )
            self.expect_op(")")
            has_as = self.accept_kw("as")
            if not has_as and self.peek().kind != "IDENT":
                # without this, a missing alias would swallow the next
                # clause keyword (WHERE/ORDER) as the alias
                raise ParseError("derived table requires an alias")
            alias = self.expect_ident()
            self.aliases[alias] = alias
            if self._scopes:
                self._scopes[-1].add(alias)
            if self.peek().kind == "KW" and self.peek().value.lower() in (
                "join", "inner", "left"
            ):
                raise ParseError("JOIN over a derived table unsupported")
            return Subquery(inner, alias, inner_vis)
        name = self.expect_ident()
        alias = None
        t = self.peek()
        if t.kind == "IDENT":
            alias = self.expect_ident()
        if name in self.views:
            # a view reference expands to a derived table of its defining
            # SELECT (re-parsed with the view itself removed, so chains
            # of views work and cycles cannot recurse)
            self.aliases[alias or name] = alias or name
            if self._scopes:
                self._scopes[-1].add(alias or name)
            if self.peek().kind == "KW" and self.peek().value.lower() in (
                "join", "inner", "left"
            ):
                raise ParseError("JOIN over a view unsupported")
            return self._view_subquery(name, alias)
        self.aliases[alias or name] = name
        if self._scopes:
            self._scopes[-1].add(alias or name)
        node: Any = name
        while True:
            how = None
            if self.accept_kw("inner"):
                self.expect_kw("join")
                how = "inner"
            elif self.accept_kw("left"):
                self.expect_kw("join")
                how = "left"
            elif self.accept_kw("join"):
                how = "inner"
            else:
                break
            rname = self.expect_ident()
            if rname in self.views:
                raise ParseError("a view cannot appear in join position")
            ralias = None
            if self.peek().kind == "IDENT":
                ralias = self.expect_ident()
            self.aliases[ralias or rname] = rname
            if self._scopes:
                self._scopes[-1].add(ralias or rname)
            self.expect_kw("on")
            on: List[Tuple[str, str]] = []
            while True:
                l = self._qualified_name()
                self.expect_op("=")
                r = self._qualified_name()
                on.append((l, r))
                if not self.accept_kw("and"):
                    break
            node = JoinClause(node, rname, ralias, on, how)
        return node

    def _view_subquery(self, name: str, alias: Optional[str]) -> Subquery:
        inner_views = {k: v for k, v in self.views.items() if k != name}
        p2 = Parser(self.views[name], views=inner_views)
        stmt = p2.parse()
        return Subquery(stmt, alias or name, tuple(p2.aliases.items()))

    def _qualified_name(self) -> str:
        a = self.expect_ident()
        if self.accept_op("."):
            b = self.expect_ident()
            return f"{a}.{b}"
        return a

    # -- expressions ---------------------------------------------------------

    def expr(self) -> E.Expr:
        return self._or()

    def _or(self) -> E.Expr:
        left = self._and()
        while self.accept_kw("or"):
            left = E.BoolOp("or", (left, self._and()))
        return left

    def _and(self) -> E.Expr:
        left = self._not()
        while self.accept_kw("and"):
            left = E.BoolOp("and", (left, self._not()))
        return left

    def _not(self) -> E.Expr:
        if self.accept_kw("not"):
            return E.BoolOp("not", (self._not(),))
        if self.accept_kw("exists"):
            # EXISTS (SELECT ...): the fallback resolves it to a constant
            # row-count check, or per outer binding when correlated
            self.expect_op("(")
            inner, inner_vis, outer_refs = self._parse_subselect()
            self.expect_op(")")
            return E.ExistsSubquery(
                inner, inner_vis, outer_refs=outer_refs or None
            )
        return self._cmp()

    def _cmp(self) -> E.Expr:
        left = self._add()
        t = self.peek()
        if t.kind == "OP" and t.value in ("=", "==", "!=", "<>", "<", "<=", ">", ">="):
            self.next()
            op = {"=": "==", "<>": "!="}.get(t.value, t.value)
            return E.Comparison(op, left, self._add())
        negated = False
        if self.peek().kind == "KW" and self.peek().value.lower() == "not":
            nxt = self.toks[self.i + 1]
            if nxt.kind == "KW" and nxt.value.lower() in ("in", "like", "between"):
                self.next()
                negated = True
        if self.accept_kw("between"):
            lo = self._add()
            self.expect_kw("and")
            hi = self._add()
            e: E.Expr = E.BoolOp(
                "and",
                (E.Comparison(">=", left, lo), E.Comparison("<=", left, hi)),
            )
            return E.BoolOp("not", (e,)) if negated else e
        if self.accept_kw("in"):
            self.expect_op("(")
            if (
                self.peek().kind == "KW"
                and self.peek().value.lower() == "select"
            ):
                inner, inner_vis, outer_refs = self._parse_subselect()
                self.expect_op(")")
                if len(inner.items) != 1:
                    raise ParseError(
                        "IN subquery must select exactly one column"
                    )
                e: E.Expr = E.InSubquery(
                    left, inner, inner_vis, outer_refs=outer_refs or None
                )
                return E.BoolOp("not", (e,)) if negated else e
            vals = []
            while True:
                v = self._primary()
                if not isinstance(v, E.Literal):
                    raise ParseError("IN list must be literals")
                vals.append(v.value)
                if not self.accept_op(","):
                    break
            self.expect_op(")")
            e = E.InExpr(left, tuple(vals))
            return E.BoolOp("not", (e,)) if negated else e
        if self.accept_kw("like"):
            t = self.next()
            if t.kind != "STRING":
                raise ParseError("LIKE requires a string pattern")
            return E.LikeExpr(left, t.value, negated=negated)
        if self.accept_kw("is"):
            neg = bool(self.accept_kw("not"))
            self.expect_kw("null")
            isnull = E.Comparison("==", left, E.Literal(None))
            return E.BoolOp("not", (isnull,)) if neg else isnull
        return left

    def _add(self) -> E.Expr:
        left = self._mul()
        while True:
            if self.accept_op("+"):
                left = E.BinaryOp("+", left, self._mul())
            elif self.accept_op("-"):
                left = E.BinaryOp("-", left, self._mul())
            else:
                return left

    def _mul(self) -> E.Expr:
        left = self._unary()
        while True:
            if self.accept_op("*"):
                left = E.BinaryOp("*", left, self._unary())
            elif self.accept_op("/"):
                left = E.BinaryOp("/", left, self._unary())
            elif self.accept_op("%"):
                left = E.BinaryOp("%", left, self._unary())
            else:
                return left

    def _unary(self) -> E.Expr:
        if self.accept_op("-"):
            return E.UnaryOp("-", self._unary())
        return self._primary()

    def _primary(self) -> E.Expr:
        t = self.peek()
        if t.kind == "NUMBER":
            self.next()
            v = float(t.value)
            if v.is_integer() and "." not in t.value and "e" not in t.value.lower():
                return E.Literal(int(t.value))
            return E.Literal(v)
        if t.kind == "STRING":
            self.next()
            return E.Literal(t.value)
        if t.kind == "KW":
            kw = t.value.lower()
            if kw in ("date", "timestamp"):
                self.next()
                s = self.next()
                if s.kind != "STRING":
                    raise ParseError(f"{kw.upper()} requires a string literal")
                ms = int(
                    np.datetime64(s.value).astype("datetime64[ms]").astype(np.int64)
                )
                return E.Literal(ms)
            if kw == "cast":
                self.next()
                self.expect_op("(")
                inner = self.expr()
                self.expect_kw("as")
                ty = self.expect_ident().lower()
                self.expect_op(")")
                to = {
                    "double": "double", "float": "double", "real": "double",
                    "bigint": "long", "int": "long", "integer": "long",
                    "long": "long", "boolean": "bool",
                }.get(ty)
                if to is None:
                    raise ParseError(f"CAST to {ty!r} unsupported")
                return E.Cast(inner, to)
            if kw == "extract":
                self.next()
                self.expect_op("(")
                field = self.expect_ident().lower()
                from ..plan.expr import _EXTRACT_FIELDS

                if field not in _EXTRACT_FIELDS:
                    raise ParseError(
                        f"EXTRACT field {field!r}; supported: "
                        f"{sorted(_EXTRACT_FIELDS)}"
                    )
                self.expect_kw("from")
                inner = self.expr()
                self.expect_op(")")
                return E.TimeExtract(field, inner)
            if kw == "case":
                return self._case()
            if kw in ("true", "false"):
                self.next()
                return E.Literal(kw == "true")
            if kw == "null":
                self.next()
                return E.Literal(None)
            if kw == "interval":
                raise ParseError("INTERVAL literals not supported; use ms")
        if t.kind == "IDENT" or t.kind == "KW":
            name = self.expect_ident()
            if self.accept_op("("):
                return self._maybe_over(self._call(name.lower()))
            if self.accept_op("."):
                col = self.expect_ident()
                return E.Col(f"{name}.{col}")
            return E.Col(name)
        if self.accept_op("("):
            if (
                self.peek().kind == "KW"
                and self.peek().value.lower() == "select"
            ):
                # scalar subquery: (SELECT max(v) FROM t ...) — resolved to
                # a literal (or a per-outer-binding column when correlated)
                # by the host fallback executor
                inner, inner_vis, outer_refs = self._parse_subselect()
                self.expect_op(")")
                if len(inner.items) != 1:
                    raise ParseError(
                        "scalar subquery must select exactly one column"
                    )
                return E.ScalarSubquery(
                    inner, inner_vis, outer_refs=outer_refs or None
                )
            e = self.expr()
            self.expect_op(")")
            return e
        raise ParseError(f"unexpected token {t.value!r}")

    def _case(self) -> E.Expr:
        self.expect_kw("case")
        # simple form: CASE operand WHEN value THEN ... (desugars to the
        # searched form with operand == value conditions)
        operand: Optional[E.Expr] = None
        t = self.peek()
        if not (t.kind == "KW" and t.value.lower() in ("when", "else", "end")):
            operand = self.expr()
        whens: List[Tuple[E.Expr, E.Expr]] = []
        otherwise: E.Expr = E.Literal(None)
        while self.accept_kw("when"):
            c = self.expr()
            if operand is not None:
                c = E.Comparison("==", operand, c)
            self.expect_kw("then")
            v = self.expr()
            whens.append((c, v))
        if self.accept_kw("else"):
            otherwise = self.expr()
        self.expect_kw("end")
        out = otherwise
        for c, v in reversed(whens):
            out = E.IfExpr(c, v, out)
        return out

    # -- window clauses ------------------------------------------------------

    def _accept_word(self, *words: str) -> Optional[str]:
        """Contextual (non-reserved) word: OVER/PARTITION/ROWS/... match as
        plain identifiers so they stay usable as column names elsewhere."""
        t = self.peek()
        if t.kind in ("IDENT", "KW") and t.value.lower() in words:
            self.next()
            return t.value.lower()
        return None

    def _expect_word(self, word: str):
        if not self._accept_word(word):
            raise ParseError(
                f"expected {word.upper()} at {self.peek().value!r}"
            )

    def _maybe_over(self, e: E.Expr) -> E.Expr:
        """Attach an OVER clause to the call that just parsed."""
        if not (
            self.peek().kind in ("IDENT", "KW")
            and self.peek().value.lower() == "over"
            and self.toks[self.i + 1].kind == "OP"
            and self.toks[self.i + 1].value == "("
        ):
            if isinstance(e, WindowCall):
                raise ParseError(f"{e.fn.upper()} requires an OVER clause")
            return e
        self.next()  # over
        self.expect_op("(")
        partition, order_exprs, order_asc, frame = self._over_clause()
        if isinstance(e, WindowCall):
            base = e
        elif isinstance(e, AggCall):
            if e.distinct:
                raise ParseError(
                    "DISTINCT aggregates in an OVER clause are unsupported"
                )
            if e.fn not in WINDOW_AGG_FNS:
                raise ParseError(
                    f"{e.fn.upper()} cannot be used as a window function"
                )
            base = WindowCall(e.fn, e.arg, e.args, filter=e.filter)
        else:
            raise ParseError("OVER must follow a function call")
        if base.fn in ("rank", "dense_rank", "ntile", "lag", "lead",
                       "percent_rank", "cume_dist"):
            if not order_exprs:
                raise ParseError(
                    f"{base.fn.upper()} requires ORDER BY in its OVER clause"
                )
            if frame is not None:
                raise ParseError(
                    f"{base.fn.upper()} does not accept a frame clause"
                )
        return dataclasses.replace(
            base,
            partition=tuple(partition),
            order_exprs=tuple(order_exprs),
            order_asc=tuple(order_asc),
            frame=frame,
        )

    def _over_clause(self):
        """Parses the body of OVER ( ... ) up to and including the `)`."""
        partition: List[E.Expr] = []
        if self._accept_word("partition"):
            self.expect_kw("by")
            partition.append(self.expr())
            while self.accept_op(","):
                partition.append(self.expr())
        order_exprs: List[E.Expr] = []
        order_asc: List[bool] = []
        if self.accept_kw("order"):
            self.expect_kw("by")
            while True:
                order_exprs.append(self.expr())
                asc = True
                if self.accept_kw("desc"):
                    asc = False
                else:
                    self.accept_kw("asc")
                order_asc.append(asc)
                if not self.accept_op(","):
                    break
        frame = None
        if self._accept_word("range"):
            raise ParseError("RANGE frames unsupported; use ROWS")
        if self._accept_word("rows"):
            if self.accept_kw("between"):
                lo = self._frame_bound()
                self.expect_kw("and")
                hi = self._frame_bound()
            else:
                lo = self._frame_bound()
                hi = 0
            if lo == "+inf":
                raise ParseError("frame start cannot be UNBOUNDED FOLLOWING")
            if hi == "-inf":
                raise ParseError("frame end cannot be UNBOUNDED PRECEDING")
            lo_v = None if lo == "-inf" else lo
            hi_v = None if hi == "+inf" else hi
            if lo_v is not None and hi_v is not None and lo_v > hi_v:
                raise ParseError("frame start is after frame end")
            if not order_exprs:
                raise ParseError("a ROWS frame requires ORDER BY")
            frame = (lo_v, hi_v)
        self.expect_op(")")
        return partition, order_exprs, order_asc, frame

    def _frame_bound(self):
        """UNBOUNDED PRECEDING|FOLLOWING / CURRENT ROW / N PRECEDING|FOLLOWING
        -> "-inf" / "+inf" / 0 / -N / +N (row offsets relative to current)."""
        if self._accept_word("unbounded"):
            d = self._accept_word("preceding", "following")
            if d is None:
                raise ParseError("expected PRECEDING or FOLLOWING")
            return "-inf" if d == "preceding" else "+inf"
        if self._accept_word("current"):
            self._expect_word("row")
            return 0
        e = self._primary()
        if not isinstance(e, E.Literal) or not isinstance(e.value, int):
            raise ParseError("frame offset must be an integer literal")
        d = self._accept_word("preceding", "following")
        if d is None:
            raise ParseError("expected PRECEDING or FOLLOWING")
        return -e.value if d == "preceding" else e.value

    @staticmethod
    def _fold_neg_literal(d: E.Expr) -> E.Expr:
        """`-3` parses as UnaryOp('-', Literal(3)); literal-argument
        positions (LAG/LEAD defaults, ROUND digits) want the folded form."""
        if (
            isinstance(d, E.UnaryOp)
            and d.op == "-"
            and isinstance(d.operand, E.Literal)
        ):
            return E.Literal(-d.operand.value)
        return d

    def _filter_clause(self) -> Optional[E.Expr]:
        """Optional SQL `FILTER (WHERE <cond>)` after an aggregate call."""
        if not self.accept_kw("filter"):
            return None
        self.expect_op("(")
        self.expect_kw("where")
        cond = self.expr()
        self.expect_op(")")
        return cond

    def _call(self, fn: str) -> E.Expr:
        if fn in (
            "approx_count_distinct_ds_theta",
            "approx_count_distinct_ds_hll",
        ):
            # APPROX_COUNT_DISTINCT_DS_THETA(expr[, k]) /
            # APPROX_COUNT_DISTINCT_DS_HLL(expr[, lgK]) — Druid SQL's
            # DataSketches variants with an explicit size argument
            arg = self.expr()
            extra = ()
            if self.accept_op(","):
                k = self.expr()
                if not isinstance(k, E.Literal) or not isinstance(
                    k.value, int
                ):
                    raise ParseError(f"{fn.upper()} size must be an integer")
                extra = (int(k.value),)
            self.expect_op(")")
            return AggCall(fn, arg, False, self._filter_clause(), extra)
        if fn in ("approx_quantile", "approx_quantile_ds"):
            # APPROX_QUANTILE[_DS](expr, fraction[, k]) — Druid SQL's
            # DataSketches quantile aggregate
            arg = self.expr()
            self.expect_op(",")
            frac = self.expr()
            if not isinstance(frac, E.Literal) or not isinstance(
                frac.value, (int, float)
            ):
                raise ParseError(
                    "APPROX_QUANTILE fraction must be a numeric literal"
                )
            extra = (float(frac.value),)
            if self.accept_op(","):
                k = self.expr()
                if not isinstance(k, E.Literal) or not isinstance(
                    k.value, int
                ):
                    raise ParseError("APPROX_QUANTILE k must be an integer")
                extra = extra + (int(k.value),)
            self.expect_op(")")
            return AggCall(
                "approx_quantile", arg, False, self._filter_clause(), extra
            )
        if fn in AGG_FNS or fn == "count":
            distinct = bool(self.accept_kw("distinct"))
            if self.accept_op("*"):
                arg = None
            elif self.accept_op(")"):
                raise ParseError(f"{fn} requires an argument")
            else:
                arg = self.expr()
            if arg is not None:
                self.expect_op(")")
            else:
                self.expect_op(")")
            return AggCall(fn, arg, distinct, self._filter_clause())
        if fn == "date_trunc":
            gran = self.expr()
            self.expect_op(",")
            arg = self.expr()
            self.expect_op(")")
            if not isinstance(gran, E.Literal):
                raise ParseError("DATE_TRUNC granularity must be a literal")
            return E.TimeBucket(arg, str(gran.value))
        if fn in ("time_floor",):
            arg = self.expr()
            self.expect_op(",")
            gran = self.expr()
            self.expect_op(")")
            return E.TimeBucket(arg, str(gran.value))  # type: ignore[union-attr]
        if fn in ("substr", "substring"):
            arg = self.expr()
            self.expect_op(",")
            start = self.expr()
            length = None
            if self.accept_op(","):
                length = self.expr()
            self.expect_op(")")
            args = (int(start.value),)  # type: ignore[union-attr]
            if length is not None:
                args = args + (int(length.value),)  # type: ignore[union-attr]
            return E.StrFunc("substr", arg, args)
        if fn in ("upper", "lower", "length"):
            arg = self.expr()
            self.expect_op(")")
            return E.StrFunc(fn, arg)
        if fn == "nullif":
            a = self.expr()
            self.expect_op(",")
            b = self.expr()
            self.expect_op(")")
            # NULLIF(a, b) == CASE WHEN a = b THEN NULL ELSE a END
            return E.IfExpr(
                E.Comparison("==", a, b), E.Literal(None), a
            )
        if fn == "concat":
            args = [self.expr()]
            while self.accept_op(","):
                args.append(self.expr())
            self.expect_op(")")
            cols = [a for a in args if not isinstance(a, E.Literal)]
            lits = [a for a in args if isinstance(a, E.Literal)]
            if any(
                not isinstance(a.value, str) for a in lits
            ):
                raise ParseError("CONCAT literal arguments must be strings")
            if not cols:
                return E.Literal("".join(a.value for a in lits))
            if len(cols) != 1:
                raise ParseError(
                    "CONCAT supports one column operand plus string "
                    "literals (the dictionary-rewrite form)"
                )
            i = args.index(cols[0])
            prefix = "".join(a.value for a in args[:i])
            suffix = "".join(a.value for a in args[i + 1:])
            return E.StrFunc("concat", cols[0], (prefix, suffix))
        if fn == "lookup":
            # LOOKUP(expr, 'name'[, 'replaceMissingValueWith'])
            arg = self.expr()
            self.expect_op(",")
            lname = self.expr()
            replace = None
            if self.accept_op(","):
                replace = self.expr()
            self.expect_op(")")
            if not isinstance(lname, E.Literal) or not isinstance(
                lname.value, str
            ):
                raise ParseError("LOOKUP name must be a string literal")
            args = (lname.value,)
            if replace is not None:
                if not isinstance(replace, E.Literal) or not isinstance(
                    replace.value, str
                ):
                    raise ParseError(
                        "LOOKUP replaceMissingValueWith must be a string "
                        "literal"
                    )
                args = args + (replace.value,)
            return E.StrFunc("lookup", arg, args)
        if fn in ("year", "month", "day", "hour", "minute"):
            arg = self.expr()
            self.expect_op(")")
            return E.TimeExtract(fn, arg)
        if fn in ("abs", "floor", "ceil", "sqrt", "exp", "ln"):
            arg = self.expr()
            self.expect_op(")")
            return E.UnaryOp(fn, arg)
        if fn in ("trim", "ltrim", "rtrim"):
            arg = self.expr()
            self.expect_op(")")
            return E.StrFunc(fn, arg)
        if fn == "replace":
            arg = self.expr()
            self.expect_op(",")
            frm = self.expr()
            self.expect_op(",")
            to = self.expr()
            self.expect_op(")")
            if not (
                isinstance(frm, E.Literal)
                and isinstance(frm.value, str)
                and isinstance(to, E.Literal)
                and isinstance(to.value, str)
            ):
                raise ParseError(
                    "REPLACE search/replacement must be string literals"
                )
            return E.StrFunc("replace", arg, (frm.value, to.value))
        if fn == "round":
            arg = self.expr()
            digits = 0
            if self.accept_op(","):
                d = self._fold_neg_literal(self.expr())
                if not isinstance(d, E.Literal) or not isinstance(
                    d.value, int
                ):
                    raise ParseError(
                        "ROUND digits must be an integer literal"
                    )
                digits = d.value
            self.expect_op(")")
            if digits == 0:
                return E.UnaryOp("round", arg)
            # ROUND(x, d) == ROUND(x * 10^d) / 10^d
            scale = E.Literal(float(10.0 ** digits))
            return E.BinaryOp(
                "/", E.UnaryOp("round", E.BinaryOp("*", arg, scale)), scale
            )
        if fn == "mod":
            a = self.expr()
            self.expect_op(",")
            b = self.expr()
            self.expect_op(")")
            return E.BinaryOp("%", a, b)
        if fn in ("power", "pow"):
            a = self.expr()
            self.expect_op(",")
            b = self.expr()
            self.expect_op(")")
            return E.BinaryOp("pow", a, b)
        if fn == "if":
            # if(cond, then, else) — Druid's native expression form AND the
            # spelling str(IfExpr) serializes to, so expression post-aggs /
            # virtual columns containing CASE round-trip through the wire
            cond = self.expr()
            self.expect_op(",")
            then = self.expr()
            self.expect_op(",")
            otherwise = self.expr()
            self.expect_op(")")
            return E.IfExpr(cond, then, otherwise)
        if fn == "coalesce":
            args = self._expr_list()
            self.expect_op(")")
            out = args[-1]
            for a in reversed(args[:-1]):
                out = E.IfExpr(E.Comparison("!=", a, E.Literal(None)), a, out)
            return out
        if fn == "grouping":
            arg = self.expr()
            self.expect_op(")")
            return GroupingCall(arg)
        if fn in WINDOW_FNS:
            # the OVER clause itself attaches in _maybe_over
            if fn in ("row_number", "rank", "dense_rank",
                      "percent_rank", "cume_dist"):
                self.expect_op(")")
                return WindowCall(fn, None)
            if fn == "ntile":
                k = self.expr()
                self.expect_op(")")
                if not isinstance(k, E.Literal) or not isinstance(
                    k.value, int
                ) or k.value < 1:
                    raise ParseError(
                        "NTILE requires a positive integer literal"
                    )
                return WindowCall(fn, None, (k.value,))
            if fn in ("lag", "lead"):
                arg = self.expr()
                args: tuple = ()
                if self.accept_op(","):
                    off = self.expr()
                    if not isinstance(off, E.Literal) or not isinstance(
                        off.value, int
                    ) or off.value < 0:
                        raise ParseError(
                            f"{fn.upper()} offset must be a non-negative "
                            "integer literal"
                        )
                    args = (off.value,)
                    if self.accept_op(","):
                        d = self._fold_neg_literal(self.expr())
                        if not isinstance(d, E.Literal):
                            raise ParseError(
                                f"{fn.upper()} default must be a literal"
                            )
                        args = args + (d.value,)
                self.expect_op(")")
                return WindowCall(fn, arg, args)
            if fn == "nth_value":
                arg = self.expr()
                self.expect_op(",")
                n = self.expr()
                self.expect_op(")")
                if not isinstance(n, E.Literal) or not isinstance(
                    n.value, int
                ) or n.value < 1:
                    raise ParseError(
                        "NTH_VALUE position must be a positive integer "
                        "literal"
                    )
                return WindowCall(fn, arg, (n.value,))
            # first_value / last_value
            arg = self.expr()
            self.expect_op(")")
            return WindowCall(fn, arg)
        raise ParseError(f"unknown function {fn!r}")


# ---------------------------------------------------------------------------
# Analyzer: SelectStmt -> logical plan
# ---------------------------------------------------------------------------


def _expr_eq(a: E.Expr, b: E.Expr) -> bool:
    return a == b


def _find_group(e: E.Expr, group_keys: Sequence[E.Expr]) -> Optional[int]:
    for i, g in enumerate(group_keys):
        if _expr_eq(e, g):
            return i
    return None


def _contains_agg(e: E.Expr) -> bool:
    # NOTE: deliberately descends into WindowCall specs — an AggCall inside
    # an OVER clause (RANK() OVER (ORDER BY SUM(v))) makes the query an
    # aggregate query, while the window function itself does not
    return E.any_node(e, lambda x: isinstance(x, AggCall))


def _contains_grouping(e: E.Expr) -> bool:
    return E.any_node(e, lambda x: isinstance(x, GroupingCall))


def _contains_window(e: E.Expr) -> bool:
    return E.any_node(e, lambda x: isinstance(x, WindowCall))


def _strip_qualifiers(e: E.Expr, aliases: Dict[str, str]) -> E.Expr:
    """table.col -> col (the engine's datasources are flat); alias tables
    resolve through the FROM-clause alias map."""
    if isinstance(e, E.Col) and "." in e.name:
        return E.Col(e.name.split(".", 1)[1])
    if isinstance(e, (E.Literal, E.AggRef)):
        return e
    kw = {}
    for f in dataclasses.fields(e):  # type: ignore[arg-type]
        v = getattr(e, f.name)
        if isinstance(v, E.Expr):
            kw[f.name] = _strip_qualifiers(v, aliases)
        elif isinstance(v, tuple) and v and isinstance(v[0], E.Expr):
            kw[f.name] = tuple(_strip_qualifiers(x, aliases) for x in v)
        else:
            kw[f.name] = v
    return type(e)(**kw)


class Analyzer:
    """SelectStmt -> logical plan (the Catalyst-analyzer analog)."""

    def __init__(self, stmt: SelectStmt, aliases: Dict[str, str]):
        self.stmt = stmt
        self.aliases = aliases
        self.agg_exprs: List[L.AggExpr] = []
        self.agg_by_key: Dict[str, str] = {}  # str(AggCall) -> assigned name
        self.win_exprs: List[L.WindowExpr] = []
        # GROUPING() substitution context: (group keys, k, has grouping
        # sets) — set by the aggregate path so ORDER BY can substitute too
        self._grouping_ctx: tuple = ([], 0, False)
        # (output name, group-key expr) pairs — window specs over an
        # aggregated frame must reference group keys by their OUTPUT names
        # (GROUP BY g with `g AS grp` yields a frame column `grp`, not `g`)
        self._win_groups: List[Tuple[str, E.Expr]] = []

    def to_logical(self) -> L.LogicalPlan:
        stmt = self.stmt
        self._check_window_positions(stmt)
        base = self._from_clause(stmt.table)
        if stmt.where is not None:
            base = L.Filter(_strip_qualifiers(stmt.where, self.aliases), base)

        has_agg = (
            bool(stmt.group_by)
            or any(_contains_agg(e) for _, e in stmt.items)
            or (stmt.having is not None)
        )
        has_window = any(_contains_window(e) for _, e in stmt.items)
        if stmt.distinct and has_window:
            raise ParseError(
                "SELECT DISTINCT with window functions unsupported"
            )
        if stmt.distinct:
            if has_agg:
                # grouped output rows are already distinct per group in the
                # overwhelmingly common case; deduplicating aggregate values
                # across groups is out of scope (the reference fell back to
                # Spark for it too)
                raise ParseError(
                    "SELECT DISTINCT with GROUP BY / aggregates unsupported"
                )
            # SELECT DISTINCT a, b FROM t == SELECT a, b FROM t GROUP BY a, b
            # (the reference's planner saw the same rewrite from Catalyst)
            if any(
                isinstance(e, E.Col) and e.name == "*" for _, e in stmt.items
            ):
                raise ParseError("SELECT DISTINCT * unsupported")
            stmt = dataclasses.replace(
                stmt,
                distinct=False,
                group_by=[e for _, e in stmt.items],
            )
            self.stmt = stmt
            has_agg = True
        if not has_agg:
            if has_window:
                out_exprs = []
                for alias, e in stmt.items:
                    if isinstance(e, E.Col) and e.name == "*":
                        raise ParseError(
                            "SELECT * cannot be mixed with window functions"
                        )
                    es = _strip_qualifiers(e, self.aliases)
                    name = alias or _auto_name(es)
                    out_exprs.append((name, self._lift_windows(es)))
                plan = L.Window(
                    tuple(self.win_exprs), tuple(out_exprs), base
                )
                return self._order_limit(plan, post_agg=False)
            exprs = []
            for alias, e in stmt.items:
                if isinstance(e, E.Col) and e.name == "*":
                    exprs = []  # SELECT * -> project all (planner fills)
                    break
                e = _strip_qualifiers(e, self.aliases)
                exprs.append((alias or _auto_name(e), e))
            plan: L.LogicalPlan = (
                L.Project(tuple(exprs), base) if exprs else base
            )
            plan = self._order_limit(plan, post_agg=False)
            return plan

        # aggregate query
        group_exprs: List[Tuple[str, E.Expr]] = []
        group_keys: List[E.Expr] = []
        alias_of_item: Dict[str, E.Expr] = {}
        for alias, e in stmt.items:
            if alias is not None:
                alias_of_item[alias] = e
        for ge in stmt.group_by:
            ge = self._resolve_group_ref(ge, stmt.items)
            ge_s = _strip_qualifiers(ge, self.aliases)
            name = None
            for alias, ie in stmt.items:
                if _expr_eq(_strip_qualifiers(ie, self.aliases), ge_s):
                    name = alias or _auto_name(ge_s)
                    break
            group_exprs.append((name or _auto_name(ge_s), ge_s))
            group_keys.append(ge_s)

        # SELECT items -> outputs.  Window-containing items skip the
        # Aggregate's post_exprs entirely: their windows (and any
        # aggregates inside or around them) are computed in an L.Window
        # stage ABOVE the Aggregate/Having, referencing the aggregated
        # frame's group/agg columns.  `out_exprs` preserves SELECT order
        # for the Window stage when one is needed.
        post_exprs: List[Tuple[str, E.Expr]] = []
        out_exprs: List[Tuple[str, E.Expr]] = []
        self._win_groups = list(group_exprs)
        has_sets = stmt.group_mode != "plain"
        k_groups = len(group_exprs)
        self._grouping_ctx = (group_keys, k_groups, has_sets)
        for alias, e in stmt.items:
            es0 = _strip_qualifiers(e, self.aliases)
            had_grouping = _contains_grouping(es0)
            es = self._sub_grouping_calls(es0, group_keys, k_groups, has_sets)
            if _contains_window(es):
                name = alias or _auto_name(es0)
                lifted = self._lift_windows(es)
                if _contains_agg(lifted):
                    lifted = self._lift_aggs(lifted, name, _top=False)
                out_exprs.append((name, self._sub_group_refs(lifted)))
                continue
            if _contains_agg(es) or had_grouping:
                # GROUPING()-containing items are post-aggregate
                # expressions over __grouping_id even without an aggregate
                name = alias or _auto_name(es0)
                post = (
                    self._lift_aggs(es, name) if _contains_agg(es) else es
                )
                post_exprs.append((name, post))
                out_exprs.append((name, E.Col(name)))
            else:
                idx = _find_group(es, group_keys)
                if idx is None:
                    raise ParseError(
                        f"SELECT item {e} is neither aggregated nor grouped"
                    )
                name = alias or group_exprs[idx][0]
                post_exprs.append((name, E.Col(group_exprs[idx][0])))
                out_exprs.append((name, E.Col(name)))

        having_expr = None
        if stmt.having is not None:
            hs = _strip_qualifiers(stmt.having, self.aliases)
            hs = self._sub_grouping_calls(hs, group_keys, k_groups, has_sets)
            having_expr = self._lift_aggs(hs, "having")

        grouping_sets: Tuple[Tuple[int, ...], ...] = ()
        k = len(group_exprs)
        if stmt.group_mode == "cube":
            grouping_sets = tuple(
                tuple(i for i in range(k) if (m >> i) & 1)
                for m in range(1 << k)
            )
        elif stmt.group_mode == "rollup":
            grouping_sets = tuple(
                tuple(range(j)) for j in range(k, -1, -1)
            )
        elif stmt.group_mode == "sets":
            sets = []
            for s in stmt.grouping_sets:
                idxs = []
                for e in s:
                    es = _strip_qualifiers(
                        self._resolve_group_ref(e, stmt.items), self.aliases
                    )
                    i = _find_group(es, group_keys)
                    if i is None:
                        raise ParseError(f"grouping set expr {e} not in GROUP BY")
                    idxs.append(i)
                sets.append(tuple(idxs))
            grouping_sets = tuple(sets)

        plan = L.Aggregate(
            group_exprs=tuple(group_exprs),
            agg_exprs=tuple(self.agg_exprs),
            child=base,
            post_exprs=tuple(post_exprs),
            grouping_sets=grouping_sets,
        )
        if having_expr is not None:
            plan = L.Having(having_expr, plan)
        if self.win_exprs:
            # windows see the post-HAVING aggregated frame (SQL evaluation
            # order: ... HAVING -> window functions -> ORDER BY); a spec
            # referencing an ungrouped, unaggregated source column must be
            # an analysis error, not a runtime KeyError
            valid = (
                {n for n, _ in group_exprs}
                | {ae.name for ae in self.agg_exprs}
                | {n for n, _ in post_exprs}
            )
            for w in self.win_exprs:
                for ex in (w.arg, w.filter, *w.partition, *w.order_exprs):
                    if ex is None:
                        continue
                    for cname in ex.columns():
                        if cname not in valid:
                            raise ParseError(
                                f"window reference {cname!r} is neither "
                                "aggregated nor grouped"
                            )
            plan = L.Window(tuple(self.win_exprs), tuple(out_exprs), plan)
        return self._order_limit(plan, post_agg=True)

    # -- helpers -------------------------------------------------------------

    def _from_clause(self, t) -> L.LogicalPlan:
        if isinstance(t, str):
            return L.Scan(t)
        if isinstance(t, Subquery):
            # the derived table's plan becomes the outer query's leaf,
            # wrapped in a SubqueryScan scope boundary: the outer may only
            # reference the subquery's SELECT-list names (the planner's
            # Project-collapsing walk would otherwise resolve renamed-away
            # names against the base table — silent wrong data)
            if isinstance(t.stmt, UnionStmt):
                # a set-operation view expands here: fold its branches
                names = _stmt_out_names(
                    t.stmt.branches[0], dict(t.aliases)
                )
                return L.SubqueryScan(
                    _union_logical(t.stmt, dict(t.aliases)),
                    tuple(names) if names else None,
                    t.alias,
                )
            inner = Analyzer(t.stmt, dict(t.aliases))
            names = _stmt_out_names(t.stmt, self.aliases)  # [] = SELECT *
            return L.SubqueryScan(
                inner.to_logical(),
                tuple(names) if names else None,
                t.alias,
            )
        assert isinstance(t, JoinClause)
        left = self._from_clause(t.left)
        lk, rk = [], []
        for l, r in t.on:
            lk.append(self._resolve_qualified(l))
            rk.append(self._resolve_qualified(r))
        return L.Join(left, L.Scan(t.right), tuple(lk), tuple(rk), t.how)

    def _resolve_qualified(self, name: str) -> str:
        if "." in name:
            tbl, col = name.split(".", 1)
            tbl = self.aliases.get(tbl, tbl)
            return f"{tbl}.{col}"
        return name

    def _resolve_group_ref(self, ge: E.Expr, items) -> E.Expr:
        # positional GROUP BY 1,2 and alias references
        if isinstance(ge, E.Literal) and isinstance(ge.value, int):
            idx = ge.value - 1
            if not (0 <= idx < len(items)):
                raise ParseError(f"GROUP BY position {ge.value} out of range")
            return items[idx][1]
        if isinstance(ge, E.Col):
            for alias, ie in items:
                if alias == ge.name and not _contains_agg(ie):
                    return ie
        return ge

    def _sub_group_refs(self, e: E.Expr) -> E.Expr:
        """Replace subtrees equal to a GROUP BY key with the key's OUTPUT
        column (no-op outside aggregate queries; aggregates were already
        lifted to AggRefs before this runs).  NOT expressible via
        map_expr: the match is whole-subtree equality against the key
        expression, and map_expr's bottom-up order would rewrite the
        children first and break the comparison."""
        if e is None or not self._win_groups:
            return e
        for name, ge in self._win_groups:
            if e == ge:
                return E.Col(name)
        if isinstance(e, (E.Literal, E.Col, E.AggRef)):
            return e
        kw = {}
        for f in dataclasses.fields(e):  # type: ignore[arg-type]
            v = getattr(e, f.name)
            if isinstance(v, E.Expr):
                kw[f.name] = self._sub_group_refs(v)
            elif isinstance(v, tuple) and v and isinstance(v[0], E.Expr):
                kw[f.name] = tuple(self._sub_group_refs(x) for x in v)
            else:
                kw[f.name] = v
        return type(e)(**kw)

    def _sub_grouping_calls(
        self, e: E.Expr, group_keys, k: int, has_sets: bool
    ) -> E.Expr:
        """GROUPING(col) -> bit test over __grouping_id (or literal 0 for
        a plain GROUP BY, where nothing is ever rolled away)."""

        def sub(x):
            if not isinstance(x, GroupingCall):
                return x
            arg = _strip_qualifiers(x.col, self.aliases)
            idx = _find_group(arg, group_keys)
            if idx is None:
                raise ParseError(
                    f"GROUPING({x.col}) argument must be a GROUP BY "
                    "expression"
                )
            if not has_sets:
                return E.Literal(0)
            # bit (k-1-idx) of __grouping_id: floor(gid / 2^(k-1-idx)) % 2
            return E.Cast(
                E.BinaryOp(
                    "%",
                    E.UnaryOp(
                        "floor",
                        E.BinaryOp(
                            "/",
                            E.Col("__grouping_id"),
                            E.Literal(float(1 << (k - 1 - idx))),
                        ),
                    ),
                    E.Literal(2.0),
                ),
                "long",
            )

        return E.map_expr(e, sub)

    def _check_window_positions(self, stmt: SelectStmt):
        """Window functions are legal only in the SELECT list (SQL: they
        evaluate after WHERE/GROUP BY/HAVING; ORDER BY must reference the
        SELECT alias)."""
        if stmt.where is not None and _contains_window(stmt.where):
            raise ParseError("window functions are not allowed in WHERE")
        for ge in stmt.group_by:
            if _contains_window(ge):
                raise ParseError("window functions are not allowed in GROUP BY")
        if stmt.having is not None and _contains_window(stmt.having):
            raise ParseError("window functions are not allowed in HAVING")
        for e, _ in stmt.order_by:
            if _contains_window(e):
                raise ParseError(
                    "window functions in ORDER BY: reference the window's "
                    "SELECT alias instead"
                )

    def _lift_windows(self, e: E.Expr, _in_agg_arg: bool = False) -> E.Expr:
        """Replace WindowCall subtrees with hidden-column Col refs,
        accumulating `win_exprs`.  Aggregates inside a window spec (RANK()
        OVER (ORDER BY SUM(v))) lift to hidden agg names so the spec
        evaluates over the aggregated frame."""
        if isinstance(e, WindowCall):
            if _in_agg_arg:
                raise ParseError(
                    "window functions cannot appear inside aggregate "
                    "arguments"
                )

            def inner(x):
                if x is None:
                    return None
                if _contains_window(x):
                    raise ParseError("nested window functions unsupported")
                if _contains_agg(x):
                    x = self._lift_aggs(x, "win", _top=False)
                return self._sub_group_refs(x)

            spec = L.WindowExpr(
                name=f"__win{len(self.win_exprs)}",
                fn=e.fn,
                arg=inner(e.arg),
                args=e.args,
                filter=inner(e.filter),
                partition=tuple(inner(p) for p in e.partition),
                order_exprs=tuple(inner(o) for o in e.order_exprs),
                order_asc=e.order_asc,
                frame=e.frame,
            )
            for w in self.win_exprs:  # dedup identical window calls
                if dataclasses.replace(w, name=spec.name) == spec:
                    return E.Col(w.name)
            self.win_exprs.append(spec)
            return E.Col(spec.name)
        if isinstance(e, (E.Literal, E.Col, E.AggRef)):
            return e
        in_agg = _in_agg_arg or isinstance(e, AggCall)
        kw = {}
        for f in dataclasses.fields(e):  # type: ignore[arg-type]
            v = getattr(e, f.name)
            if isinstance(v, E.Expr):
                kw[f.name] = self._lift_windows(v, in_agg)
            elif isinstance(v, tuple) and v and isinstance(v[0], E.Expr):
                kw[f.name] = tuple(
                    self._lift_windows(x, in_agg) for x in v
                )
            else:
                kw[f.name] = v
        return type(e)(**kw)

    def _lift_aggs(self, e: E.Expr, hint: str, _top: bool = True) -> E.Expr:
        """Replace AggCall subtrees with AggRefs, accumulating agg_exprs.

        The hint names an aggregate only when it IS the whole item (`_top`);
        aggregates nested inside an expression get hidden `__aggN` names —
        two distinct aggregates under one alias (q14's numerator/denominator
        sums) must not collide on the output name."""
        if isinstance(e, AggCall):
            key = str(e) + (f" FILTER {e.filter}" if e.filter else "")
            if key in self.agg_by_key:
                return E.AggRef(self.agg_by_key[key])
            if _top and _is_simple_output(e, hint):
                name = hint
            else:
                name = f"__agg{len(self.agg_exprs)}"
            fn = e.fn
            if fn == "count" and e.distinct:
                fn = "count_distinct"
            self.agg_exprs.append(
                L.AggExpr(name, fn, e.arg, e.distinct, e.filter, e.args)
            )
            self.agg_by_key[key] = name
            return E.AggRef(name)
        if isinstance(e, (E.Literal, E.Col, E.AggRef)):
            return e
        kw = {}
        for f in dataclasses.fields(e):  # type: ignore[arg-type]
            v = getattr(e, f.name)
            if isinstance(v, E.Expr):
                kw[f.name] = self._lift_aggs(v, hint, _top=False)
            elif isinstance(v, tuple) and v and isinstance(v[0], E.Expr):
                kw[f.name] = tuple(
                    self._lift_aggs(x, hint, _top=False) for x in v
                )
            else:
                kw[f.name] = v
        return type(e)(**kw)

    def _order_limit(self, plan: L.LogicalPlan, post_agg: bool) -> L.LogicalPlan:
        stmt = self.stmt
        if stmt.order_by:
            keys = []
            for e, asc in stmt.order_by:
                es = _strip_qualifiers(e, self.aliases)
                if _contains_grouping(es):
                    if not post_agg:
                        raise ParseError("GROUPING() requires GROUP BY")
                    es = self._sub_grouping_calls(es, *self._grouping_ctx)
                if post_agg and _contains_agg(es):
                    es = self._lift_aggs(es, _auto_name(es))
                    if not isinstance(es, E.AggRef):
                        raise ParseError(
                            "ORDER BY over aggregate expressions must be "
                            "a plain aggregate or a SELECT alias"
                        )
                elif isinstance(es, E.Literal) and isinstance(es.value, int):
                    idx = es.value - 1
                    alias, ie = stmt.items[idx]
                    es = E.Col(alias or _auto_name(
                        _strip_qualifiers(ie, self.aliases)
                    ))
                keys.append(L.SortKey(es, asc))
            plan = L.Sort(tuple(keys), plan)
        if stmt.limit is not None or stmt.offset:
            plan = L.Limit(
                stmt.limit if stmt.limit is not None else (1 << 62),
                plan,
                stmt.offset,
            )
        return plan


def _is_simple_output(e: AggCall, hint: str) -> bool:
    return not hint.startswith("__")


def _auto_name(e: E.Expr) -> str:
    if isinstance(e, E.Col):
        return e.name
    if isinstance(e, AggCall):
        base = e.fn
        if isinstance(e.arg, E.Col):
            return f"{base}_{e.arg.name}"
        return base
    if isinstance(e, E.TimeBucket):
        return "__time_bucket"
    s = "".join(ch if ch.isalnum() else "_" for ch in str(e))[:40]
    return f"expr_{s}" if s else "expr"


def _stmt_out_names(stmt: SelectStmt, aliases) -> List[str]:
    out_names: List[str] = []
    for alias, e in stmt.items:
        if isinstance(e, E.Col) and e.name == "*":
            return []
        es = _strip_qualifiers(e, aliases)
        out_names.append(alias or _auto_name(es))
    return out_names


#: set operations that are associative — consecutive same-op branches
#: flatten into one n-ary Union node (EXCEPT is not associative: it stays
#: strictly binary under the standard left fold)
_ASSOCIATIVE_SETOPS = {"union_all", "union", "intersect", "intersect_all"}


def _fold_setops(plans, ops) -> L.LogicalPlan:
    """Fold a flat set-operation chain into a logical tree with SQL
    precedence: INTERSECT [ALL] binds tighter than UNION/EXCEPT, all
    left-associative.  `A UNION B INTERSECT C` == `A UNION (B INTERSECT C)`."""

    def join(left: L.LogicalPlan, op: str, right: L.LogicalPlan):
        if (
            op in _ASSOCIATIVE_SETOPS
            and isinstance(left, L.Union)
            and left.op == op
        ):
            return L.Union(left.branches + (right,), op=op)
        return L.Union((left, right), op=op)

    # pass 1: bind INTERSECT [ALL] runs
    terms = [plans[0]]
    term_ops = []
    for op, p in zip(ops, plans[1:]):
        if op.startswith("intersect"):
            terms[-1] = join(terms[-1], op, p)
        else:
            term_ops.append(op)
            terms.append(p)
    # pass 2: left fold UNION / EXCEPT
    plan = terms[0]
    for op, p in zip(term_ops, terms[1:]):
        plan = join(plan, op, p)
    return plan


def parse_sql(
    sql: str, views: Optional[Dict[str, str]] = None
) -> Tuple[L.LogicalPlan, bool, List[str]]:
    """Returns (logical plan, explain?, SELECT-order output names).
    `views` maps view names to their defining SELECT text (CREATE VIEW)."""
    p = Parser(sql, views=views)
    stmt = p.parse()
    if isinstance(stmt, UnionStmt):
        plan = _union_logical(stmt, p.aliases)
        return (
            plan,
            stmt.explain,
            _stmt_out_names(stmt.branches[0], p.aliases),
        )
    analyzer = Analyzer(stmt, p.aliases)
    plan = analyzer.to_logical()
    return plan, stmt.explain, _stmt_out_names(stmt, p.aliases)


def _union_logical(stmt: UnionStmt, aliases) -> L.LogicalPlan:
    """UnionStmt -> folded logical tree with trailing ORDER BY / LIMIT."""
    plans = [
        Analyzer(b, dict(aliases)).to_logical() for b in stmt.branches
    ]
    plan = _fold_setops(plans, stmt.ops)
    first = stmt.branches[0]
    if stmt.order_by:
        # mirror Analyzer._order_limit's resolution: ordinals bind to
        # the first branch's SELECT items; aggregates have no grouping
        # context after UNION ALL and are rejected, not crashed on
        keys = []
        for e, asc in stmt.order_by:
            es = _strip_qualifiers(e, aliases)
            if _contains_agg(es) or _contains_window(es):
                raise ParseError(
                    "ORDER BY after a set operation must reference "
                    "output columns, not aggregates or window functions"
                )
            if isinstance(es, E.Literal) and isinstance(es.value, int):
                idx = es.value - 1
                if not 0 <= idx < len(first.items):
                    raise ParseError(
                        f"ORDER BY ordinal {es.value} out of range"
                    )
                alias, ie = first.items[idx]
                es = E.Col(
                    alias or _auto_name(_strip_qualifiers(ie, aliases))
                )
            keys.append(L.SortKey(es, asc))
        plan = L.Sort(tuple(keys), plan)
    if stmt.limit is not None or stmt.offset:
        plan = L.Limit(
            stmt.limit if stmt.limit is not None else (1 << 62),
            plan,
            stmt.offset,
        )
    return plan
