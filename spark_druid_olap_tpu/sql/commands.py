"""Non-SELECT SQL commands (the reference's parser-extension commands).

Reference parity: SURVEY.md §2 "SQL commands / parser extras" row `[U]` and
the L6 surface (§1): the reference's registration DDL is
`CREATE TEMPORARY TABLE t USING org.sparklinedata.druid OPTIONS (...)` plus a
clear-metadata-cache command and session flags via SQLConf.  Here:

    CREATE [TEMPORARY] TABLE t USING <fmt> OPTIONS (path '...', timeColumn
        'ts', dimensions 'a,b', metrics 'x', starSchema '<json>',
        columnMapping '<json>', rowsPerSegment '4194304')
    DROP TABLE [IF EXISTS] t
    SHOW TABLES
    DESCRIBE t | SHOW COLUMNS FROM t
    SET key = value        -- SessionConfig flags (SQLConf analog)
    SET                    -- show all flags
    CLEAR CACHE

Dispatched by `TPUOlapContext.sql` before the SELECT parser runs.
"""

from __future__ import annotations

import dataclasses
import json
import re
from typing import Dict, Optional

_CLEAR = re.compile(r"^\s*clear\s+cache\s*;?\s*$", re.IGNORECASE)
_DROP = re.compile(
    r"^\s*drop\s+table\s+(?P<ife>if\s+exists\s+)?(?P<name>[A-Za-z_]\w*)\s*;?\s*$",
    re.IGNORECASE,
)
_SHOW = re.compile(r"^\s*show\s+tables\s*;?\s*$", re.IGNORECASE)
_DESC = re.compile(
    r"^\s*(describe|desc)\s+(?P<name>[A-Za-z_]\w*)\s*;?\s*$", re.IGNORECASE
)
_SHOWCOLS = re.compile(
    r"^\s*show\s+columns\s+from\s+(?P<name>[A-Za-z_]\w*)\s*;?\s*$",
    re.IGNORECASE,
)
_SET = re.compile(
    r"^\s*set\s+(?P<key>[A-Za-z_]\w*)\s*=\s*(?P<val>.+?)\s*;?\s*$",
    re.IGNORECASE,
)
_SET_SHOW = re.compile(r"^\s*set\s*;?\s*$", re.IGNORECASE)
_CREATE = re.compile(
    r"^\s*create\s+(temporary\s+)?table\s+(?P<name>[A-Za-z_]\w*)\s+"
    r"using\s+(?P<fmt>[\w.]+)\s+options\s*\((?P<opts>.*)\)\s*;?\s*$",
    re.IGNORECASE | re.DOTALL,
)
# one OPTIONS entry: key 'value' or key "value"
_CTAS = re.compile(
    r"^\s*create\s+(temporary\s+)?table\s+(?P<name>[A-Za-z_]\w*)\s+as\s+"
    r"(?P<sel>select\b.*)$",
    re.IGNORECASE | re.DOTALL,
)
_CREATE_VIEW = re.compile(
    r"^\s*create\s+(?:or\s+replace\s+)?(?:temporary\s+)?view\s+"
    r"(?P<name>[A-Za-z_]\w*)\s+as\s+(?P<sel>select\b.*)$",
    re.IGNORECASE | re.DOTALL,
)
_DROP_VIEW = re.compile(
    r"^\s*drop\s+view\s+(?P<ife>if\s+exists\s+)?(?P<name>[A-Za-z_]\w*)"
    r"\s*;?\s*$",
    re.IGNORECASE,
)
_OPT_ENTRY = re.compile(
    r"^\s*([A-Za-z_]\w*)\s+(?:'((?:[^']|'')*)'|\"([^\"]*)\")\s*$"
)


def _split_options(text: str):
    """Split an OPTIONS(...) body on commas outside quotes; every chunk must
    match `key 'value'` — malformed entries are rejected, never dropped."""
    chunks, buf, q = [], [], None
    for ch in text:
        if q:
            buf.append(ch)
            if ch == q:
                q = None
        elif ch in ("'", '"'):
            q = ch
            buf.append(ch)
        elif ch == ",":
            chunks.append("".join(buf))
            buf = []
        else:
            buf.append(ch)
    if buf and "".join(buf).strip():
        chunks.append("".join(buf))
    out = {}
    for c in chunks:
        m = _OPT_ENTRY.match(c)
        if not m:
            raise ValueError(
                f"malformed OPTIONS entry {c.strip()!r}: expected key 'value'"
            )
        k, a, b = m.group(1), m.group(2), m.group(3)
        out[k] = (a if a is not None else b).replace("''", "'")
    return out


@dataclasses.dataclass(frozen=True)
class Command:
    kind: str
    table: Optional[str] = None
    if_exists: bool = False
    key: Optional[str] = None
    value: Optional[str] = None
    options: Optional[Dict[str, str]] = None
    fmt: Optional[str] = None


def parse_command(sql: str) -> Optional[Command]:
    if _CLEAR.match(sql):
        return Command("clear_cache")
    m = _DROP.match(sql)
    if m:
        return Command(
            "drop_table", table=m.group("name"), if_exists=bool(m.group("ife"))
        )
    if _SHOW.match(sql):
        return Command("show_tables")
    m = _DESC.match(sql) or _SHOWCOLS.match(sql)
    if m:
        return Command("describe", table=m.group("name"))
    if _SET_SHOW.match(sql):
        return Command("set_show")
    m = _SET.match(sql)
    if m:
        return Command("set", key=m.group("key"), value=m.group("val"))
    m = _CREATE.match(sql)
    if m:
        opts = _split_options(m.group("opts"))
        return Command(
            "create_table",
            table=m.group("name"),
            options=opts,
            fmt=m.group("fmt").lower(),
        )
    m = _CTAS.match(sql)
    if m:
        return Command("ctas", table=m.group("name"), value=m.group("sel"))
    m = _CREATE_VIEW.match(sql)
    if m:
        return Command(
            "create_view", table=m.group("name"), value=m.group("sel")
        )
    m = _DROP_VIEW.match(sql)
    if m:
        return Command(
            "drop_view", table=m.group("name"), if_exists=bool(m.group("ife"))
        )
    return None


def _coerce_flag(cfg, key: str, raw: str):
    fields = {f.name: f for f in dataclasses.fields(cfg)}
    if key not in fields:
        raise KeyError(
            f"unknown session flag {key!r}; flags: {sorted(fields)}"
        )
    raw = raw.strip().strip("'\"")
    # coerce by the declared field type, not the current value: Optional
    # fields default to None, and `isinstance(None, int)` would fall through
    # to storing a raw string
    ann = str(fields[key].type)
    if raw.lower() in ("none", "null"):
        if "Optional" not in ann and "None" not in ann:
            raise ValueError(
                f"session flag {key!r} ({ann}) does not accept none"
            )
        return None
    if "bool" in ann:
        return raw.lower() in ("1", "true", "yes", "on")
    if "int" in ann:
        return int(raw)
    if "float" in ann:
        return float(raw)
    return raw


def run_command(ctx, cmd: Command):
    import pandas as pd

    if cmd.kind == "clear_cache":
        ctx.clear_cache()
        return pd.DataFrame({"status": ["cache cleared"]})
    if cmd.kind == "drop_table":
        if ctx.catalog.get(cmd.table) is None and not cmd.if_exists:
            raise KeyError(f"table {cmd.table!r} does not exist")
        ctx.drop_table(cmd.table)
        return pd.DataFrame({"status": [f"dropped {cmd.table}"]})
    if cmd.kind == "show_tables":
        tables = sorted(ctx.catalog.tables())
        views = sorted(ctx.views)
        return pd.DataFrame(
            {
                "table": tables + views,
                "kind": ["table"] * len(tables) + ["view"] * len(views),
            }
        )
    if cmd.kind == "describe":
        ds = ctx.catalog.get(cmd.table)
        if ds is None and cmd.table in ctx.views:
            return pd.DataFrame(
                {"view": [cmd.table], "definition": [ctx.views[cmd.table]]}
            )
        if ds is None:
            raise KeyError(f"table {cmd.table!r} does not exist")
        return pd.DataFrame(
            {
                "column": [c.name for c in ds.columns],
                "kind": [c.kind for c in ds.columns],
                "dtype": [c.dtype for c in ds.columns],
                "cardinality": [c.cardinality for c in ds.columns],
            }
        )
    if cmd.kind == "set_show":
        items = sorted(dataclasses.asdict(ctx.config).items())
        return pd.DataFrame(
            {"key": [k for k, _ in items], "value": [str(v) for _, v in items]}
        )
    if cmd.kind == "set":
        val = _coerce_flag(ctx.config, cmd.key, cmd.value)
        setattr(ctx.config, cmd.key, val)
        if cmd.key == "result_cache_entries":
            # the cache object was sized at construction; resize live
            # (evicts down, releasing held results when shrinking/disabling)
            ctx._result_cache.resize(int(val))
        return pd.DataFrame({"status": [f"set {cmd.key}={val}"]})
    if cmd.kind == "create_table":
        if cmd.fmt not in ("csv", "parquet", "tpu_olap"):
            raise ValueError(
                f"CREATE TABLE USING {cmd.fmt!r}: supported providers are "
                "'csv', 'parquet', 'tpu_olap'"
            )
        opts = dict(cmd.options or {})
        path = opts.pop("path", None)
        if path is None:
            raise ValueError("CREATE TABLE ... OPTIONS requires path '...'")
        if cmd.fmt in ("csv", "parquet") and not path.lower().endswith(
            "." + cmd.fmt
        ):
            raise ValueError(
                f"USING {cmd.fmt} but path {path!r} has a different "
                "extension (use USING tpu_olap to ingest by extension)"
            )
        import os

        if cmd.fmt == "tpu_olap" and os.path.isdir(path):
            # a saved-datasource directory (catalog/persist.py): restore
            # encoded segments directly, no re-ingest
            if opts:
                raise ValueError(
                    "saved-datasource load takes no options besides path; "
                    f"got {sorted(opts)}"
                )
            ds = ctx.load_table(path, name=cmd.table)
            return pd.DataFrame(
                {"status": [f"loaded {cmd.table} ({ds.num_rows} rows)"]}
            )
        kwargs = {}
        if "timeColumn" in opts:
            kwargs["time_column"] = opts.pop("timeColumn")
        if "dimensions" in opts:
            kwargs["dimensions"] = [
                s.strip() for s in opts.pop("dimensions").split(",") if s.strip()
            ]
        if "metrics" in opts:
            kwargs["metrics"] = [
                s.strip() for s in opts.pop("metrics").split(",") if s.strip()
            ]
        if "starSchema" in opts:
            kwargs["star_schema"] = json.loads(opts.pop("starSchema"))
        if "columnMapping" in opts:
            kwargs["column_mapping"] = json.loads(opts.pop("columnMapping"))
        if "rowsPerSegment" in opts:
            kwargs["rows_per_segment"] = int(opts.pop("rowsPerSegment"))
        if "sortBy" in opts:
            # secondary partitioning: rows sorted by these columns before
            # segmenting, so zone maps prune filtered segments
            kwargs["sort_by"] = [
                s.strip() for s in opts.pop("sortBy").split(",") if s.strip()
            ]
        if opts:
            raise ValueError(f"unknown CREATE TABLE options: {sorted(opts)}")
        ds = ctx.register_table(cmd.table, path, **kwargs)
        return pd.DataFrame(
            {"status": [f"created {cmd.table} ({ds.num_rows} rows)"]}
        )
    if cmd.kind == "ctas":
        # CREATE TABLE name AS SELECT ...: materialize the result as a new
        # datasource (the local analog of a Druid ingestion rollup);
        # dimensions/metrics are inferred from the result dtypes
        if ctx.catalog.get(cmd.table) is not None:
            raise ValueError(f"table {cmd.table!r} already exists")
        if cmd.table in ctx.views:
            raise ValueError(
                f"a view named {cmd.table!r} exists; it would shadow the "
                "new table (DROP VIEW first)"
            )
        df = ctx.sql(cmd.value)
        ds = ctx.register_table(cmd.table, df)
        return pd.DataFrame(
            {"status": [f"created {cmd.table} ({ds.num_rows} rows)"]}
        )
    if cmd.kind == "create_view":
        # the definition is PARSE-validated now (a syntactically broken
        # view fails at CREATE; name/type resolution happens per query,
        # so a view may legitimately precede its tables)
        if ctx.catalog.get(cmd.table) is not None:
            raise ValueError(
                f"a table named {cmd.table!r} exists; the view would "
                "shadow it (queries would silently read the view while "
                "DESCRIBE/DROP TABLE address the table)"
            )
        from .parser import parse_sql

        views = dict(ctx.views)
        views.pop(cmd.table, None)
        parse_sql(cmd.value, views=views)
        ctx.views[cmd.table] = cmd.value.strip()
        return pd.DataFrame({"status": [f"created view {cmd.table}"]})
    if cmd.kind == "drop_view":
        if cmd.table not in ctx.views:
            if cmd.if_exists:
                return pd.DataFrame(
                    {"status": [f"view {cmd.table} did not exist"]}
                )
            raise KeyError(f"view {cmd.table!r} does not exist")
        del ctx.views[cmd.table]
        return pd.DataFrame({"status": [f"dropped view {cmd.table}"]})
    raise ValueError(cmd.kind)
