"""Non-SELECT SQL commands (the reference's parser-extension commands).

Reference parity: SURVEY.md §2 "SQL commands / parser extras" row `[U]` —
beyond `EXPLAIN DRUID REWRITE` the reference registers a clear-metadata-cache
command and small DDL helpers.  Here: `CLEAR CACHE`, `DROP TABLE [IF EXISTS]
t`, and `SHOW TABLES`, dispatched by `TPUOlapContext.sql` before the SELECT
parser runs.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Optional

_CLEAR = re.compile(r"^\s*clear\s+cache\s*;?\s*$", re.IGNORECASE)
_DROP = re.compile(
    r"^\s*drop\s+table\s+(?P<ife>if\s+exists\s+)?(?P<name>[A-Za-z_]\w*)\s*;?\s*$",
    re.IGNORECASE,
)
_SHOW = re.compile(r"^\s*show\s+tables\s*;?\s*$", re.IGNORECASE)


@dataclasses.dataclass(frozen=True)
class Command:
    kind: str  # "clear_cache" | "drop_table" | "show_tables"
    table: Optional[str] = None
    if_exists: bool = False


def parse_command(sql: str) -> Optional[Command]:
    if _CLEAR.match(sql):
        return Command("clear_cache")
    m = _DROP.match(sql)
    if m:
        return Command(
            "drop_table", table=m.group("name"), if_exists=bool(m.group("ife"))
        )
    if _SHOW.match(sql):
        return Command("show_tables")
    return None


def run_command(ctx, cmd: Command):
    import pandas as pd

    if cmd.kind == "clear_cache":
        ctx.clear_cache()
        return pd.DataFrame({"status": ["cache cleared"]})
    if cmd.kind == "drop_table":
        if ctx.catalog.get(cmd.table) is None and not cmd.if_exists:
            raise KeyError(f"table {cmd.table!r} does not exist")
        ctx.drop_table(cmd.table)
        return pd.DataFrame({"status": [f"dropped {cmd.table}"]})
    if cmd.kind == "show_tables":
        return pd.DataFrame({"table": sorted(ctx.catalog.tables())})
    raise ValueError(cmd.kind)
