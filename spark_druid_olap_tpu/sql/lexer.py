"""SQL lexer.

Reference parity: the reference extends Spark's SQL parser only for extra
commands (`EXPLAIN DRUID REWRITE`, clear-cache — SURVEY.md §2 SQL-commands row
`[U]`) and otherwise rides Catalyst's parser.  Standalone, we need our own:
a compact hand-rolled lexer + recursive-descent parser covering the OLAP
subset the reference accelerates (aggregate SELECTs over star schemas).
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional


@dataclasses.dataclass(frozen=True)
class Token:
    kind: str  # IDENT | NUMBER | STRING | OP | KW | EOF
    value: str
    pos: int


KEYWORDS = {
    "select", "from", "where", "group", "by", "having", "order", "limit",
    "offset", "as", "and", "or", "not", "in", "like", "between", "is",
    "null", "asc", "desc", "distinct", "join", "inner", "left", "on",
    "cube", "rollup", "grouping", "sets", "date", "timestamp", "interval",
    "case", "when", "then", "else", "end", "cast", "extract", "filter",
    "explain", "rewrite", "union", "all", "true", "false", "exists",
    "intersect", "except",
}

_TWO_CHAR = {"<=", ">=", "<>", "!=", "=="}


class LexError(Exception):
    pass


def tokenize(sql: str) -> List[Token]:
    out: List[Token] = []
    i, n = 0, len(sql)
    while i < n:
        c = sql[i]
        if c.isspace():
            i += 1
            continue
        if c == "-" and i + 1 < n and sql[i + 1] == "-":  # comment
            while i < n and sql[i] != "\n":
                i += 1
            continue
        if c.isalpha() or c == "_":
            j = i
            while j < n and (sql[j].isalnum() or sql[j] == "_"):
                j += 1
            word = sql[i:j]
            kind = "KW" if word.lower() in KEYWORDS else "IDENT"
            out.append(Token(kind, word, i))
            i = j
            continue
        if c.isdigit() or (c == "." and i + 1 < n and sql[i + 1].isdigit()):
            j = i
            seen_dot = False
            while j < n and (sql[j].isdigit() or (sql[j] == "." and not seen_dot)):
                if sql[j] == ".":
                    seen_dot = True
                j += 1
            if j < n and sql[j] in "eE":
                j += 1
                if j < n and sql[j] in "+-":
                    j += 1
                while j < n and sql[j].isdigit():
                    j += 1
            out.append(Token("NUMBER", sql[i:j], i))
            i = j
            continue
        if c == "'":
            j = i + 1
            buf = []
            while j < n:
                if sql[j] == "'" and j + 1 < n and sql[j + 1] == "'":
                    buf.append("'")
                    j += 2
                elif sql[j] == "'":
                    break
                else:
                    buf.append(sql[j])
                    j += 1
            if j >= n:
                raise LexError(f"unterminated string at {i}")
            out.append(Token("STRING", "".join(buf), i))
            i = j + 1
            continue
        if c == '"':
            j = sql.find('"', i + 1)
            if j < 0:
                raise LexError(f"unterminated quoted identifier at {i}")
            out.append(Token("IDENT", sql[i + 1 : j], i))
            i = j + 1
            continue
        two = sql[i : i + 2]
        if two in _TWO_CHAR:
            out.append(Token("OP", two, i))
            i += 2
            continue
        if c in "(),.*+-/%<>=;":
            out.append(Token("OP", c, i))
            i += 1
            continue
        raise LexError(f"unexpected character {c!r} at {i}")
    out.append(Token("EOF", "", n))
    return out
