"""L7 serving surface: HTTP endpoints for BI tools and Druid clients.

Reference parity: the reference ships a patched Spark ThriftServer
(`SparklineDataThriftServer`, SURVEY.md §1 L7 / §2 ThriftServer row `[U]`) so
BI tools reach accelerated tables over JDBC.  JDBC/Thrift is JVM machinery
with no place in a TPU-native Python runtime; the equivalent surface here is
HTTP — the SAME protocol Druid's own broker speaks, so existing Druid
clients/dashboards can point at this server:

    POST /druid/v2            native Druid query JSON -> Druid-shaped results
    POST /druid/v2/sql        {"query": "SELECT ..."} -> array of row objects
    POST /druid/v2/ingest/{datasource}    streamed row append (realtime
                                          ingest; rows queryable immediately)
    GET  /druid/v2/datasources            -> ["lineorder", ...]
    GET  /druid/v2/datasources/{name}     -> {"dimensions": .., "metrics": ..}
    GET  /druid/v2/trace/{query_id}       -> span tree of a recent query
    GET  /status, /status/health          -> liveness + metrics of last query
    GET  /status/metrics                  -> Prometheus text exposition

Every query response carries an `X-Druid-Query-Id` header (the client's
`context.queryId` when set, generated otherwise — Druid parity); the id
keys the query's span tree in the trace ring buffer (obs/).

Native queries bypass the SQL planner (they ARE the planner's output
language) and run straight on the engine; SQL goes through the full rewrite
stack.  Stdlib-only (ThreadingHTTPServer); one process serves one
TPUOlapContext.

    from spark_druid_olap_tpu.server import OlapServer
    OlapServer(ctx, port=8082).serve_forever()      # or .start() for a thread
"""

from __future__ import annotations

import dataclasses
import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Optional

import numpy as np

from .models import query as Q
from .models.filters import _ms_to_iso
from .models.wire import WireError, query_from_druid
from .obs import (
    SPAN_ADMISSION,
    SPAN_LANE,
    default_tracer,
    get_registry,
    new_query_id,
    span,
)
from .resilience import (
    CircuitOpenError,
    DeadlineExceeded,
    classify_error,
    current_partial,
    deadline_scope,
    fire,
    partial_scope,
)
from .utils.log import get_logger

log = get_logger("server")


def _route_label(path: str) -> str:
    """Coarse route label for the http-requests counter: bounded label
    cardinality (per-datasource / per-query-id suffixes collapse)."""
    for prefix in (
        "/druid/v2/trace",
        "/druid/v2/datasources",
        "/druid/v2/sql",
        "/druid/v2/ingest",
        "/druid/v2",
        "/status/metrics",
        "/status/health",
        "/status/profile",
        "/status",
    ):
        if path == prefix or path.startswith(prefix + "/"):
            return prefix
    return "other"


def _jsonable(v: Any):
    import datetime

    import pandas as pd

    if isinstance(v, (np.integer,)):
        return int(v)
    if isinstance(v, (np.floating,)):
        f = float(v)
        return None if np.isnan(f) else f
    if isinstance(v, np.datetime64):
        return _ms_to_iso(int(v.astype("datetime64[ms]").astype(np.int64)))
    if isinstance(v, (pd.Timestamp, datetime.datetime)):
        # Druid wire format is ISO-8601 with the Z designator, not
        # str(Timestamp)'s "YYYY-MM-DD HH:MM:SS"
        return _ms_to_iso(
            int(np.datetime64(v.replace(tzinfo=None), "ms").astype(np.int64))
        )
    if isinstance(v, np.bool_):
        return bool(v)
    if isinstance(v, float) and np.isnan(v):
        return None
    if v is None or isinstance(v, (str, int, float, bool)):
        return v
    if isinstance(v, dict):
        return {k: _jsonable(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_jsonable(x) for x in v]
    return str(v)


def _rows(df) -> list:
    return [
        {k: _jsonable(v) for k, v in rec.items()}
        for rec in df.to_dict(orient="records")
    ]


def _result_timestamp(q) -> str:
    ivs = getattr(q, "intervals", ())
    return _ms_to_iso(ivs[0][0] if ivs else 0)


def druid_result_shape(q: Q.QuerySpec, df) -> Any:
    """Results in the shape Druid's broker returns for each query type."""
    if isinstance(q, Q.GroupByQuery):
        ts = _result_timestamp(q)
        out = []
        for rec in _rows(df):
            t = rec.pop("timestamp", ts)
            out.append({"version": "v1", "timestamp": t, "event": rec})
        return out
    if isinstance(q, Q.TimeseriesQuery):
        # wire shape always says "timestamp" whatever the SQL alias was
        return [
            {
                "timestamp": rec.pop(q.output_name, _result_timestamp(q)),
                "result": rec,
            }
            for rec in _rows(df)
        ]
    if isinstance(q, Q.TopNQuery):
        return [{"timestamp": _result_timestamp(q), "result": _rows(df)}]
    if isinstance(q, Q.ScanQuery):
        if q.result_format == "compactedList":
            # Druid compactedList: events are POSITIONAL value arrays
            # aligned with "columns", not keyed objects
            events = [
                [_jsonable(v) for v in row]
                for row in df.itertuples(index=False)
            ]
        else:
            events = _rows(df)
        return [
            {
                "segmentId": q.datasource,
                "columns": list(df.columns),
                "events": events,
            }
        ]
    if isinstance(q, Q.SearchQuery):
        return [{"timestamp": _result_timestamp(q), "result": _rows(df)}]
    if isinstance(q, Q.TimeBoundaryQuery):
        if df.empty:
            return []
        rec = _rows(df)[0]
        ts = rec.get("minTime", rec.get("maxTime"))
        return [{"timestamp": ts, "result": rec}]
    if isinstance(q, Q.DataSourceMetadataQuery):
        if df.empty:
            return []
        rec = _rows(df)[0]
        return [{"timestamp": rec["maxIngestedEventTime"], "result": rec}]
    if isinstance(q, Q.SegmentMetadataQuery):
        return _rows(df)
    return _rows(df)


class _Handler(BaseHTTPRequestHandler):
    # chunked transfer-encoding (the progressive streaming path) is only
    # defined for HTTP/1.1 — the stdlib default of HTTP/1.0 would make
    # spec-compliant clients read the hex chunk-size lines as body bytes.
    # Safe to enable: every buffered response carries Content-Length
    # (_begin_response) and every chunked one ends with the terminal
    # 0-chunk, so keep-alive connections can never hang.
    protocol_version = "HTTP/1.1"
    ctx = None  # set by OlapServer
    server_version = "sdol-tpu/0.2"
    _query_id: Optional[str] = None  # per-request; set by do_POST
    _req_t0: Optional[float] = None
    # trace-before-response contract (see do_POST): while a query trace
    # is open, buffered responses are captured here and written only
    # after the trace publishes to the ring
    _defer_buffered = False
    _buffered_response: Optional[tuple] = None

    # -- plumbing ------------------------------------------------------------

    def log_message(self, fmt, *args):
        # library etiquette: no stderr spray; stdlib-internal messages
        # (malformed request lines etc.) surface at DEBUG instead of the
        # old silent pass (ISSUE 4 satellite)
        log.debug("http %s", (fmt % args) if args else fmt)

    def log_request(self, code="-", size="-"):
        """Structured access log at DEBUG: method, path, status, query_id,
        duration — the queryId-tagged request log Druid keeps (SURVEY.md
        §5), replacing the silenced default."""
        import time as _time

        dur_ms = (
            (_time.perf_counter() - self._req_t0) * 1e3
            if self._req_t0 is not None
            else -1.0
        )
        log.debug(
            "access method=%s path=%s status=%s query_id=%s "
            "duration_ms=%.2f",
            self.command, self.path, code, self._query_id or "-", dur_ms,
        )

    # -- response writer ----------------------------------------------------
    # ONE writer serves both the buffered and the chunked (progressive)
    # paths (ISSUE 7 ride-along): status+headers — including the
    # X-Druid-Query-Id echo — are emitted by `_begin_response` for BOTH,
    # and the http-requests counter fires exactly once per response via
    # `_finish_response`, so streamed responses can never drift from the
    # buffered contract.

    def _begin_response(
        self,
        code: int,
        content_type: str,
        headers: Optional[dict] = None,
        length: Optional[int] = None,
    ):
        """Status line + headers.  `length=None` switches the body to
        chunked transfer-encoding (`_write_chunk`/`_finish_response`);
        otherwise the caller writes exactly `length` bytes."""
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        if length is not None:
            self.send_header("Content-Length", str(length))
        else:
            self.send_header("Transfer-Encoding", "chunked")
        if self._query_id:
            # Druid parity: every query response (success OR error, buffered
            # OR streamed) echoes the query's id so clients can correlate
            # logs and traces
            self.send_header("X-Druid-Query-Id", self._query_id)
        for k, v in (headers or {}).items():
            self.send_header(k, str(v))
        self.end_headers()

    def _write_chunk(self, data: bytes):
        self.wfile.write(b"%x\r\n" % len(data))
        self.wfile.write(data)
        self.wfile.write(b"\r\n")
        self.wfile.flush()

    def _finish_response(self, code: int, chunked: bool = False):
        if chunked:
            self.wfile.write(b"0\r\n\r\n")
            self.wfile.flush()
        get_registry().counter(
            "sdol_http_requests_total",
            "HTTP responses by method/route/status",
            labels=("method", "route", "code"),
        ).labels(
            method=self.command or "-",
            route=_route_label(self.path.split("?")[0].rstrip("/")),
            code=str(code),
        ).inc()

    def _send(self, code: int, payload: Any, headers: Optional[dict] = None):
        body = json.dumps(payload, default=_jsonable).encode()
        self._send_bytes(code, body, "application/json", headers)

    def _send_bytes(
        self,
        code: int,
        body: bytes,
        content_type: str,
        headers: Optional[dict] = None,
    ):
        if self._defer_buffered:
            # a query trace is open: capture the response; do_POST writes
            # it after the trace publishes so /druid/v2/trace/{id} can
            # never 404 on a query whose response was already read
            self._buffered_response = (code, body, content_type, headers)
            return
        self._begin_response(code, content_type, headers, length=len(body))
        self.wfile.write(body)
        self._finish_response(code)

    def _error(
        self,
        code: int,
        msg: str,
        error_class: str = "QueryInterruptedException",
        headers: Optional[dict] = None,
    ):
        # Druid's structured error object: `error` stays the readable
        # message (clients and older tests read it), `errorMessage` /
        # `errorClass` carry the structure Druid clients dispatch on
        self._send(
            code,
            {"error": msg, "errorMessage": msg, "errorClass": error_class},
            headers=headers,
        )

    def _body(self) -> Optional[dict]:
        try:
            n = int(self.headers.get("Content-Length", 0))
            body = json.loads(self.rfile.read(n) or b"{}")
        except (ValueError, json.JSONDecodeError):
            return None
        # valid JSON that isn't an object (`[1,2]`, `"x"`) is equally a
        # client error, not a 500 from a surprised .get()
        return body if isinstance(body, dict) else None

    # -- routes --------------------------------------------------------------

    def _resilience(self):
        return getattr(self.ctx, "resilience", None)

    def _tracer(self):
        return getattr(self.ctx, "tracer", None) or default_tracer()

    def do_GET(self):
        import time as _time

        # keep-alive: clear the previous request's query id (GETs have
        # none) so health/metrics/trace responses never echo a stale
        # X-Druid-Query-Id from an earlier POST on this connection
        self._query_id = None
        self._req_t0 = _time.perf_counter()
        path = self.path.split("?")[0].rstrip("/")
        if path in ("/status/health", ""):
            res = self._resilience()
            if res is None:
                return self._send(200, True)
            # breaker state + slots in use: a load balancer (or the
            # concurrent-serving test) reads degradation from here
            doc = res.health()
            storage = getattr(self.ctx, "storage", None)
            # durable-tier state (ISSUE 13): WAL sequence, last snapshot
            # version, replay-in-progress, dirty-delta counts — what an
            # operator needs to answer "what would a restart lose" (zero)
            # and "is this node still replaying"
            doc["storage"] = (
                storage.state() if storage is not None
                else {"enabled": False}
            )
            # cluster tier (ISSUE 16): per-historical liveness/breaker
            # state, the assignment epoch, and the replication deficit.
            # Served through ANY breaker state — health must stay
            # readable exactly when the cluster is degraded.
            cluster = getattr(self.ctx, "cluster", None)
            if cluster is not None:
                doc["cluster"] = cluster.state()
            return self._send(200, doc)
        if path == "/status/metrics":
            # Prometheus text exposition of the process registry (engines,
            # resilience, http counters, per-phase latency histograms).
            # ?cluster=1 on a BROKER federates the scrape: every
            # historical's registry merges in under a `node` label, with
            # unreachable nodes stamped stale — the scrape never 500s on
            # a dead historical (cluster/federation.py, ISSUE 19).
            from urllib.parse import parse_qs, urlparse

            qs = parse_qs(urlparse(self.path).query)
            cluster = getattr(self.ctx, "cluster", None)
            if qs.get("cluster", ["0"])[0] in ("1", "true") and (
                cluster is not None
            ):
                return self._send_bytes(
                    200,
                    cluster.federated_metrics().encode(),
                    "text/plain; version=0.0.4; charset=utf-8",
                )
            return self._send_bytes(
                200,
                get_registry().render_prometheus().encode(),
                "text/plain; version=0.0.4; charset=utf-8",
            )
        if path == "/status/profile":
            # workload profiler (obs/prof.py, ISSUE 9): rolling-window
            # top-K queries by device time, per-family compile totals,
            # per-lane SLO burn-rate.  ?k= and ?window_s= override the
            # configured defaults per request; ?cluster=1 on a broker
            # federates every historical's profile under its node id
            # (stale entries for unreachable nodes, never a 500).
            from urllib.parse import parse_qs, urlparse

            from .obs.prof import profile_doc

            qs = parse_qs(urlparse(self.path).query)

            def _num(name, cast):
                try:
                    return cast(qs[name][0])
                except (KeyError, IndexError, TypeError, ValueError):
                    return None

            local = profile_doc(
                config=getattr(self.ctx, "config", None),
                top_k=_num("k", int),
                window_s=_num("window_s", float),
            )
            cluster = getattr(self.ctx, "cluster", None)
            if qs.get("cluster", ["0"])[0] in ("1", "true") and (
                cluster is not None
            ):
                return self._send(
                    200, cluster.federated_profile(local)
                )
            return self._send(200, local)
        if path.startswith("/druid/v2/trace/"):
            qid = path.rsplit("/", 1)[1]
            tr = self._tracer().ring.get(qid)
            if tr is None:
                return self._error(
                    404, f"no trace for query id {qid!r} (ring holds the "
                    "most recent traces only)", "NotFound",
                )
            return self._send(200, tr)
        if path == "/status":
            m = self.ctx.last_metrics
            res = self._resilience()
            return self._send(
                200,
                {
                    "service": "spark-druid-olap-tpu",
                    "datasources": sorted(self.ctx.catalog.tables()),
                    "last_query_metrics": m.to_dict() if m else None,
                    "resilience": res.health() if res else None,
                    # serving core (serve/): fusion + result-cache stats
                    "serving": (
                        self.ctx.serve.to_dict()
                        if getattr(self.ctx, "serve", None) is not None
                        else None
                    ),
                    # registry summary: counter/gauge values + histogram
                    # p50/p95/p99 (full series live at /status/metrics)
                    "metrics": get_registry().to_dict(),
                    # __sys telemetry sampler (obs/telemetry.py): tick/
                    # row/drop counters; None when never started
                    "sys_sampler": (
                        self.ctx.sys_sampler.status()
                        if getattr(self.ctx, "sys_sampler", None)
                        is not None
                        else None
                    ),
                },
            )
        if path == "/druid/v2/datasources":
            return self._send(200, sorted(self.ctx.catalog.tables()))
        if path.startswith("/druid/v2/datasources/"):
            name = path.rsplit("/", 1)[1]
            ds = self.ctx.catalog.get(name)
            if ds is None:
                return self._error(404, f"unknown datasource {name!r}")
            return self._send(
                200,
                {
                    "dimensions": [
                        c.name for c in ds.columns if c.kind == "dimension"
                    ],
                    "metrics": [
                        c.name for c in ds.columns if c.kind == "metric"
                    ],
                    "timeColumn": ds.time_column,
                    "numRows": ds.num_rows,
                    "segments": len(ds.segments),
                },
            )
        return self._error(404, f"no route {path!r}")

    def do_POST(self):
        import time as _time

        # per-request state: with HTTP/1.1 keep-alive the SAME handler
        # instance serves every request on the connection — a stale id
        # from the previous query must never echo on this response
        self._query_id = None
        self._req_t0 = _time.perf_counter()
        path = self.path.split("?")[0].rstrip("/")
        body = self._body()
        if body is None:
            return self._error(
                400, "invalid JSON body", "BadJsonQueryException"
            )
        if path.startswith("/druid/v2/ingest/"):
            return self._ingest(path.rsplit("/", 1)[1], body)
        if path == "/druid/v2/cluster/partial":
            # the historical's scatter surface (cluster/, ISSUE 16)
            return self._cluster_partial(body)
        if path not in ("/druid/v2", "/druid/v2/sql"):
            return self._error(404, f"no route {path!r}", "NotFound")
        # A non-dict context is client noise, not a server error: ignore it.
        qctx = body.get("context")
        qctx = qctx if isinstance(qctx, dict) else {}
        # query_id is born HERE, the server boundary: honor Druid's
        # `context.queryId` when the client set one, generate otherwise.
        # Echoed on every response as X-Druid-Query-Id (_send_bytes) and
        # carried through the whole execution by the active trace.
        client_qid = qctx.get("queryId")
        self._query_id = (
            str(client_qid) if client_qid else new_query_id()
        )
        cfg = getattr(self.ctx, "config", None)
        res = self._resilience()
        self._buffered_response = None
        self._defer_buffered = True
        try:
            with self._tracer().query_trace(
                query_id=self._query_id,
                query_type="native" if path == "/druid/v2" else "sql",
                slow_ms=cfg.slow_query_ms if cfg else 0.0,
            ):
                return self._handle_query(path, body, qctx, res, cfg)
        finally:
            # trace-before-response contract: the buffered response was
            # CAPTURED by _send_bytes during the query scope and is
            # written HERE — after the trace published to the ring — so
            # a client that reads it and immediately fetches
            # /druid/v2/trace/{id} can never race the publish
            self._defer_buffered = False
            pending = self._buffered_response
            if pending is not None:
                self._buffered_response = None
                try:
                    self._send_bytes(*pending)
                except OSError:
                    pass  # client disconnected before the body landed
            # a streamed (chunked) response gets the same guarantee from
            # its terminal 0-chunk, deferred to HERE — the client's read
            # completes only on that chunk
            code = getattr(self, "_pending_chunked_finish", None)
            if code is not None:
                self._pending_chunked_finish = None
                try:
                    self._finish_response(code, chunked=True)
                except OSError:
                    # client disconnected mid-stream: the terminal
                    # 0-chunk has no socket to land on — not an error
                    pass

    def _handle_query(self, path, body, qctx, res, cfg):
        # a recovering node is BUSY, not wedged: while boot WAL replay is
        # still applying journaled appends, answering queries would serve
        # a state mid-way between the snapshot and the pre-crash tail —
        # 503 + Retry-After tells the balancer to come back, exactly like
        # an exhausted admission pool does
        storage = getattr(self.ctx, "storage", None)
        if storage is not None and storage.replay_in_progress:
            return self._error(
                503,
                "node is recovering (WAL replay in progress); retry later",
                "QueryUnavailableException",
                headers={
                    "Retry-After": res.admission.retry_after_s()
                    if res is not None
                    else 1
                },
            )
        # admission is per-route and LANE-FIRST (serve/lanes.py): the
        # query takes its priority lane's slot before the global pool,
        # so a heavy query queued on a full heavy lane never sits on a
        # global slot while waiting — that ordering is what keeps the
        # interactive lane's capacity reachable under a heavy storm
        try:
            # Druid-native per-query deadline: `context.timeout` (ms)
            # overrides the session default — including `timeout: 0`,
            # Druid's explicit "no timeout".  The scope set HERE is the
            # outermost, so ctx.sql's own scope defers to it.
            if "timeout" in qctx:
                try:
                    timeout_ms = float(qctx["timeout"])
                except (TypeError, ValueError):
                    timeout_ms = 0
                if timeout_ms <= 0:
                    # explicit opt-out: arm an INFINITE deadline so the
                    # session default inside ctx.sql (which defers to any
                    # outer scope) cannot re-arm a budget the client
                    # declined
                    timeout_ms = float("inf")
            else:
                timeout_ms = cfg.query_timeout_ms if cfg else 0
            # partial-result collection: session default, overridable per
            # request via context.partialResults (Druid-style context
            # flag).  The scope armed HERE is the outermost, so ctx.sql's
            # own scope joins it and the response headers can read the
            # collector after execution.
            p_enabled = bool(cfg.partial_results) if cfg else False
            pflag = qctx.get("partialResults")
            if isinstance(pflag, bool):
                p_enabled = pflag
            with deadline_scope(timeout_ms), partial_scope(p_enabled):
                if path == "/druid/v2":
                    return self._native_query(body, qctx)
                return self._sql_query(body, qctx)
        except WireError as e:
            return self._error(400, str(e), "BadQueryException")
        except KeyError as e:
            return self._error(400, f"missing field: {e}", "BadQueryException")
        except Q.QueryValidationError as e:
            # validation of a decoded query (unknown orderBy column,
            # __time ordering on a timeless table): client error.  Plain
            # ValueError stays a 500 — internal invariants are not the
            # client's fault
            return self._error(400, str(e), "BadQueryException")
        except CircuitOpenError as e:
            # native wire queries have no logical plan to degrade to the
            # host fallback with: an open breaker fails them FAST (503 +
            # Retry-After) instead of burning retry budget on a device
            # known to be down
            return self._error(
                503, str(e), "QueryUnavailableException",
                headers={
                    "Retry-After": res.admission.retry_after_s()
                    if res is not None
                    else 1
                },
            )
        except DeadlineExceeded as e:
            # the api layer counts SQL deadline expiry itself; only count
            # here when the exception arrives uncounted (the native path)
            if res is not None and not getattr(e, "_sdol_counted", False):
                res.note_deadline_exceeded()
            return self._error(504, str(e), "QueryTimeoutException")
        except Exception as e:
            # a 500 must not leak raw exception text (internals, paths,
            # data values) to clients: structured Druid-style error out,
            # full traceback to the server log, failure recorded on the
            # resilience counters + the query's metrics
            log.error("query failed: %s", type(e).__name__, exc_info=True)
            # the failing query's OWN metrics already carry error_class
            # (the engine retry loop stamps it); stamping last_metrics here
            # would pollute an unrelated earlier query when the failure
            # precedes execution (e.g. a parse error)
            if res is not None:
                res.note_server_error(e)
            return self._error(
                500,
                "query execution failed; see server logs",
                type(e).__name__,
            )

    def _admit(self, res) -> bool:
        """The GLOBAL admission pool — acquired AFTER the lane slot (a
        query waiting out a full lane must not hold global capacity
        while it waits).  A bounded slot pool with a queue-wait timeout
        answers 503 + Retry-After instead of piling handler threads
        behind a slow device until the process wedges."""
        with span(SPAN_ADMISSION):
            admitted = res is None or res.admission.acquire()
        if not admitted:
            self._error(
                503,
                "query capacity exceeded; retry later",
                "QueryCapacityExceededException",
                headers={"Retry-After": res.admission.retry_after_s()},
            )
        return admitted

    def _ingest(self, name: str, body: dict):
        """POST /druid/v2/ingest/{datasource}: streamed row append (the
        realtime-node push analog).  Body: {"rows": [...row objects...]}
        or {"columns": {name: [values...]}}.  Gated on the SEPARATE
        ingest admission pool (503 + Retry-After when full) so appends
        and queries cannot starve each other, and on the same per-request
        deadline contract queries get (`context.timeout` honored)."""
        res = self._resilience()
        cfg = getattr(self.ctx, "config", None)
        qctx = body.get("context")
        qctx = qctx if isinstance(qctx, dict) else {}
        client_qid = qctx.get("queryId")
        self._query_id = str(client_qid) if client_qid else new_query_id()
        rows = body.get("rows", body.get("columns"))
        if rows is None:
            return self._error(
                400,
                'body must carry "rows" (row objects) or "columns" '
                "(column arrays)",
                "BadQueryException",
            )
        with span(SPAN_ADMISSION):
            admitted = res is None or res.ingest_admission.acquire()
        if not admitted:
            return self._error(
                503,
                "ingest capacity exceeded; retry later",
                "QueryCapacityExceededException",
                headers={
                    "Retry-After": res.ingest_admission.retry_after_s()
                },
            )
        try:
            # tolerate a malformed context.timeout exactly like the query
            # route: client noise means "no timeout", never a 500
            if "timeout" in qctx:
                try:
                    timeout_ms = float(qctx["timeout"])
                except (TypeError, ValueError):
                    timeout_ms = 0
            else:
                timeout_ms = cfg.query_timeout_ms if cfg else 0
            if timeout_ms <= 0:
                timeout_ms = float("inf")
            with self._tracer().query_trace(
                query_id=self._query_id,
                query_type="ingest",
                slow_ms=cfg.slow_query_ms if cfg else 0.0,
            ), deadline_scope(timeout_ms):
                ack = self.ctx.ingest.append_rows(name, rows)
            return self._send(200, ack)
        except KeyError as e:
            return self._error(
                400, f"unknown dataSource: {e}", "BadQueryException"
            )
        except ValueError as e:
            # malformed client payload (ragged columns, unknown columns,
            # unparseable time values): 400, not a server error
            return self._error(400, str(e), "BadQueryException")
        except DeadlineExceeded as e:
            if res is not None:
                res.note_deadline_exceeded()
            return self._error(504, str(e), "QueryTimeoutException")
        except Exception as e:
            log.error("ingest failed: %s", type(e).__name__, exc_info=True)
            if res is not None:
                res.note_server_error(e)
            return self._error(
                500, "ingest failed; see server logs", type(e).__name__
            )
        finally:
            if res is not None:
                res.ingest_admission.release()

    def _cluster_partial(self, body: dict):
        """POST /druid/v2/cluster/partial: the historical's scatter
        surface (cluster/, ISSUE 16).  Body: {"query": native query
        object, "segments": [segment_id, ...] | null (full scope),
        "version": broker's expected datasource version, "context":
        {...}}.  Executes the query's HOST partial state over exactly
        the requested segments and returns it wire-encoded with the
        datasource version, the served segment ids, and this node's
        per-query cost receipt — the broker ⊕'s the states through the
        same merge tree the mesh slices use.

        Contract edges: a node still replaying its WAL answers 503 +
        Retry-After (its replicas carry the traffic; the replay-while-
        serving test pins this); a segment id or version this catalog
        cannot satisfy answers 409 (assignment skew — the broker treats
        the replica as failed and rebalances), never a wrong merge."""
        from .cluster.wire import HEADER_PARENT_SPAN, HEADER_QUERY_ID

        res = self._resilience()
        cfg = getattr(self.ctx, "config", None)
        qctx = body.get("context")
        qctx = qctx if isinstance(qctx, dict) else {}
        # trace propagation (ISSUE 19): the broker sends the query id
        # both ways (context.queryId AND the X-Druid-Query-Id header) —
        # context wins, the header covers native clients; the parent
        # span id stamps this trace's cross-process parentage so the
        # OTLP exports of both processes join under one trace id
        client_qid = qctx.get("queryId") or self.headers.get(
            HEADER_QUERY_ID
        )
        self._query_id = str(client_qid) if client_qid else new_query_id()
        parent_span = str(self.headers.get(HEADER_PARENT_SPAN) or "")
        storage = getattr(self.ctx, "storage", None)
        if storage is not None and storage.replay_in_progress:
            return self._error(
                503,
                "node is recovering (WAL replay in progress); retry later",
                "QueryUnavailableException",
                headers={
                    "Retry-After": res.admission.retry_after_s()
                    if res is not None
                    else 1
                },
            )
        qdoc = body.get("query")
        if not isinstance(qdoc, dict):
            return self._error(
                400, 'body must carry a native "query" object',
                "BadQueryException",
            )
        if not self._admit(res):
            return None
        try:
            # chaos site: an armed error IS this historical dying while
            # serving (the broker sees the failure and fails over to a
            # replica); delay mode is the slow-replica cell
            fire("cluster.historical_kill")
            from .cluster.wire import encode_state

            q = query_from_druid(qdoc)
            ds = self.ctx.catalog.get(q.datasource)
            if ds is None:
                return self._error(
                    400, f"unknown dataSource {q.datasource!r}",
                    "BadQueryException",
                )
            # snapshot-generation check (GL2301): the LIVE catalog
            # version is process-local (every republish bumps it), so
            # replicas compare the SNAPSHOT version they booted — the
            # one number identical across processes sharing the store
            have = (
                storage.snapshot_version(q.datasource)
                if storage is not None else None
            )
            if have is None:
                have = int(ds.version)
            expect = body.get("version")
            if expect is not None and have != int(expect):
                return self._error(
                    409,
                    f"datasource {q.datasource!r} at snapshot version "
                    f"{have}, broker's assignment expects {int(expect)} "
                    "— replica/assignment skew; rebalance and retry",
                    "VersionMismatchException",
                )
            want = body.get("segments")
            by_id = {s.segment_id: s.uid for s in ds.segments}
            if want is None:
                uids = None
                served = sorted(by_id)
            else:
                missing = [sid for sid in want if sid not in by_id]
                if missing:
                    return self._error(
                        409,
                        f"unknown segments {missing[:4]} (assignment vs "
                        "catalog skew) — rebalance and retry",
                        "VersionMismatchException",
                    )
                uids = frozenset(by_id[sid] for sid in want)
                served = [str(sid) for sid in want]
            with self._tracer().query_trace(
                query_id=self._query_id,
                query_type="cluster_partial",
                slow_ms=cfg.slow_query_ms if cfg else 0.0,
                parent_span_id=parent_span,
            ) as tr:
                if tr is not None:
                    tr.root.attrs["node"] = getattr(
                        self.ctx, "cluster_node_id", ""
                    )
                self.ctx._sync_engine_resilience(self.ctx.engine)
                state, rows = self.ctx.engine.groupby_partials_host(
                    q, ds, within_uids=uids
                )
            doc = {
                "node": getattr(self.ctx, "cluster_node_id", ""),
                "version": int(have),
                "rows": int(rows),
                "segments": served,
                "state": encode_state(state),
            }
            if tr is not None and tr.receipt:
                # per-historical receipt (ISSUE 16 obs satellite): the
                # broker folds this into its own receipt's cluster
                # section, so one query attributes across processes
                doc["receipt"] = tr.receipt
            if tr is not None:
                # rendered span subtree for the broker to graft under
                # its cluster_rpc span (ISSUE 19); size-capped, and any
                # defect degrades to a stub — never a failed response
                from .cluster.wire import encode_trace

                subtree = encode_trace(tr.to_dict())
                if subtree is not None:
                    doc["trace"] = subtree
            return self._send(200, doc)
        except (WireError, ValueError) as e:
            return self._error(400, str(e), "BadQueryException")
        except DeadlineExceeded as e:
            if res is not None:
                res.note_deadline_exceeded()
            return self._error(504, str(e), "QueryTimeoutException")
        except Exception as e:
            log.error(
                "cluster partial failed: %s", type(e).__name__,
                exc_info=True,
            )
            if res is not None:
                res.note_server_error(e)
            return self._error(
                500, "cluster partial failed; see server logs",
                type(e).__name__,
            )
        finally:
            if res is not None:
                res.admission.release()

    def _partial_headers(self) -> Optional[dict]:
        """X-Druid-Response-Context carrying the partial-result contract
        (ISSUE 7): when the answer about to be sent is deadline-bounded,
        the header holds {"partial": true, "coverage": ..., rows seen /
        total, delta split} — Druid's own response-context header, so
        existing clients that already parse it see the flag.

        A SAMPLED query (obs/prof.py, ISSUE 9) additionally carries its
        cost receipt under a "receipt" key — the per-query device/host/
        transfer split and cache-tier outcomes on the wire.  Unsampled
        queries keep the exact historical header behavior (absent unless
        partial)."""
        from .obs.prof import live_receipt, profiled

        rctx = {}
        pc = current_partial()
        if pc is not None and pc.is_partial:
            rctx.update(pc.to_dict())
        if profiled():
            rc = live_receipt()
            if rc is not None:
                rctx["receipt"] = rc
        if not rctx:
            return None
        return {
            "X-Druid-Response-Context": json.dumps(rctx, default=_jsonable)
        }

    # query types that never dispatch device work: answered from catalog
    # metadata, so breaker state is irrelevant to them
    _METADATA_QUERIES = (
        Q.TimeBoundaryQuery,
        Q.DataSourceMetadataQuery,
        Q.SegmentMetadataQuery,
    )

    def _acquire_lane(self, lane_name: str):
        """Gate one query on its priority lane's slot pool (serve/lanes):
        returns True when admitted, or sends the 503 (naming the lane,
        with the lane's OWN observed-load Retry-After) and returns False.
        A context without resilience state admits everything."""
        res = self._resilience()
        if res is None or not getattr(res, "lanes", None):
            return True
        pool = res.lane(lane_name)
        with span(SPAN_LANE, lane=lane_name):
            admitted = pool.acquire()
        if not admitted:
            self._error(
                503,
                f"{lane_name} lane capacity exceeded; retry later",
                "QueryCapacityExceededException",
                headers={"Retry-After": pool.retry_after_s()},
            )
        return admitted

    def _release_lane(self, lane_name: Optional[str]):
        res = self._resilience()
        if lane_name and res is not None and getattr(res, "lanes", None):
            res.lane(lane_name).release()

    def _native_query(self, body: dict, qctx: dict):
        res = self._resilience()
        serve = getattr(self.ctx, "serve", None)
        try:
            # cross-request decoded-QuerySpec plan cache (ROADMAP 1(c)):
            # dashboards POST the identical body every refresh — a hit
            # skips the wire decode entirely, shaving the fast lane's
            # per-request floor
            if serve is not None:
                q = serve.decode_native(body)
            else:
                q = query_from_druid(body)
        except ValueError as e:
            # decode-time ValueErrors (unsupported filter type, malformed
            # interval timestamps) are malformed CLIENT input — 400, same
            # as WireError; execution-time ValueErrors stay 500
            raise WireError(str(e)) from e
        ds = self.ctx.catalog.get(q.datasource)
        if ds is None:
            return self._error(400, f"unknown dataSource {q.datasource!r}")
        # priority lanes (serve/lanes.py): a cheap dashboard query takes
        # an interactive slot an SF100-scale scan cannot starve; heavy
        # work gates on its own small pool with a per-lane Retry-After
        from .obs.prof import note_lane
        from .serve.lanes import classify_native

        lane_name = classify_native(
            q, ds, getattr(self.ctx, "config", None)
        )
        note_lane(lane_name)  # the workload profiler's SLO burn key
        if not self._acquire_lane(lane_name):
            return None
        try:
            if not self._admit(res):
                return None
            try:
                return self._native_query_admitted(q, ds, body, qctx, res)
            finally:
                if res is not None:
                    res.admission.release()
        finally:
            self._release_lane(lane_name)

    def _native_query_admitted(self, q, ds, body: dict, qctx: dict, res):
        needs_device = not isinstance(q, self._METADATA_QUERIES)
        serve = getattr(self.ctx, "serve", None)
        if (
            needs_device
            and res is not None
            and not res.breaker_for("device").allow()
        ):
            # an open circuit must not cost a cached answer (same stance
            # as the SQL path): exact hits need no device — but a delta
            # refresh WOULD dispatch, so allow_delta=False
            if serve is not None:
                hit = serve.cached_native(q, ds, allow_delta=False)
                if hit is not None:
                    return self._send(
                        200, druid_result_shape(q, hit),
                        headers=self._partial_headers(),
                    )
            # the device breaker is open: degrade the wire query through
            # the native->logical fallback interpreter instead of the old
            # blanket 503 (the completed degradation-matrix cell); shapes
            # the interpreter can't cover still fail fast with 503
            return self._native_degraded(q, None, "circuit_open")
        progressive = (
            bool(qctx.get("progressive"))
            and isinstance(
                q, (Q.GroupByQuery, Q.TimeseriesQuery, Q.TopNQuery)
            )
            and not (isinstance(q, Q.GroupByQuery) and q.subtotals)
        )
        if progressive:
            return self._progressive_query(q, ds)
        def run():
            if isinstance(q, Q.GroupByQuery) and q.subtotals:
                # wire subtotalsSpec: same grouping-set expansion the SQL
                # path uses — the engine alone would silently run only
                # the full set
                from .api import execute_grouping_sets

                df = execute_grouping_sets(
                    dataclasses.replace(q, subtotals=()), q.subtotals, ds,
                    self.ctx.engine,
                )
                # internal bitmask column; real Druid events don't carry it
                return df.drop(columns=["__grouping_id"])
            # the serving core's native path (serve/): result cache
            # (exact hit = zero device dispatch; delta-aware after an
            # append) -> micro-batch fusion -> serial state-capturing
            # execution, with the computed answer published back
            if serve is None:
                return self.ctx.engine.execute(q, ds)
            # ONE key computation per request (it JSON-serializes the
            # spec), shared by lookup and store
            rkey = serve.native_key(q, ds)
            hit = serve.cached_native(q, ds, key=rkey)
            if hit is not None:
                return hit
            # broker mode (cluster/, ISSUE 16): scatter the query's
            # assigned segments to historicals and ⊕ their states — the
            # result cache above rides the broker (an exact hit never
            # scatters) and fusion stays local-only below, so coverage
            # of the two tiers composes instead of competing
            cluster = getattr(self.ctx, "cluster", None)
            if cluster is not None and cluster.covers(q, ds):
                df = cluster.execute(q, ds)
                self.ctx._last_engine_metrics = cluster.last_metrics
                pc = current_partial()
                if rkey is not None and not (
                    pc is not None and pc.triggered
                ):
                    # frame-only: a gathered answer has no LOCAL state
                    # to delta-refresh, and a coverage-stamped partial
                    # must never seed the cache
                    serve.store_native(q, ds, df, key=rkey)
                return df
            fusable = self.ctx.engine.fusable(q, ds)
            if fusable:
                fused = serve.fused_execute(q, ds)
                if fused is not None:
                    df, state, m = fused
                    self.ctx._last_engine_metrics = m
                    serve.store_native(q, ds, df, state=state, key=rkey)
                    return df
            if fusable and rkey is not None:
                # capture the merged host state alongside the serial
                # execution so the next append refreshes this entry by
                # scanning only the delta
                with self.ctx.engine.state_capture() as cap:
                    df = self.ctx.engine.execute(q, ds)
                # stamp the context's most-recent metrics: an earlier
                # cache hit left its own object pinned there, and
                # ctx.last_metrics prefers it over the engine's — a
                # stale "result-cache" would misattribute THIS execution
                self.ctx._last_engine_metrics = (
                    self.ctx.engine.last_metrics
                )
                serve.store_native(q, ds, df, state=cap["state"], key=rkey)
                return df
            df = self.ctx.engine.execute(q, ds)
            self.ctx._last_engine_metrics = self.ctx.engine.last_metrics
            if rkey is not None:
                # non-fusable GroupBy-family shapes (sparse/adaptive
                # tiers hold no dense state) still cache frame-only:
                # identical refreshes hit version-exact, appends miss
                serve.store_native(q, ds, df, key=rkey)
            return df

        try:
            self.ctx._sync_engine_resilience(self.ctx.engine)
            try:
                df = run()
            except Exception as err:
                # deadline expiry OUTSIDE the partial-capable loops
                # (planning, a blocking fetch, a ladder rung): same
                # drain-rerun the SQL surface does in
                # api._execute_with_resilience — trigger the collector
                # so every checkpoint no-ops, and the rerun yields the
                # well-formed coverage-stamped answer instead of a 504
                pc = current_partial()
                if pc is None or classify_error(err) != "deadline":
                    raise
                pc.trigger(getattr(err, "site", "") or "deadline")
                log.warning(
                    "deadline expired outside a partial-capable loop "
                    "(%s); draining a best-effort native answer", err,
                )
                df = run()
            # partial-result discipline (GL16xx): the native surface
            # publishes a deadline-bounded answer (partial span +
            # sdol_partial_results_total/coverage histogram) exactly like
            # ctx.sql's _stamp_partial path; _partial_headers below only
            # adds the wire header.  The cost receipt (ISSUE 9) rides the
            # same stamp point.
            df = self.ctx._stamp_receipt(self.ctx._stamp_partial(df))
        except Exception as err:
            # a transient device failure that survived the engine's retry
            # budget degrades exactly like the SQL path does; static
            # errors and deadlines keep their taxonomy (handled above)
            if res is None or classify_error(err) != "transient":
                raise
            return self._native_degraded(q, err, "device_failed")
        self._send(
            200, druid_result_shape(q, df),
            headers=self._partial_headers(),
        )

    def _native_degraded(self, q, err, reason: str):
        """Degrade one wire-native query to the host fallback via the
        QuerySpec->logical interpreter.  Unsupported shapes keep the old
        fail-fast contract (503 on an open circuit, the original error
        otherwise) — a wrong degraded answer is worse than no answer."""
        from .exec.wire_fallback import WireFallbackUnsupported
        from .plan.transforms import RewriteError

        try:
            df = self.ctx.execute_native_degraded(q, err, reason=reason)
        except (WireFallbackUnsupported, NotImplementedError, RewriteError) as e:
            # RewriteError covers config.fallback_execution=False: the
            # degraded route is administratively off, so an open breaker
            # must keep the old fail-fast 503 + Retry-After contract
            # (not surface as a 500 through the generic handler)
            if err is None:
                raise CircuitOpenError(
                    "device circuit open and this native query cannot "
                    f"degrade to the host fallback ({e}) — retry after "
                    "the breaker's cooldown"
                ) from e
            raise err
        self._send(
            200, druid_result_shape(q, df),
            headers=self._partial_headers(),
        )

    def _progressive_query(self, q, ds):
        """Chunked progressive response (ISSUE 7 tentpole (b)): one
        NDJSON line per refinement — {"sequence", "coverage", "partial",
        "final", "result"} — converging to the exact answer as segment
        batches complete.  The FIRST refinement is computed before the
        status line commits, so pre-execution errors still produce
        normal structured error responses; mid-stream failures emit a
        terminal {"error": ...} line (the status is already on the
        wire)."""
        self.ctx._sync_engine_resilience(self.ctx.engine)
        gen = self.ctx.engine.execute_progressive(q, ds)
        return self._stream_refinements(gen, lambda df: druid_result_shape(q, df))

    def _stream_refinements(self, gen, shape):
        """Drive one refinement generator onto the wire as chunked
        NDJSON — shared by the native route and the SQL route (ROADMAP
        3(b)) so the line protocol, error handling, and the deferred
        terminal chunk cannot drift between surfaces.  `shape` renders a
        refinement frame into the route's result payload."""
        from .obs import SPAN_STREAM_FLUSH, span

        item = next(gen)  # may raise -> structured error path
        self._begin_response(200, "application/x-ndjson")
        try:
            while True:
                df, info = item
                line = {
                    "sequence": info["sequence"],
                    "coverage": info["coverage"],
                    "partial": bool(info.get("partial", False)),
                    "final": bool(info["final"]),
                    "rows_seen": info.get("rows_seen"),
                    "rows_total": info.get("rows_total"),
                    "result": shape(df),
                }
                if line["final"]:
                    # the FINAL refinement carries the stream's cost
                    # receipt (ISSUE 9 satellite): progressive clients
                    # get the same attribution a buffered response puts
                    # in df.attrs / the response-context header
                    from .obs.prof import live_receipt

                    rc = live_receipt()
                    if rc is not None:
                        line["receipt"] = rc
                with span(SPAN_STREAM_FLUSH, sequence=info["sequence"]):
                    self._write_chunk(
                        json.dumps(line, default=_jsonable).encode()
                        + b"\n"
                    )
                if info["final"]:
                    break
                item = next(gen)
        except OSError as e:
            # the CLIENT went away mid-stream (broken pipe / reset):
            # there is no socket to write a terminal line to, and a
            # disconnect is not a server error — swallow it here so it
            # neither attempts a second response through _error(500) nor
            # inflates the /status/health server-error counters
            log.info(
                "progressive client disconnected mid-stream: %s",
                type(e).__name__,
            )
        except Exception as e:  # fault-ok: status already sent; emit a terminal error line
            log.error(
                "progressive stream failed: %s", type(e).__name__,
                exc_info=True,
            )
            try:
                self._write_chunk(
                    json.dumps(
                        {
                            "error": "progressive stream failed; see "
                            "server logs",
                            "errorClass": type(e).__name__,
                            "final": True,
                        }
                    ).encode()
                    + b"\n"
                )
            except OSError:
                pass  # dead socket: the log line above is the record
        finally:
            # the terminal 0-chunk is DEFERRED to do_POST, past the
            # query_trace exit: the client's read() completes only on
            # that chunk, so the finished trace is guaranteed to be in
            # the ring before the client can ask /druid/v2/trace for it
            self._pending_chunked_finish = 200

    def _sql_query(self, body: dict, qctx: dict):
        sql = body.get("query")
        if not sql:
            return self._error(400, 'body must be {"query": "SELECT ..."}')
        # priority lanes: SQL classifies from its planned rewrite (via
        # the plan cache, so repeated dashboard statements pay planning
        # once); anything unplannable gates interactive
        serve = getattr(self.ctx, "serve", None)
        lane_name = serve.lane_for_sql(sql) if serve is not None else None
        if lane_name is not None:
            from .obs.prof import note_lane

            note_lane(lane_name)
        if lane_name is not None and not self._acquire_lane(lane_name):
            return None
        res = self._resilience()
        try:
            if not self._admit(res):
                return None
            try:
                if qctx.get("progressive"):
                    # progressive SQL surface (ROADMAP 3(b)): chunked
                    # NDJSON refinements converging to the exact answer,
                    # same line protocol as the native route; shapes that
                    # cannot stream fall through to the buffered response
                    gen = self.ctx.sql_progressive(sql)
                    if gen is not None:
                        return self._stream_refinements(gen, _rows)
                df = self.ctx.sql(sql)
                self._send(
                    200, _rows(df), headers=self._partial_headers()
                )
            finally:
                if res is not None:
                    res.admission.release()
        finally:
            self._release_lane(lane_name)


class _OlapHTTPServer(ThreadingHTTPServer):
    # the stdlib listen backlog is 5: a burst of concurrent dashboard
    # connections (the workload the serving core exists for) overflows
    # it, the kernel drops the SYN, and the client retries after ~1 s —
    # a full second of invisible latency the handler never sees.  128
    # accommodates hammer-scale connection bursts.
    request_queue_size = 128


class OlapServer:
    """Threaded HTTP server over one TPUOlapContext.

    Queries execute on handler threads; the engine's caches are guarded by
    the catalog lock + XLA's own thread-safe dispatch, and query programs are
    cached per (query, schema) so concurrent BI dashboards share compiles.
    """

    def __init__(self, ctx, host: str = "127.0.0.1", port: int = 8082):
        handler = type("BoundHandler", (_Handler,), {"ctx": ctx})
        self.httpd = _OlapHTTPServer((host, port), handler)
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self.httpd.server_address[1]

    def start(self) -> "OlapServer":
        self._thread = threading.Thread(
            target=self.httpd.serve_forever, daemon=True
        )
        self._thread.start()
        return self

    def serve_forever(self):
        self.httpd.serve_forever()

    def shutdown(self):
        self.httpd.shutdown()
        self.httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
