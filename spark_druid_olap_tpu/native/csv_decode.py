"""ctypes bindings over the native CSV decoder (olap_native.cc).

Two read modes:

* `read_csv(path)` — drop-in for the pandas fallback in catalog/ingest.py:
  string columns come back as object arrays (None for empty fields).
* `read_csv_encoded(path)` — the fast path register_table uses: string
  columns come back as int32 rank codes plus a `DimensionDict` (sorted-unique
  domain, identical contract to catalog/segment.py), so build_datasource
  skips re-encoding entirely.
"""

from __future__ import annotations

import ctypes
from typing import Dict, Tuple

import numpy as np

from . import load

COL_INT64, COL_DOUBLE, COL_STRING = 0, 1, 2


class _Handle:
    def __init__(self, lib, h):
        self._lib = lib
        self._h = h

    def __del__(self):
        if getattr(self, "_h", None):
            self._lib.olap_csv_free(self._h)
            self._h = None


def _open(path: str):
    lib = load()
    if lib is None:
        raise RuntimeError("native library unavailable")
    h = lib.olap_csv_read(path.encode())
    if not h:
        raise RuntimeError(f"native csv_read failed for {path!r}")
    handle = _Handle(lib, h)
    err = lib.olap_csv_error(h)
    if err:
        raise ValueError(f"csv parse error in {path!r}: {err.decode()}")
    return lib, handle


def _columns(lib, handle, decode_strings: bool):
    h = handle._h
    n_rows = lib.olap_csv_num_rows(h)
    n_cols = lib.olap_csv_num_cols(h)
    cols: Dict[str, np.ndarray] = {}
    dicts: Dict[str, "DimensionDict"] = {}
    from ..catalog.segment import DimensionDict

    for c in range(n_cols):
        name = lib.olap_csv_col_name(h, c).decode()
        t = lib.olap_csv_col_type(h, c)
        if t == COL_INT64:
            out = np.empty(n_rows, dtype=np.int64)
            lib.olap_csv_col_int64(h, c, out.ctypes.data_as(ctypes.c_void_p))
            cols[name] = out
        elif t == COL_DOUBLE:
            out = np.empty(n_rows, dtype=np.float64)
            lib.olap_csv_col_double(h, c, out.ctypes.data_as(ctypes.c_void_p))
            cols[name] = out
        else:
            codes = np.empty(n_rows, dtype=np.int32)
            lib.olap_csv_col_codes(h, c, codes.ctypes.data_as(ctypes.c_void_p))
            k = lib.olap_csv_dict_size(h, c)
            values = tuple(
                lib.olap_csv_dict_value(h, c, i).decode() for i in range(k)
            )
            d = DimensionDict(values=values)
            if decode_strings:
                cols[name] = d.decode(codes)
            else:
                cols[name] = codes
                dicts[name] = d
    return cols, dicts


def read_csv(path: str) -> Dict[str, np.ndarray]:
    lib, handle = _open(path)
    cols, _ = _columns(lib, handle, decode_strings=True)
    return cols


def read_csv_encoded(path: str) -> Tuple[Dict[str, np.ndarray], Dict]:
    """(columns, dicts): string columns pre-encoded as rank codes."""
    lib, handle = _open(path)
    return _columns(lib, handle, decode_strings=False)


def encode_strings(values) -> Tuple[np.ndarray, Tuple[str, ...]]:
    """Native sorted-unique dictionary encode of a python string sequence
    (None -> null code -1).  Returns (int32 codes, sorted values)."""
    lib = load()
    if lib is None:
        raise RuntimeError("native library unavailable")
    n = len(values)
    arr = (ctypes.c_char_p * n)()
    keepalive = []
    for i, v in enumerate(values):
        if v is None or (isinstance(v, float) and np.isnan(v)):
            arr[i] = None
        else:
            b = v.encode() if isinstance(v, str) else str(v).encode()
            keepalive.append(b)
            arr[i] = b
    h = lib.olap_dict_encode(arr, n)
    try:
        codes = np.empty(n, dtype=np.int32)
        lib.olap_dict_codes(h, codes.ctypes.data_as(ctypes.c_void_p))
        k = lib.olap_dict_size(h)
        vals = tuple(lib.olap_dict_value(h, i).decode() for i in range(k))
    finally:
        lib.olap_dict_free(h)
    return codes, vals
