"""Native (C++) host-side runtime components, bound via ctypes.

The reference has no native code — its engine is an external Druid cluster
(SURVEY.md §2 "Native components: NONE in reference").  The obligation moves
here: the host hot paths around the TPU compute (columnar decode, dictionary
encoding) are implemented in C++ (`olap_native.cc`) and loaded through a
plain C ABI.  pybind11 is not available in this image, so bindings are
ctypes; the library is compiled on first use with g++ and cached next to the
source.  Every caller has a pure-python fallback — the native layer is an
accelerator, never a requirement.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "olap_native.cc")
_SO = os.path.join(_HERE, "_olap_native.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_build_failed = False


def _needs_build() -> bool:
    if not os.path.exists(_SO):
        return True
    return os.path.getmtime(_SO) < os.path.getmtime(_SRC)


def _build() -> bool:
    """Compile olap_native.cc -> _olap_native.so.  Atomic (tmp + rename) so
    concurrent processes can race safely."""
    tmp = _SO + f".tmp.{os.getpid()}"
    cmd = [
        "g++", "-O3", "-std=c++17", "-shared", "-fPIC",
        "-o", tmp, _SRC,
    ]
    try:
        subprocess.run(
            cmd, check=True, capture_output=True, timeout=120
        )
        os.replace(tmp, _SO)
        return True
    except (subprocess.SubprocessError, OSError):
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False


def load() -> Optional[ctypes.CDLL]:
    """The loaded native library, building it if necessary; None when no
    toolchain is available (callers then use their python fallbacks)."""
    global _lib, _build_failed
    if _lib is not None:
        return _lib
    if _build_failed:
        return None
    with _lock:
        if _lib is not None:
            return _lib
        if _needs_build() and not _build():
            _build_failed = True
            return None
        try:
            lib = ctypes.CDLL(_SO)
        except OSError:
            _build_failed = True
            return None
        _declare(lib)
        if lib.olap_abi_version() != 1:
            _build_failed = True
            return None
        _lib = lib
    return _lib


def available() -> bool:
    return load() is not None


def _declare(lib: ctypes.CDLL) -> None:
    c = ctypes
    lib.olap_csv_read.argtypes = [c.c_char_p]
    lib.olap_csv_read.restype = c.c_void_p
    lib.olap_csv_error.argtypes = [c.c_void_p]
    lib.olap_csv_error.restype = c.c_char_p
    lib.olap_csv_num_rows.argtypes = [c.c_void_p]
    lib.olap_csv_num_rows.restype = c.c_longlong
    lib.olap_csv_num_cols.argtypes = [c.c_void_p]
    lib.olap_csv_num_cols.restype = c.c_int
    lib.olap_csv_col_name.argtypes = [c.c_void_p, c.c_int]
    lib.olap_csv_col_name.restype = c.c_char_p
    lib.olap_csv_col_type.argtypes = [c.c_void_p, c.c_int]
    lib.olap_csv_col_type.restype = c.c_int
    lib.olap_csv_col_int64.argtypes = [c.c_void_p, c.c_int, c.c_void_p]
    lib.olap_csv_col_int64.restype = None
    lib.olap_csv_col_double.argtypes = [c.c_void_p, c.c_int, c.c_void_p]
    lib.olap_csv_col_double.restype = None
    lib.olap_csv_col_codes.argtypes = [c.c_void_p, c.c_int, c.c_void_p]
    lib.olap_csv_col_codes.restype = None
    lib.olap_csv_dict_size.argtypes = [c.c_void_p, c.c_int]
    lib.olap_csv_dict_size.restype = c.c_int
    lib.olap_csv_dict_value.argtypes = [c.c_void_p, c.c_int, c.c_int]
    lib.olap_csv_dict_value.restype = c.c_char_p
    lib.olap_csv_free.argtypes = [c.c_void_p]
    lib.olap_csv_free.restype = None

    lib.olap_dict_encode.argtypes = [c.POINTER(c.c_char_p), c.c_longlong]
    lib.olap_dict_encode.restype = c.c_void_p
    lib.olap_dict_codes.argtypes = [c.c_void_p, c.c_void_p]
    lib.olap_dict_codes.restype = None
    lib.olap_dict_size.argtypes = [c.c_void_p]
    lib.olap_dict_size.restype = c.c_int
    lib.olap_dict_value.argtypes = [c.c_void_p, c.c_int]
    lib.olap_dict_value.restype = c.c_char_p
    lib.olap_dict_free.argtypes = [c.c_void_p]
    lib.olap_dict_free.restype = None
    lib.olap_abi_version.argtypes = []
    lib.olap_abi_version.restype = c.c_int
