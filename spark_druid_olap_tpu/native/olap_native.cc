// Native host-side columnar ingest for the TPU OLAP framework.
//
// Reference parity: the reference (spark-druid-olap) has no native code — it
// delegates storage+compute to an external Druid cluster whose segment
// engine is JVM; its hot host loop is the per-row JSON -> InternalRow decode
// in `DruidRDD.compute` (SURVEY.md §3.3 [U]).  In the TPU rebuild the
// analogous host hot path is raw-file -> dictionary-encoded columns ready
// for HBM upload, so that is what lives in native code: a single-pass CSV
// parser with per-column type inference and sorted-unique dictionary
// encoding (the same encoding catalog/segment.py's DimensionDict produces).
//
// Exposed as a plain C ABI consumed via ctypes (no pybind11 in this image).
// Column-major results; numeric columns are written straight into caller
// (numpy) buffers, string columns come back as int32 rank codes plus a
// sorted dictionary.

#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <algorithm>
#include <deque>
#include <memory>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

struct Field {
  // View into the file buffer; materialized into `arena` when the field
  // contained quote escapes ("" -> ").
  const char* ptr;
  int64_t len;
};

enum ColType : int {
  COL_INT64 = 0,
  COL_DOUBLE = 1,
  COL_STRING = 2,  // dictionary-encoded
};

struct Column {
  std::string name;
  ColType type = COL_STRING;
  // exactly one of these is populated after finish():
  std::vector<int64_t> i64;
  std::vector<double> f64;
  std::vector<int32_t> codes;          // rank codes, -1 = null
  std::vector<std::string> dict;       // sorted unique values
};

struct CsvTable {
  std::string error;
  std::string buf;                     // whole file
  // Unescaped quoted fields live here.  Field.ptr points INTO these strings,
  // so the container must never move elements — deque (stable addresses on
  // push_back), not vector.
  std::deque<std::string> arena;
  std::vector<Column> cols;
  int64_t num_rows = 0;
};

// pandas' default na_values set: these read as null in every column type
// (the python fallback is pd.read_csv — inference must not fork from it).
bool is_null_field(const char* p, int64_t len) {
  if (len == 0) return true;
  if (len > 9) return false;
  static const char* kNa[] = {
      "#N/A", "#N/A N/A", "#NA", "-1.#IND", "-1.#QNAN", "-NaN", "-nan",
      "1.#IND", "1.#QNAN", "<NA>", "N/A", "NA", "NULL", "NaN", "None",
      "n/a", "nan", "null"};
  for (const char* s : kNa) {
    if ((int64_t)strlen(s) == len && memcmp(p, s, (size_t)len) == 0)
      return true;
  }
  return false;
}

bool parse_i64(const char* p, int64_t len, int64_t* out) {
  if (len == 0) return false;
  char tmp[32];
  if (len >= (int64_t)sizeof(tmp)) return false;
  memcpy(tmp, p, len);
  tmp[len] = 0;
  char* end = nullptr;
  errno = 0;
  long long v = strtoll(tmp, &end, 10);
  if (errno != 0 || end != tmp + len) return false;
  *out = (int64_t)v;
  return true;
}

bool parse_f64(const char* p, int64_t len, double* out) {
  if (len == 0) return false;
  char tmp[64];
  if (len >= (int64_t)sizeof(tmp)) return false;
  memcpy(tmp, p, len);
  tmp[len] = 0;
  char* end = nullptr;
  errno = 0;
  double v = strtod(tmp, &end);
  if (end != tmp + len) return false;
  *out = v;
  return true;
}

// Single-pass RFC4180-ish tokenizer: quoted fields may contain commas,
// newlines, and doubled quotes.  Fills row-major `fields`; returns column
// count from the header row.
bool tokenize(CsvTable* t, std::vector<Field>* fields, int* ncols_out) {
  const char* p = t->buf.data();
  const char* end = p + t->buf.size();
  std::vector<Field> row;
  int ncols = -1;
  bool header_done = false;
  std::vector<std::string> names;

  while (p < end) {
    // parse one field
    Field f{p, 0};
    if (*p == '"') {
      ++p;
      const char* start = p;
      bool escaped = false;
      while (p < end) {
        if (*p == '"') {
          if (p + 1 < end && p[1] == '"') { escaped = true; p += 2; continue; }
          break;
        }
        ++p;
      }
      if (p >= end) { t->error = "unterminated quoted field"; return false; }
      if (!escaped) {
        f.ptr = start;
        f.len = p - start;
      } else {
        std::string s;
        s.reserve(p - start);
        for (const char* q = start; q < p; ++q) {
          s.push_back(*q);
          if (*q == '"') ++q;  // skip the doubled quote
        }
        t->arena.push_back(std::move(s));
        f.ptr = t->arena.back().data();
        f.len = (int64_t)t->arena.back().size();
      }
      ++p;  // closing quote
    } else {
      const char* start = p;
      while (p < end && *p != ',' && *p != '\n' && *p != '\r') ++p;
      f.ptr = start;
      f.len = p - start;
    }
    row.push_back(f);

    bool end_of_row = false;
    if (p < end && *p == ',') {
      ++p;
      // trailing comma then EOF => one empty final field
      if (p == end) { row.push_back(Field{p, 0}); end_of_row = true; }
    } else {
      if (p < end && *p == '\r') ++p;
      if (p < end && *p == '\n') ++p;
      end_of_row = true;
    }

    if (end_of_row) {
      if (!header_done) {
        ncols = (int)row.size();
        for (auto& h : row) names.emplace_back(h.ptr, (size_t)h.len);
        header_done = true;
      } else {
        if ((int)row.size() != ncols) {
          // tolerate a trailing blank line
          if (row.size() == 1 && row[0].len == 0 && p >= end) { row.clear(); break; }
          t->error = "row with " + std::to_string(row.size()) +
                     " fields, expected " + std::to_string(ncols);
          return false;
        }
        for (auto& f2 : row) fields->push_back(f2);
        ++t->num_rows;
      }
      row.clear();
    }
  }
  if (!row.empty()) {  // file ended without newline mid-row
    if ((int)row.size() == ncols) {
      for (auto& f2 : row) fields->push_back(f2);
      ++t->num_rows;
    } else if (!(row.size() == 1 && row[0].len == 0)) {
      t->error = "ragged final row";
      return false;
    }
  }
  if (ncols <= 0) { t->error = "empty file / no header"; return false; }
  t->cols.resize(ncols);
  for (int c = 0; c < ncols; ++c) t->cols[c].name = names[c];
  *ncols_out = ncols;
  return true;
}

// Arena-stable string_view substitute (pre-C++17-string_view-in-map safety).
struct SV {
  const char* p;
  int64_t n;
  bool operator==(const SV& o) const {
    return n == o.n && memcmp(p, o.p, (size_t)n) == 0;
  }
};
struct SVHash {
  size_t operator()(const SV& s) const {
    // FNV-1a
    size_t h = 1469598103934665603ull;
    for (int64_t i = 0; i < s.n; ++i) {
      h ^= (unsigned char)s.p[i];
      h *= 1099511628211ull;
    }
    return h;
  }
};

void infer_and_build(CsvTable* t, const std::vector<Field>& fields, int ncols) {
  const int64_t R = t->num_rows;
  for (int c = 0; c < ncols; ++c) {
    Column& col = t->cols[c];
    // pass 1: infer type
    bool all_int = true, all_num = true, any_null = false, any_val = false;
    for (int64_t r = 0; r < R; ++r) {
      const Field& f = fields[(size_t)r * ncols + c];
      if (is_null_field(f.ptr, f.len)) { any_null = true; continue; }
      any_val = true;
      int64_t iv;
      double dv;
      if (all_int && !parse_i64(f.ptr, f.len, &iv)) all_int = false;
      if (!all_int && all_num && !parse_f64(f.ptr, f.len, &dv)) {
        all_num = false;
        break;
      }
    }
    if (!any_val) { all_int = all_num = false; }  // all-null -> string/null col

    if (all_int && !any_null) {
      col.type = COL_INT64;
      col.i64.resize(R);
      for (int64_t r = 0; r < R; ++r) {
        const Field& f = fields[(size_t)r * ncols + c];
        parse_i64(f.ptr, f.len, &col.i64[r]);
      }
    } else if (all_num) {
      // ints-with-nulls also land here (pandas parity: NaN promotes to float)
      col.type = COL_DOUBLE;
      col.f64.resize(R);
      for (int64_t r = 0; r < R; ++r) {
        const Field& f = fields[(size_t)r * ncols + c];
        double dv;
        col.f64[r] = (!is_null_field(f.ptr, f.len) &&
                      parse_f64(f.ptr, f.len, &dv))
                         ? dv
                         : NAN;
      }
    } else {
      col.type = COL_STRING;
      col.codes.resize(R);
      std::unordered_map<SV, int32_t, SVHash> seen;
      std::vector<SV> uniq;
      std::vector<int32_t> tmp((size_t)R);
      for (int64_t r = 0; r < R; ++r) {
        const Field& f = fields[(size_t)r * ncols + c];
        if (is_null_field(f.ptr, f.len)) { tmp[r] = -1; continue; }
        SV sv{f.ptr, f.len};
        auto it = seen.find(sv);
        if (it == seen.end()) {
          int32_t id = (int32_t)uniq.size();
          seen.emplace(sv, id);
          uniq.push_back(sv);
          tmp[r] = id;
        } else {
          tmp[r] = it->second;
        }
      }
      // sorted-unique dictionary + rank remap (DimensionDict contract:
      // codes are ranks in the sorted value domain, so bound filters on
      // strings push down as integer ranges on codes)
      std::vector<int32_t> order((size_t)uniq.size());
      for (size_t i = 0; i < order.size(); ++i) order[i] = (int32_t)i;
      std::sort(order.begin(), order.end(), [&](int32_t a, int32_t b) {
        const SV &x = uniq[a], &y = uniq[b];
        int cmp = memcmp(x.p, y.p, (size_t)std::min(x.n, y.n));
        if (cmp != 0) return cmp < 0;
        return x.n < y.n;
      });
      std::vector<int32_t> rank((size_t)uniq.size());
      col.dict.resize(uniq.size());
      for (size_t i = 0; i < order.size(); ++i) {
        rank[(size_t)order[i]] = (int32_t)i;
        col.dict[i].assign(uniq[(size_t)order[i]].p,
                           (size_t)uniq[(size_t)order[i]].n);
      }
      for (int64_t r = 0; r < R; ++r)
        col.codes[r] = tmp[r] < 0 ? -1 : rank[(size_t)tmp[r]];
    }
  }
}

}  // namespace

extern "C" {

void* olap_csv_read(const char* path) {
  auto t = std::make_unique<CsvTable>();
  FILE* fp = fopen(path, "rb");
  if (!fp) {
    t->error = std::string("cannot open ") + path;
    return t.release();
  }
  fseek(fp, 0, SEEK_END);
  long sz = ftell(fp);
  fseek(fp, 0, SEEK_SET);
  t->buf.resize((size_t)sz);
  if (sz > 0 && fread(&t->buf[0], 1, (size_t)sz, fp) != (size_t)sz) {
    fclose(fp);
    t->error = "short read";
    return t.release();
  }
  fclose(fp);

  std::vector<Field> fields;
  int ncols = 0;
  if (!tokenize(t.get(), &fields, &ncols)) return t.release();
  infer_and_build(t.get(), fields, ncols);
  return t.release();
}

const char* olap_csv_error(void* h) {
  auto* t = (CsvTable*)h;
  return t->error.empty() ? nullptr : t->error.c_str();
}

long long olap_csv_num_rows(void* h) { return ((CsvTable*)h)->num_rows; }
int olap_csv_num_cols(void* h) { return (int)((CsvTable*)h)->cols.size(); }

const char* olap_csv_col_name(void* h, int c) {
  return ((CsvTable*)h)->cols[c].name.c_str();
}

int olap_csv_col_type(void* h, int c) {
  return (int)((CsvTable*)h)->cols[c].type;
}

void olap_csv_col_int64(void* h, int c, long long* out) {
  auto& col = ((CsvTable*)h)->cols[c];
  memcpy(out, col.i64.data(), col.i64.size() * sizeof(long long));
}

void olap_csv_col_double(void* h, int c, double* out) {
  auto& col = ((CsvTable*)h)->cols[c];
  memcpy(out, col.f64.data(), col.f64.size() * sizeof(double));
}

void olap_csv_col_codes(void* h, int c, int32_t* out) {
  auto& col = ((CsvTable*)h)->cols[c];
  memcpy(out, col.codes.data(), col.codes.size() * sizeof(int32_t));
}

int olap_csv_dict_size(void* h, int c) {
  return (int)((CsvTable*)h)->cols[c].dict.size();
}

const char* olap_csv_dict_value(void* h, int c, int i) {
  return ((CsvTable*)h)->cols[c].dict[i].c_str();
}

void olap_csv_free(void* h) { delete (CsvTable*)h; }

// ---------------------------------------------------------------------------
// Standalone dictionary encoder: char** values -> sorted dict + rank codes.
// Used to accelerate DimensionDict.build/encode for in-memory object columns.
// ---------------------------------------------------------------------------

struct DictResult {
  std::vector<int32_t> codes;
  std::vector<std::string> dict;
};

void* olap_dict_encode(const char** vals, long long n) {
  auto r = std::make_unique<DictResult>();
  r->codes.resize((size_t)n);
  std::unordered_map<SV, int32_t, SVHash> seen;
  std::vector<SV> uniq;
  std::vector<int32_t> tmp((size_t)n);
  for (long long i = 0; i < n; ++i) {
    if (vals[i] == nullptr) { tmp[i] = -1; continue; }
    SV sv{vals[i], (int64_t)strlen(vals[i])};
    auto it = seen.find(sv);
    if (it == seen.end()) {
      int32_t id = (int32_t)uniq.size();
      seen.emplace(sv, id);
      uniq.push_back(sv);
      tmp[i] = id;
    } else {
      tmp[i] = it->second;
    }
  }
  std::vector<int32_t> order((size_t)uniq.size());
  for (size_t i = 0; i < order.size(); ++i) order[i] = (int32_t)i;
  std::sort(order.begin(), order.end(), [&](int32_t a, int32_t b) {
    const SV &x = uniq[a], &y = uniq[b];
    int cmp = memcmp(x.p, y.p, (size_t)std::min(x.n, y.n));
    if (cmp != 0) return cmp < 0;
    return x.n < y.n;
  });
  std::vector<int32_t> rank((size_t)uniq.size());
  r->dict.resize(uniq.size());
  for (size_t i = 0; i < order.size(); ++i) {
    rank[(size_t)order[i]] = (int32_t)i;
    r->dict[i].assign(uniq[(size_t)order[i]].p, (size_t)uniq[(size_t)order[i]].n);
  }
  for (long long i = 0; i < n; ++i)
    r->codes[(size_t)i] = tmp[i] < 0 ? -1 : rank[(size_t)tmp[i]];
  return r.release();
}

void olap_dict_codes(void* h, int32_t* out) {
  auto* r = (DictResult*)h;
  memcpy(out, r->codes.data(), r->codes.size() * sizeof(int32_t));
}

int olap_dict_size(void* h) { return (int)((DictResult*)h)->dict.size(); }

const char* olap_dict_value(void* h, int i) {
  return ((DictResult*)h)->dict[i].c_str();
}

void olap_dict_free(void* h) { delete (DictResult*)h; }

int olap_abi_version() { return 1; }

}  // extern "C"
