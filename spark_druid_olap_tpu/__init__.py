"""spark_druid_olap_tpu — a TPU-native OLAP aggregation framework.

Brand-new implementation of the capabilities of tushargosavi/spark-druid-olap
(the Sparkline BI Accelerator — SQL plan rewriting into Druid-style OLAP
queries), redesigned TPU-first: the planner rewrites SQL/DataFrame aggregates
over star schemas into compact query specs, and — where the reference POSTed
those specs to an external Druid cluster — executes them as fused XLA/Pallas
aggregation kernels over dictionary-encoded columns in HBM, with partial
states merged across chips by ICI collectives.  See SURVEY.md for the layer
map and the provenance caveat (reference mount empty; expected-path citations
marked `[U]`).
"""

import jax as _jax

# Timestamps are int64 milliseconds (Druid convention).  With x64 disabled JAX
# silently truncates them to int32; enable it once here.  All hot-path arrays
# are explicitly f32/int32, so TPU compute is unaffected.
_jax.config.update("jax_enable_x64", True)

from .api import (  # noqa: E402,F401
    TPUOlapContext,
    default_context,
    explain,
    register_table,
    sql,
    table,
)
from .catalog.star import (  # noqa: E402,F401
    FunctionalDependency,
    StarRelationInfo,
    StarSchemaInfo,
)
from .config import SessionConfig, TableOptions  # noqa: E402,F401

__version__ = "0.1.0"
