"""Self-hosted telemetry: the `__sys` datasource (obs/, ISSUE 19).

Druid ships a `sys`/metrics-emitter surface so operators can ask the
database about itself IN SQL instead of standing up an external TSDB.
This module is that analog: a background sampler flushes the process
metrics registry (`obs.registry.get_registry().to_dict()`) into a
normal datasource named `__sys` through the SAME ingest/WAL tier user
appends take — journaled before publish, rolled up at `second`
granularity, flushed/compacted by the standard sweeps — so QPS, query
p99, breaker flips and scatter outcomes are one `SELECT ... FROM
__sys` away, with full history for as long as the store retains it.

Schema (long/narrow, one row per series per tick):

    ts      int64  sample wall-clock, ms      (time column)
    metric  str    family name; histograms flatten into suffixed
                   `_count/_sum/_p50/_p95/_p99` rows
    labels  str    comma-joined label VALUES of the child series
                   ("" for a bare family)
    kind    str    counter | gauge | histogram
    value   float  the sampled reading
    delta   float  reading minus the previous tick's reading for the
                   same (metric, labels) series — QPS is
                   `sum(delta) / interval` over the query counter,
                   no window function needed

Admission posture: ticks append via `ctx.ingest.append_rows` DIRECTLY
— not `ctx.append_rows`, not the HTTP ingest route — so telemetry
never opens a query trace, never queues behind the server admission
pool, and can keep flushing while the serving path is saturated (the
moment the history matters most).  The sampler thread is a daemon and
every tick is fault-isolated: a failed append logs, counts, and the
next tick proceeds.

Cardinality guard: one tick emits at most `max_series` rows (sorted
family order, deterministic truncation) and the drop count is visible
in `status()` and in `__sys` itself via the sampler's own
`sdol_sys_sampler_*` families.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from .registry import get_registry
from ..utils.log import get_logger

log = get_logger("obs.telemetry")

__all__ = ["SYS_TABLE", "SysSampler"]

SYS_TABLE = "__sys"

# histogram snapshot entries flatten into these suffixed series; the
# percentile rows sample as gauges (a delta of p99 is meaningless)
_HIST_FIELDS: Tuple[Tuple[str, str, str], ...] = (
    ("count", "_count", "counter"),
    ("sum_ms", "_sum", "counter"),
    ("p50", "_p50", "gauge"),
    ("p95", "_p95", "gauge"),
    ("p99", "_p99", "gauge"),
)


def _flatten(
    snapshot: Dict[str, dict]
) -> List[Tuple[str, str, str, float]]:
    """Registry `to_dict()` -> [(metric, labels, kind, value)] in
    deterministic (family, labels) order."""
    out: List[Tuple[str, str, str, float]] = []
    for name in sorted(snapshot):
        fam = snapshot[name]
        kind = str(fam.get("type", "gauge"))
        values = fam.get("values") or {}
        for labels in sorted(values):
            v = values[labels]
            if isinstance(v, dict):
                for field, suffix, fkind in _HIST_FIELDS:
                    fv = v.get(field)
                    if fv is None:
                        continue
                    out.append(
                        (name + suffix, labels, fkind, float(fv))
                    )
            else:
                try:
                    out.append((name, labels, kind, float(v)))
                except (TypeError, ValueError):
                    continue
    return out


class SysSampler:
    """Background registry -> `__sys` flusher.  `start()` spawns the
    daemon tick loop; `sample_once()` is the synchronous single tick
    (tests and `tools/obs_dump.py --sys` call it directly)."""

    def __init__(
        self,
        ctx,
        interval_s: float = 5.0,
        max_series: int = 512,
    ):
        self.ctx = ctx
        self.interval_s = max(0.1, float(interval_s))
        self.max_series = int(max_series)
        self._prev: Dict[Tuple[str, str], float] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()
        self.ticks = 0
        self.rows_appended = 0
        self.rows_dropped = 0
        self.errors = 0
        self.last_tick_ms = 0.0
        self.last_error = ""
        reg = get_registry()
        self._m_rows = reg.counter(
            "sdol_sys_sampler_rows_total",
            "rows appended to __sys by the telemetry sampler",
        )
        self._m_dropped = reg.counter(
            "sdol_sys_sampler_dropped_total",
            "series dropped by the __sys per-tick cardinality cap",
        )
        self._m_errors = reg.counter(
            "sdol_sys_sampler_errors_total",
            "failed __sys sampler ticks (fault-isolated, loop continues)",
        )

    # -- registration --------------------------------------------------------

    def _ensure_table(self, seed_cols: Dict[str, np.ndarray]) -> None:
        """First tick registers `__sys` (idempotent thereafter) with the
        seed batch itself — `register_table` needs rows, and this way
        the very first sample is queryable too.  Rollup at `second`
        granularity: a re-sampled second folds instead of duplicating,
        and the WAL journals the already-rolled batch."""
        if self.ctx.catalog.get(SYS_TABLE) is not None:
            return
        self.ctx.register_table(
            SYS_TABLE,
            seed_cols,
            dimensions=["metric", "labels", "kind"],
            metrics=["value", "delta"],
            time_column="ts",
            rows_per_segment=1 << 16,
            rollup_granularity="second",
        )

    # -- sampling ------------------------------------------------------------

    def _tick_cols(self) -> Tuple[Dict[str, np.ndarray], int]:
        series = _flatten(get_registry().to_dict())
        dropped = 0
        if len(series) > self.max_series:
            dropped = len(series) - self.max_series
            series = series[: self.max_series]
        now_ms = int(time.time() * 1e3)
        metric: List[str] = []
        labels: List[str] = []
        kind: List[str] = []
        value: List[float] = []
        delta: List[float] = []
        for name, lab, k, v in series:
            key = (name, lab)
            prev = self._prev.get(key)
            metric.append(name)
            labels.append(lab)
            kind.append(k)
            value.append(v)
            delta.append(v - prev if prev is not None else 0.0)
            self._prev[key] = v
        cols = {
            "ts": np.full(len(metric), now_ms, dtype=np.int64),
            "metric": np.array(metric, dtype=object),
            "labels": np.array(labels, dtype=object),
            "kind": np.array(kind, dtype=object),
            "value": np.asarray(value, dtype=np.float64),
            "delta": np.asarray(delta, dtype=np.float64),
        }
        return cols, dropped

    def sample_once(self) -> int:
        """One synchronous tick: snapshot -> flatten -> append.  Returns
        the row count appended (0 on a fault-isolated failure)."""
        t0 = time.perf_counter()
        with self._lock:
            try:
                cols, dropped = self._tick_cols()
                n = int(len(cols["ts"]))
                if n == 0:
                    return 0
                fresh = self.ctx.catalog.get(SYS_TABLE) is None
                self._ensure_table(cols)
                if not fresh:
                    # separate admission: straight into the ingest tier,
                    # no query trace, no server admission queue (the
                    # first tick's batch already seeded registration)
                    self.ctx.ingest.append_rows(SYS_TABLE, cols)
                self.ticks += 1
                self.rows_appended += n
                self.rows_dropped += dropped
                self._m_rows.inc(n)
                if dropped:
                    self._m_dropped.inc(dropped)
                self.last_tick_ms = (time.perf_counter() - t0) * 1e3
                return n
            except Exception as e:  # fault-ok: telemetry never takes
                # down the process it observes
                self.errors += 1
                self.last_error = f"{type(e).__name__}: {e}"
                self._m_errors.inc()
                log.warning("__sys sampler tick failed: %s", e)
                return 0

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "SysSampler":
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()

        def run() -> None:
            while not self._stop.wait(self.interval_s):
                self.sample_once()

        self._thread = threading.Thread(
            target=run, name="sdol-sys-sampler", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
        self._thread = None

    def status(self) -> Dict[str, Any]:
        return {
            "table": SYS_TABLE,
            "running": bool(self._thread and self._thread.is_alive()),
            "interval_s": self.interval_s,
            "max_series": self.max_series,
            "ticks": self.ticks,
            "rows_appended": self.rows_appended,
            "rows_dropped": self.rows_dropped,
            "errors": self.errors,
            "last_error": self.last_error,
            "last_tick_ms": round(self.last_tick_ms, 3),
            "tracked_series": len(self._prev),
        }
