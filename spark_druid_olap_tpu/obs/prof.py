"""Performance attribution layer (ISSUE 9 tentpole).

The span tree (obs/trace.py) records WHEN phases ran; this module makes
the numbers HONEST and turns them into per-query cost receipts:

  * **Honest device timing** — JAX dispatch is asynchronous, so a
    wall-clock span around `segment_dispatch` measures enqueue time,
    not device time.  `dispatch_sync`/`fetch_sync` are sampling-gated
    sync points (`SessionConfig.prof_sample_rate`): on a SAMPLED query
    they `block_until_ready` the dispatched state and split the
    enclosing span into `enqueue_ms` vs `device_ms` attrs; on an
    unsampled query they are a single contextvar read — ZERO added
    syncs, so the overlap the executors engineered is never destroyed
    by default.
  * **Transfer + residency accounting** — every h2d move records bytes
    and effective MB/s into `sdol_h2d_link_mbps` (the link-bound claim
    becomes a scrapeable histogram); the engine's residency cache
    exports per-datasource resident-bytes gauges and eviction counters.
  * **Program-cache family attribution** — hit/miss counters and
    compile-time totals per tagged program family (`fused`,
    `fused-batch`, `sparse`, `adaptive-presence`, ...), so "what is
    recompiling and why" is a registry query, not archaeology.
  * **Per-query cost receipts** — `build_receipt` folds a finished span
    tree into {device_ms, host_ms, transfer_ms, unattributed_ms, ...}
    by summing each span's EXCLUSIVE time (duration minus children)
    into a bucket by span name.  Only the root `query` span's exclusive
    time is unattributed, so `device + host + transfer` vs `wall` is a
    real claim about lifecycle coverage, not an identity.  Receipts are
    stamped into the trace doc (served at `/druid/v2/trace/{id}`),
    `QueryMetrics.receipt`, `df.attrs["receipt"]`, and — on sampled
    queries — the `X-Druid-Response-Context` header.
  * **Workload profiler** — a process-wide rolling window of finished
    queries behind `GET /status/profile`: top-K by device time,
    per-family compile totals, per-lane SLO burn-rate against the
    `lane_*_slo_ms` latency targets.

Accounting convention: compile time happens INSIDE the first dispatch
span, so `device_ms` includes it; the receipt reports `compile_ms`
separately as attribution detail, never as an additive term.
"""

from __future__ import annotations

import contextvars
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from ..utils.log import get_logger
from .registry import bounded_label, get_registry
from .trace import current_query_id, current_span, current_trace

log = get_logger("obs.prof")

# effective host->device MB/s per transfer: spans the 45 MB/s tunnel
# floor the re-anchor note names up through PCIe-class links
LINK_MBPS_BUCKETS = (
    1.0, 5.0, 10.0, 25.0, 45.0, 75.0, 150.0, 500.0,
    1000.0, 5000.0, 20000.0,
)

# span-name -> receipt bucket.  Device spans either block on device work
# (device_fetch, collective_merge) or — on a sampled query — are split
# honestly by the sync helpers; h2d is the transfer bucket; every OTHER
# span's exclusive time is host work.  The root `query` span's exclusive
# time stays unattributed (the coverage-claim denominator).
DEVICE_SPANS = frozenset(
    {
        "segment_dispatch",
        "device_fetch",
        "sparse_dispatch",
        "adaptive_probe",
        "stream_chunk",
        "collective_merge",
    }
)
TRANSFER_SPANS = frozenset({"h2d"})
# prefetch spans measure ISSUE time of transfers overlapped behind live
# compute (exec/pipeline.py): they are deliberately NOT transfer stall —
# the overlap-efficiency denominator counts only foreground h2d time the
# dispatch loop actually waited behind
PREFETCH_SPANS = frozenset({"prefetch"})
# arena assembly (exec/arena.py): host-side stacking + placement issue of
# the segment-stacked layout — its own receipt bucket so the one-dispatch
# path's build cost is visible apart from generic host work (its child
# h2d spans still land in the transfer bucket)
ARENA_SPANS = frozenset({"arena_build"})
# cluster tier (cluster/, ISSUE 16): the broker's scatter span measures
# replica RPCs in flight (its per-reply `rpc` events carry the
# per-historical latency the receipt's cluster section aggregates);
# gather is decode + coverage accounting; cluster_merge is the ⊕ fold of
# replica states.  Each gets its own receipt bucket so a slow cluster
# query attributes to the wire, the decode, or the merge — not to
# generic host time.
SCATTER_SPANS = frozenset({"scatter"})
GATHER_SPANS = frozenset({"gather"})
CLUSTER_MERGE_SPANS = frozenset({"cluster_merge"})
# per-attempt RPC spans (ISSUE 19): cluster_rpc spans run CONCURRENTLY
# on pool threads under the one scatter span, so they are an OVERLAY on
# the scatter wall, not a partition of it — their time (and the remote
# subtrees grafted beneath them, which measure on the REMOTE clock) is
# excluded from the additive local buckets and folded into the
# per-historical `cluster.nodes` section instead
CLUSTER_RPC_SPANS = frozenset({"cluster_rpc"})
ROOT_SPAN = "query"

# device LAUNCH spans — the receipt's `dispatch_count` (ISSUE 14): how
# many host->device program launches served this query.  The arena path's
# whole point is driving this from O(segments) to O(1); device_fetch is a
# read-back, not a launch, so it does not count.
DISPATCH_SPANS = frozenset(
    {
        "segment_dispatch",
        "sparse_dispatch",
        "adaptive_probe",
        "stream_chunk",
        "collective_merge",
    }
)


class ProfScope:
    """Per-query attribution accumulators, armed by the tracer for the
    lifetime of one query trace.  `sampled` gates the sync helpers;
    the cheap counters (cache outcomes, transfer bytes) collect on
    EVERY traced query.  Contextvar-confined like the trace itself
    (fresh threads see no scope), so the mutators need no lock."""

    __slots__ = (
        "sampled",
        "lane",
        "syncs",
        "transfer_ms",
        "transfer_bytes",
        "prefetch_ms",
        "prefetch_bytes",
        "compiles",
        "compile_ms",
        "residency_hits",
        "residency_misses",
        "program_cache",
        "result_cache",
        "fused_batch",
        "pending_family",
    )

    def __init__(self, sampled: bool = False):
        self.sampled = bool(sampled)
        self.lane = ""
        self.syncs = 0
        self.transfer_ms = 0.0
        self.transfer_bytes = 0
        self.prefetch_ms = 0.0
        self.prefetch_bytes = 0
        self.compiles = 0
        self.compile_ms = 0.0
        self.residency_hits = 0
        self.residency_misses = 0
        # family -> [hits, misses]
        self.program_cache: Dict[str, List[int]] = {}
        self.result_cache: Optional[str] = None  # "hit"/"delta" when served
        self.fused_batch = 0
        self.pending_family: Optional[str] = None


_active: contextvars.ContextVar[Optional[ProfScope]] = contextvars.ContextVar(
    "sdol_active_prof", default=None
)


def current_scope() -> Optional[ProfScope]:
    return _active.get()


def activate(scope: ProfScope):
    """INTERNAL (tracer lifecycle): arm `scope` for this context."""
    return _active.set(scope)


def deactivate(token) -> None:
    _active.reset(token)


def profiled() -> bool:
    """Is the CURRENT query sampled for honest device timing?"""
    ps = _active.get()
    return ps is not None and ps.sampled


class RateSampler:
    """Deterministic rate sampler: an accumulator advances by `rate`
    per query and fires on integer crossings — rate 1.0 samples every
    query, 0.25 every fourth, 0 never.  Deterministic (no wall-clock or
    RNG) so tests and benches can reason about exactly which queries
    paid a sync."""

    def __init__(self, rate: float = 0.0):
        self.rate = float(rate)
        self._acc = 0.0
        self._force = False
        self._lock = threading.Lock()

    def force_next(self) -> None:
        with self._lock:
            self._force = True

    def take(self) -> bool:
        with self._lock:
            if self._force:
                self._force = False
                return True
            r = self.rate
            if r <= 0:
                return False
            if r >= 1.0:
                return True
            self._acc += r
            if self._acc >= 1.0:
                self._acc -= 1.0
                return True
            return False


# ---------------------------------------------------------------------------
# Sampling-gated sync points (honest device timing)
# ---------------------------------------------------------------------------


def dispatch_sync(result, t_enqueue: float):
    """Called by an executor right after an async program dispatch, with
    the pre-dispatch clock reading.  Sampled query: block until the
    dispatched state is device-complete and split the enclosing span
    into `enqueue_ms` vs `device_ms`.  Unsampled: return `result`
    untouched — one contextvar read, no sync, overlap preserved."""
    ps = _active.get()
    if ps is None or not ps.sampled:
        return result
    import jax

    t1 = time.perf_counter()
    jax.block_until_ready(result)
    t2 = time.perf_counter()
    ps.syncs += 1
    s = current_span()
    if s is not None:
        s.attrs["enqueue_ms"] = round((t1 - t_enqueue) * 1e3, 3)
        s.attrs["device_ms"] = round((t2 - t1) * 1e3, 3)
    return result


def fetch_sync(tree):
    """Called just before a blocking `device_get`: on a sampled query,
    block first so the fetch span separates device-wait from the host
    copy (`device_wait_ms` attr).  No-op otherwise."""
    ps = _active.get()
    if ps is None or not ps.sampled:
        return tree
    import jax

    t0 = time.perf_counter()
    jax.block_until_ready(tree)
    ps.syncs += 1
    s = current_span()
    if s is not None:
        s.attrs["device_wait_ms"] = round(
            (time.perf_counter() - t0) * 1e3, 3
        )
    return tree


def transfer_sync(arr):
    """On a sampled query, block on a just-issued h2d placement so the
    caller's elapsed measurement is the real link time, not the enqueue.
    No-op otherwise (the unsampled measurement is the enqueue-observed
    'effective' rate — still recorded, labeled by the sampling bit in
    the receipt)."""
    ps = _active.get()
    if ps is None or not ps.sampled:
        return arr
    import jax

    jax.block_until_ready(arr)
    ps.syncs += 1
    return arr


# ---------------------------------------------------------------------------
# Transfer / residency / program-cache accounting
# ---------------------------------------------------------------------------


def record_h2d(nbytes: int, seconds: float, prefetched: bool = False) -> None:
    """One host->device move: effective MB/s into the link-utilization
    histogram (exemplared with the query id) + the scope's transfer
    accumulators.  This is what turns 'the rollup is link-bound at
    45 MB/s' from a postmortem into a scrapeable fact.

    `prefetched` moves were issued by the transfer pipeline (exec/
    pipeline.py) BEHIND live compute: they accumulate into the scope's
    prefetch counters, never into transfer stall — the
    overlap-efficiency denominator counts only foreground waits.  They
    are also EXCLUDED from the link histogram: a prefetched put is
    never synced, so its measured window is the async enqueue
    (~microseconds) and nbytes/dt would observe absurd multi-GB/s
    samples — with the pipeline on by default, the documented '45 MB/s
    floor' fact would drown in enqueue noise."""
    ps = _active.get()
    if not prefetched:
        mbps = nbytes / max(seconds, 1e-9) / 1e6
        get_registry().histogram(
            "sdol_h2d_link_mbps",
            "effective host->device link utilization per transfer (MB/s)",
            buckets=LINK_MBPS_BUCKETS,
        ).observe(mbps, exemplar=current_query_id() or None)
    if ps is not None:
        if prefetched:
            ps.prefetch_ms += seconds * 1e3
            ps.prefetch_bytes += int(nbytes)
        else:
            ps.transfer_ms += seconds * 1e3
            ps.transfer_bytes += int(nbytes)


def record_resident(datasource: str, bytes_now: int) -> None:
    """Publish a datasource's current resident-bytes (direction 4's
    residency-aware scheduling needs this denominator)."""
    ds = bounded_label("residency_datasource", datasource or "unknown")
    get_registry().gauge(
        "sdol_resident_bytes",
        "device-resident segment bytes, by datasource",
        labels=("datasource",),
    ).labels(datasource=ds).set(bytes_now)


def record_eviction(datasource: str, n: int = 1) -> None:
    ds = bounded_label("residency_datasource", datasource or "unknown")
    get_registry().counter(
        "sdol_residency_evictions_total",
        "residency-cache evictions under byte-budget pressure, "
        "by datasource",
        labels=("datasource",),
    ).labels(datasource=ds).inc(n)


def note_residency(hit: bool) -> None:
    ps = _active.get()
    if ps is None:
        return
    if hit:
        ps.residency_hits += 1
    else:
        ps.residency_misses += 1


def note_program_cache(family: str, hit: bool) -> None:
    """One program-cache lookup under its tagged key family."""
    fam = bounded_label("program_family", family or "unknown")
    get_registry().counter(
        "sdol_program_cache_total",
        "compiled-program cache lookups, by tagged key family / outcome",
        labels=("family", "outcome"),
    ).labels(family=fam, outcome="hit" if hit else "miss").inc()
    ps = _active.get()
    if ps is not None:
        c = ps.program_cache.setdefault(family, [0, 0])
        c[0 if hit else 1] += 1
        if not hit:
            ps.pending_family = family


def note_compile(ms: float, family: Optional[str] = None) -> None:
    """First-trace/compile cost of one program build, attributed to the
    family whose cache miss triggered it (the scope remembers the last
    missed family when the caller cannot name it)."""
    ps = _active.get()
    if family is None and ps is not None:
        family = ps.pending_family
    fam = bounded_label("program_family", family or "unknown")
    reg = get_registry()
    reg.counter(
        "sdol_compiles_total",
        "program trace+compile events, by program-cache family",
        labels=("family",),
    ).labels(family=fam).inc()
    reg.counter(
        "sdol_compile_ms_total",
        "cumulative trace+compile milliseconds, by program-cache family",
        labels=("family",),
    ).labels(family=fam).inc(max(0.0, float(ms)))
    if ps is not None:
        ps.compiles += 1
        ps.compile_ms += max(0.0, float(ms))


def note_result_cache(outcome: str) -> None:
    ps = _active.get()
    if ps is not None:
        ps.result_cache = outcome


def note_fusion(batch: int) -> None:
    ps = _active.get()
    if ps is not None:
        ps.fused_batch = max(ps.fused_batch, int(batch))


def note_lane(lane: str) -> None:
    ps = _active.get()
    if ps is not None and lane:
        ps.lane = str(lane)


# ---------------------------------------------------------------------------
# Receipts
# ---------------------------------------------------------------------------


def _is_remote(node: dict) -> bool:
    """A grafted remote subtree root (broker-side clocks do not apply)."""
    return bool((node.get("attrs") or {}).get("remote"))


def _is_overlay(node: dict) -> bool:
    """Spans excluded from the local timeline partition: concurrent
    cluster_rpc attempts and grafted remote subtrees."""
    return str(node.get("name", "")) in CLUSTER_RPC_SPANS or _is_remote(
        node
    )


def _walk_exclusive(node: dict, acc: Dict[str, float], depth: int) -> None:
    if _is_overlay(node):
        # concurrent overlay / remote clock: handled by
        # _walk_cluster_nodes into per-node attribution, never the
        # additive local buckets (their sum could exceed the wall)
        return
    dur = float(node.get("duration_ms", 0.0))
    children = [
        c for c in (node.get("children") or ()) if not _is_overlay(c)
    ]
    child_sum = sum(float(c.get("duration_ms", 0.0)) for c in children)
    excl = max(0.0, dur - child_sum)
    name = str(node.get("name", ""))
    if name in DISPATCH_SPANS:
        acc["dispatch_count"] += 1
    if depth == 0 and name == ROOT_SPAN:
        acc["unattributed"] += excl
    elif name in DEVICE_SPANS:
        acc["device"] += excl
    elif name in TRANSFER_SPANS:
        acc["transfer"] += excl
    elif name in PREFETCH_SPANS:
        acc["prefetch"] += excl
    elif name in ARENA_SPANS:
        acc["arena_build"] += excl
    elif name in SCATTER_SPANS:
        acc["scatter"] += excl
    elif name in GATHER_SPANS:
        acc["gather"] += excl
    elif name in CLUSTER_MERGE_SPANS:
        acc["cluster_merge"] += excl
    else:
        acc["host"] += excl
    for c in children:
        _walk_exclusive(c, acc, depth + 1)


def _fold_remote_buckets(graft: dict) -> Dict[str, float]:
    """Per-historical device/transfer/host attribution of ONE grafted
    remote subtree.  The remote receipt (riding inside the graft root)
    is authoritative when present; otherwise the subtree folds through
    the same bucket maps — remote spans use the same registered names."""
    rc = graft.get("receipt")
    if isinstance(rc, dict):
        return {
            "device_ms": float(rc.get("device_ms", 0.0) or 0.0),
            "transfer_ms": float(rc.get("transfer_ms", 0.0) or 0.0),
            "host_ms": float(rc.get("host_ms", 0.0) or 0.0),
            "remote_wall_ms": float(rc.get("wall_ms", 0.0) or 0.0),
        }
    acc = {
        "device": 0.0, "transfer": 0.0, "prefetch": 0.0, "host": 0.0,
        "arena_build": 0.0, "unattributed": 0.0, "dispatch_count": 0,
        "scatter": 0.0, "gather": 0.0, "cluster_merge": 0.0,
    }
    clean = dict(graft)
    attrs = dict(clean.get("attrs") or {})
    attrs.pop("remote", None)
    clean["attrs"] = attrs
    _walk_exclusive(clean, acc, 0)
    return {
        "device_ms": round(acc["device"], 3),
        "transfer_ms": round(acc["transfer"], 3),
        "host_ms": round(acc["host"], 3),
        "remote_wall_ms": float(graft.get("duration_ms", 0.0) or 0.0),
    }


def _fold_rpc_span(c: dict, nodes: Dict[str, Dict[str, Any]]) -> None:
    """One `cluster_rpc` span (ISSUE 19) into its node's bucket: attempt
    count/latency/outcome plus the grafted remote buckets.  `untraced`
    counts grafts that degraded to a stub (their receipt, when it
    survived separately, still folds)."""
    attrs = c.get("attrs") or {}
    nid = str(attrs.get("node", "?"))
    b = nodes.setdefault(
        nid, {"ms": 0.0, "rpcs": 0, "ok": 0, "failed": 0, "segments": 0},
    )
    b["rpcs"] += 1
    ms = float(attrs.get("ms", c.get("duration_ms", 0.0)) or 0.0)
    b["ms"] = round(b["ms"] + ms, 3)
    if attrs.get("outcome") == "ok":
        b["ok"] += 1
        b["segments"] += int(attrs.get("segments", 0) or 0)
    else:
        b["failed"] += 1
    if attrs.get("hedge"):
        b["hedged"] = int(b.get("hedged", 0)) + 1
    for g in c.get("children") or ():
        if not _is_remote(g):
            continue
        if (g.get("attrs") or {}).get("untraced"):
            b["untraced"] = int(b.get("untraced", 0)) + 1
            if not isinstance(g.get("receipt"), dict):
                continue
        for k, v in _fold_remote_buckets(g).items():
            b[k] = round(float(b.get(k, 0.0)) + float(v), 3)


def _walk_cluster_nodes(node: dict, nodes: Dict[str, Dict[str, Any]]):
    """Aggregate the scatter span's per-attempt `cluster_rpc` child
    spans — plus legacy per-reply `rpc` events (lost replica groups
    still mark this way) — into per-historical receipt buckets:
    {node -> {ms, rpcs, ok, failed, segments, device_ms, transfer_ms,
    host_ms, remote_wall_ms, ...}}.  One bucket per historical the
    query touched — the obs_dump table renders these as the per-node
    attribution rows."""
    if str(node.get("name", "")) in SCATTER_SPANS:
        for e in node.get("events") or ():
            if e.get("name") != "rpc":
                continue
            attrs = e.get("attrs") or {}
            nid = str(attrs.get("node", "?"))
            b = nodes.setdefault(
                nid, {"ms": 0.0, "rpcs": 0, "ok": 0, "failed": 0,
                      "segments": 0},
            )
            b["rpcs"] += 1
            b["ms"] = round(b["ms"] + float(attrs.get("ms", 0.0)), 3)
            if attrs.get("outcome") == "ok":
                b["ok"] += 1
                b["segments"] += int(attrs.get("segments", 0))
            else:
                b["failed"] += 1
        for c in node.get("children") or ():
            if str(c.get("name", "")) in CLUSTER_RPC_SPANS:
                _fold_rpc_span(c, nodes)
    for c in node.get("children") or ():
        if _is_overlay(c):
            continue
        _walk_cluster_nodes(c, nodes)


def build_receipt(
    trace_doc: dict, scope: Optional[ProfScope] = None
) -> dict:
    """Fold one trace document (obs.trace.QueryTrace.to_dict shape) into
    a cost receipt.  Pure function of the doc + scope counters, so it
    can run live (mid-query, provisional span ends) or at trace close."""
    acc = {
        "device": 0.0, "transfer": 0.0, "prefetch": 0.0, "host": 0.0,
        "arena_build": 0.0, "unattributed": 0.0, "dispatch_count": 0,
        "scatter": 0.0, "gather": 0.0, "cluster_merge": 0.0,
    }
    cluster_nodes: Dict[str, Dict[str, Any]] = {}
    root = trace_doc.get("spans")
    if isinstance(root, dict):
        _walk_exclusive(root, acc, 0)
        _walk_cluster_nodes(root, cluster_nodes)
    wall = float(trace_doc.get("total_ms") or 0.0)
    # overlap efficiency (ROADMAP direction 4's success metric):
    # device-busy time over (device-busy + transfer-stall).  Stall is the
    # FOREGROUND h2d time the dispatch loop waited behind; prefetch issue
    # time is excluded — those transfers ran behind live compute, which
    # is exactly what the metric credits.  1.0 when nothing was measured
    # (a fully-resident or dispatch-free query has no stall to hide).
    busy_stall = acc["device"] + acc["transfer"]
    receipt: Dict[str, Any] = {
        "query_id": trace_doc.get("query_id", ""),
        "wall_ms": round(wall, 3),
        "device_ms": round(acc["device"], 3),
        "host_ms": round(acc["host"], 3),
        "transfer_ms": round(acc["transfer"], 3),
        "prefetch_ms": round(acc["prefetch"], 3),
        "arena_build_ms": round(acc["arena_build"], 3),
        "unattributed_ms": round(acc["unattributed"], 3),
        # device program launches this query paid (DISPATCH_SPANS): the
        # number the one-dispatch arena acceptance criterion reads
        "dispatch_count": int(acc["dispatch_count"]),
        "overlap_efficiency": (
            round(acc["device"] / busy_stall, 4) if busy_stall > 0 else 1.0
        ),
        "sampled": bool(scope.sampled) if scope is not None else False,
    }
    # cluster queries only: scatter/gather/merge attribution + the
    # per-historical buckets.  Absent on single-process receipts so the
    # existing lean shape is unchanged.
    if cluster_nodes or acc["scatter"] or acc["gather"] or (
        acc["cluster_merge"]
    ):
        receipt["scatter_ms"] = round(acc["scatter"], 3)
        receipt["gather_ms"] = round(acc["gather"], 3)
        receipt["cluster_merge_ms"] = round(acc["cluster_merge"], 3)
        receipt["cluster"] = {"nodes": cluster_nodes}
    if scope is not None:
        cache: Dict[str, Any] = {
            "result_cache": scope.result_cache,
            "fused_batch": scope.fused_batch,
            "residency": {
                "hits": scope.residency_hits,
                "misses": scope.residency_misses,
            },
            "program_cache": {
                fam: {"hits": c[0], "misses": c[1]}
                for fam, c in sorted(scope.program_cache.items())
            },
        }
        receipt.update(
            transfer_bytes=scope.transfer_bytes,
            prefetch_bytes=scope.prefetch_bytes,
            transfer_mb_per_s=(
                round(
                    scope.transfer_bytes
                    / max(scope.transfer_ms, 1e-9)
                    / 1e3,
                    1,
                )
                if scope.transfer_bytes
                else 0.0
            ),
            compiles=scope.compiles,
            compile_ms=round(scope.compile_ms, 3),
            syncs=scope.syncs,
            lane=scope.lane,
            cache=cache,
        )
    return receipt


def live_receipt() -> Optional[dict]:
    """Receipt of the ACTIVE query so far (unfinished spans measured to
    'now' under the tracer's own clock) — what df.attrs, QueryMetrics,
    and the response-context header carry; the trace doc gets the final
    recomputation at close.  None outside a trace."""
    tr = current_trace()
    if tr is None:
        return None
    try:
        return build_receipt(tr.to_dict_live(), _active.get())
    except Exception:  # fault-ok: attribution must never fail a query
        log.warning("live receipt build failed", exc_info=True)
        return None


# ---------------------------------------------------------------------------
# Workload profiler (GET /status/profile)
# ---------------------------------------------------------------------------


class WorkloadProfiler:
    """Process-wide rolling window of finished-query observations.
    Like the metrics registry it survives context rebuilds; the tracer
    feeds it one observation per finished trace."""

    def __init__(self, capacity: int = 1024):
        self._lock = threading.Lock()
        self._entries: deque = deque(maxlen=max(16, int(capacity)))

    def observe(self, trace_doc: dict, scope: Optional[ProfScope]) -> None:
        rc = trace_doc.get("receipt") or {}
        entry = {
            "t": time.monotonic(),
            "query_id": trace_doc.get("query_id", ""),
            "query_type": trace_doc.get("query_type", ""),
            "lane": (scope.lane if scope is not None else "") or "",
            "wall_ms": float(rc.get("wall_ms", trace_doc.get("total_ms", 0.0)) or 0.0),
            "device_ms": float(rc.get("device_ms", 0.0) or 0.0),
            "transfer_ms": float(rc.get("transfer_ms", 0.0) or 0.0),
            "compiles": int(rc.get("compiles", 0) or 0),
            "sampled": bool(rc.get("sampled", False)),
        }
        with self._lock:
            self._entries.append(entry)

    def window(self, window_s: float) -> List[dict]:
        cutoff = time.monotonic() - max(1e-3, float(window_s))
        with self._lock:
            return [e for e in self._entries if e["t"] >= cutoff]

    def profile(
        self,
        window_s: float = 300.0,
        top_k: int = 10,
        slo_ms: Optional[Dict[str, float]] = None,
    ) -> dict:
        """Rolling-window workload profile: top-K queries by device
        time, per-lane SLO burn-rate (fraction of the lane's queries
        whose wall exceeded its latency target), and window totals."""
        now = time.monotonic()
        entries = self.window(window_s)
        top = sorted(
            entries, key=lambda e: e["device_ms"], reverse=True
        )[: max(1, int(top_k))]
        lanes: Dict[str, dict] = {}
        for e in entries:
            lane = e["lane"] or "unclassified"
            d = lanes.setdefault(
                lane, {"queries": 0, "over_slo": 0, "wall_ms_sum": 0.0}
            )
            d["queries"] += 1
            d["wall_ms_sum"] += e["wall_ms"]
            target = (slo_ms or {}).get(lane)
            if target is not None and target > 0 and e["wall_ms"] > target:
                d["over_slo"] += 1
        for lane, d in lanes.items():
            target = (slo_ms or {}).get(lane)
            d["slo_ms"] = target
            d["burn_rate"] = (
                round(d["over_slo"] / d["queries"], 4)
                if d["queries"] and target
                else 0.0
            )
            d["mean_wall_ms"] = round(
                d["wall_ms_sum"] / max(1, d["queries"]), 3
            )
            del d["wall_ms_sum"]
        return {
            "window_s": float(window_s),
            "queries_observed": len(entries),
            "lanes": lanes,
            "top_device": [
                {
                    "query_id": e["query_id"],
                    "query_type": e["query_type"],
                    "lane": e["lane"] or "unclassified",
                    "device_ms": round(e["device_ms"], 3),
                    "wall_ms": round(e["wall_ms"], 3),
                    "sampled": e["sampled"],
                    "age_s": round(now - e["t"], 1),
                }
                for e in top
            ],
        }


_profiler: Optional[WorkloadProfiler] = None
_profiler_lock = threading.Lock()


def workload_profiler() -> WorkloadProfiler:
    global _profiler
    if _profiler is None:
        with _profiler_lock:
            if _profiler is None:
                _profiler = WorkloadProfiler()
    return _profiler


def _family_totals() -> Dict[str, dict]:
    """Per-program-family compile totals + hit/miss counts from the
    process registry (the /status/profile 'what is recompiling' table)."""
    reg = get_registry()
    out: Dict[str, dict] = {}
    for key, v in reg.counter(
        "sdol_program_cache_total",
        "compiled-program cache lookups, by tagged key family / outcome",
        labels=("family", "outcome"),
    ).snapshot().items():
        fam, _, outcome = key.partition(",")
        d = out.setdefault(
            fam, {"hits": 0, "misses": 0, "compiles": 0, "compile_ms": 0.0}
        )
        d["hits" if outcome == "hit" else "misses"] += int(v)
    for key, v in reg.counter(
        "sdol_compiles_total",
        "program trace+compile events, by program-cache family",
        labels=("family",),
    ).snapshot().items():
        out.setdefault(
            key, {"hits": 0, "misses": 0, "compiles": 0, "compile_ms": 0.0}
        )["compiles"] = int(v)
    for key, v in reg.counter(
        "sdol_compile_ms_total",
        "cumulative trace+compile milliseconds, by program-cache family",
        labels=("family",),
    ).snapshot().items():
        out.setdefault(
            key, {"hits": 0, "misses": 0, "compiles": 0, "compile_ms": 0.0}
        )["compile_ms"] = round(float(v), 3)
    return out


def profile_doc(
    config=None,
    top_k: Optional[int] = None,
    window_s: Optional[float] = None,
) -> dict:
    """The `GET /status/profile` document."""
    cfg = config
    k = int(top_k or getattr(cfg, "profile_top_k", 10) or 10)
    win = float(window_s or getattr(cfg, "profile_window_s", 300.0) or 300.0)
    slo = {
        "interactive": float(
            getattr(cfg, "lane_interactive_slo_ms", 0.0) or 0.0
        ),
        "heavy": float(getattr(cfg, "lane_heavy_slo_ms", 0.0) or 0.0),
    }
    doc = workload_profiler().profile(window_s=win, top_k=k, slo_ms=slo)
    doc["compile_families"] = _family_totals()
    plan = get_registry().counter(
        "sdol_plan_cache_total",
        "decoded-QuerySpec plan cache on the wire path, by outcome",
        labels=("outcome",),
    ).snapshot()
    doc["plan_cache"] = {k2 or "none": int(v) for k2, v in plan.items()}
    return doc
