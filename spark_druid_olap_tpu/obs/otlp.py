"""Emit-only OTLP/JSON span export (ROADMAP obs follow-up (d)).

Converts a finished `QueryTrace.to_dict()` document into one
OpenTelemetry `ResourceSpans` JSON object (the OTLP/HTTP JSON encoding)
and appends it as a single line to a local file.  Emit-only by design:
no collector, no network client, no new dependency — tier-1 stays
hermetic, and an operator who wants the spans in a real backend pipes
the file into any OTLP-speaking agent (`otelcol`'s filelog receiver,
`curl --data @line .../v1/traces`).

Span identity: OTLP wants 16-byte trace ids / 8-byte span ids as hex.
The query_id (a uuid4 in Druid's own format) hashes into the trace id;
span ids are content hashes of (name, path, start) so re-exports are
deterministic.  Timestamps: the tracer clock is monotonic-relative, so
spans are anchored at the EXPORT wall-clock minus the trace total —
phase durations and tree structure are exact, absolute placement is
approximate to within the export delay (documented, acceptable for an
emit-only debug artifact).

Cross-process join (ISSUE 19): because the trace id derives from the
query_id alone, a broker and every historical serving the same query
export under the SAME trace id in their separate OTLP files — an
external collector joins them with no coordination.  The nesting joins
too: the broker stamps each `cluster_rpc` span with a PRE-COMPUTED
span id (`rpc_span_id`, carried on the span's `otlp_span_id` attr and
sent in the `X-Sdol-Parent-Span` header), and a historical trace
opened under that header exports its root with the matching
`parentSpanId` — so the collector renders broker RPC -> remote query
as parent/child across files.
"""

from __future__ import annotations

import hashlib
import json
import time
from typing import Any, Dict, List, Optional


def _hex_id(seed: str, nbytes: int) -> str:
    return hashlib.sha256(seed.encode()).hexdigest()[: 2 * nbytes]


def rpc_span_id(query_id: str, node: str, attempt: int) -> str:
    """Deterministic OTLP span id for ONE broker->historical attempt,
    computable BEFORE the span closes — the broker must send the id in
    the RPC headers while the span is still open, and the export must
    later emit the same id.  Derives from (query id, node, attempt
    ordinal): stable across re-exports, distinct across failover and
    hedge attempts."""
    return _hex_id(f"rpc:{query_id}:{node}:{int(attempt)}", 8)


def _attr(key: str, value: Any) -> Dict[str, Any]:
    """One OTLP KeyValue; numbers keep their type, everything else is
    stringified (OTLP AnyValue has no null/dict encoding we need)."""
    if isinstance(value, bool):
        v: Dict[str, Any] = {"boolValue": value}
    elif isinstance(value, int):
        v = {"intValue": str(value)}
    elif isinstance(value, float):
        v = {"doubleValue": value}
    else:
        v = {"stringValue": str(value)}
    return {"key": key, "value": v}


def trace_to_otlp(
    doc: Dict[str, Any], epoch_ns: Optional[int] = None
) -> Dict[str, Any]:
    """One `QueryTrace.to_dict()` -> one OTLP/JSON ResourceSpans dict."""
    qid = str(doc.get("query_id", ""))
    trace_id = _hex_id("trace:" + qid, 16)
    total_ms = float(doc.get("total_ms", 0.0))
    if epoch_ns is None:
        epoch_ns = int((time.time() - total_ms / 1e3) * 1e9)
    spans: List[Dict[str, Any]] = []

    def walk(node: Dict[str, Any], parent_id: str, path: str) -> None:
        start_ms = float(node.get("start_ms", 0.0))
        dur_ms = float(node.get("duration_ms", 0.0))
        # an `otlp_span_id` attr pins the exported id to one computed
        # BEFORE export (the broker pre-computes `rpc_span_id` so the
        # id it sent in X-Sdol-Parent-Span is the id it exports under)
        pinned = (node.get("attrs") or {}).get("otlp_span_id")
        span_id = str(pinned) if pinned else _hex_id(
            f"span:{qid}:{path}:{node.get('name')}:{start_ms}", 8
        )
        start_ns = epoch_ns + int(start_ms * 1e6)
        span: Dict[str, Any] = {
            "traceId": trace_id,
            "spanId": span_id,
            "name": str(node.get("name", "span")),
            "kind": 1,  # SPAN_KIND_INTERNAL
            "startTimeUnixNano": str(start_ns),
            "endTimeUnixNano": str(start_ns + int(dur_ms * 1e6)),
        }
        if parent_id:
            span["parentSpanId"] = parent_id
        attrs = [
            _attr(k, v) for k, v in (node.get("attrs") or {}).items()
        ]
        if attrs:
            span["attributes"] = attrs
        events = [
            {
                "name": str(e.get("name", "event")),
                "timeUnixNano": str(
                    epoch_ns + int(float(e.get("at_ms", 0.0)) * 1e6)
                ),
                **(
                    {
                        "attributes": [
                            _attr(k, v)
                            for k, v in (e.get("attrs") or {}).items()
                        ]
                    }
                    if e.get("attrs")
                    else {}
                ),
            }
            for e in node.get("events", ())
        ]
        if events:
            span["events"] = events
        spans.append(span)
        for i, child in enumerate(node.get("children", ())):
            walk(child, span_id, f"{path}/{i}")

    root = doc.get("spans") or {}
    if root:
        # a historical opened under a broker RPC exports its root as a
        # child of the broker's cluster_rpc span (cross-process join)
        walk(root, str(doc.get("parent_span_id") or ""), "0")
    return {
        "resourceSpans": [
            {
                "resource": {
                    "attributes": [
                        _attr("service.name", "spark-druid-olap-tpu"),
                        _attr("sdol.query_id", qid),
                        _attr(
                            "sdol.query_type",
                            str(doc.get("query_type", "")),
                        ),
                    ]
                },
                "scopeSpans": [
                    {
                        "scope": {"name": "sdol.obs.trace"},
                        "spans": spans,
                    }
                ],
            }
        ]
    }


def append_otlp(path: str, doc: Dict[str, Any]) -> None:
    """Append one trace as one OTLP/JSON line.  O_APPEND line writes are
    atomic enough for the debug-artifact contract; concurrent queries
    each append whole lines."""
    line = json.dumps(trace_to_otlp(doc), separators=(",", ":"))
    with open(path, "a", encoding="utf-8") as f:
        f.write(line + "\n")
