"""Observability subsystem: per-query span tracing + process metrics.

Two halves (ISSUE 4 tentpole):

  * `obs.trace` — a lock-safe, injectable-clock span tracer producing a
    per-query span tree attached to a Druid-parity `query_id`, a bounded
    trace ring buffer served over HTTP, and the slow-query log.
  * `obs.registry` — a process-wide Prometheus-style metrics registry
    (counters / gauges / histograms) the engines, resilience layer, and
    HTTP server publish into; rendered at `GET /status/metrics`.

Instrumented code imports from HERE (`from .obs import span, SPAN_...`)
so the span-name registry and the context-manager discipline stay in one
place — the span-discipline lint pass (GL11xx) enforces both.
"""

from .registry import (  # noqa: F401
    MetricsRegistry,
    bounded_label,
    get_registry,
    record_compaction,
    record_ingest,
    record_partial,
    record_cluster_health,
    record_cluster_rpc,
    record_query_metrics,
    record_rollup,
    record_snapshot_flush,
    record_snapshot_sweep,
    record_storage_load,
    record_wal_append,
    record_wal_replay,
)
from . import prof  # noqa: F401  (performance attribution, ISSUE 9)
from .trace import (  # noqa: F401
    SPAN_ADAPTIVE_PROBE,
    SPAN_ADMISSION,
    SPAN_ARENA_BUILD,
    SPAN_CLUSTER_MERGE,
    SPAN_CLUSTER_RPC,
    SPAN_COLLECTIVE_MERGE,
    SPAN_COMPACT,
    SPAN_DEGRADED,
    SPAN_DEVICE_FETCH,
    SPAN_EXECUTE,
    SPAN_FALLBACK,
    SPAN_FALLBACK_DECODE,
    SPAN_FINALIZE,
    SPAN_FUSED_BATCH,
    SPAN_GATHER,
    SPAN_H2D,
    SPAN_INGEST,
    SPAN_INGEST_ENCODE,
    SPAN_LANE,
    SPAN_LOWER,
    SPAN_NAMES,
    SPAN_PARTIAL,
    SPAN_PLAN,
    SPAN_PREFETCH,
    SPAN_QUERY,
    SPAN_RETRY,
    SPAN_ROLLUP,
    SPAN_SCATTER,
    SPAN_SEGMENT_DISPATCH,
    SPAN_SNAPSHOT_FLUSH,
    SPAN_SPARSE_DISPATCH,
    SPAN_STREAM_CHUNK,
    SPAN_STREAM_FLUSH,
    SPAN_WAL_APPEND,
    SPAN_WAL_REPLAY,
    QueryTrace,
    Span,
    TraceRing,
    Tracer,
    current_query_id,
    current_trace,
    default_tracer,
    new_query_id,
    span,
    span_event,
    span_in,
)
