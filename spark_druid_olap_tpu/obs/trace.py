"""Per-query span tracing: the observability layer's timeline substrate.

Reference parity: Druid emits server-side query metrics keyed by a
`queryId` the client may set in the query context, echoed back as the
`X-Druid-Query-Id` response header, and request logs are queryId-tagged
(SURVEY.md §5).  The TPU build's flat last-query `QueryMetrics` snapshot
cannot answer "which concurrent query retried?" or "where did this
deadline die?"; this module can:

  * **Span tree per query** — a `QueryTrace` rooted at a `query` span,
    with children for every lifecycle phase (`admission → plan → lower →
    h2d → segment_dispatch → device_fetch → collective_merge →
    finalize`, plus `fallback`/`retry`/`degraded` when a query leaves
    the happy path).  Span names are DRAWN FROM the `SPAN_*` constant
    registry below — the span-discipline lint pass (GL11xx) rejects
    ad-hoc strings so the taxonomy cannot fragment.
  * **query_id end-to-end** — generated at the server boundary (honoring
    Druid's `context.queryId`), carried by a contextvar through engine,
    sparse/adaptive/streaming exec, resilience, and the host fallback;
    stamped onto `QueryMetrics.query_id`.
  * **Instrumentation that disappears when idle** — `span(name)` costs
    one contextvar read when no trace is active; with a trace it is two
    clock reads and two list/lock operations.  The clock is injectable
    (tests assert tracer overhead by *counting* clock calls, never by
    timing wall-clock).
  * **Trace ring buffer** — finished traces serialize to JSON and land
    in a bounded FIFO ring served by `GET /druid/v2/trace/{query_id}`.
  * **Slow-query log** — a finished trace whose total exceeds
    `SessionConfig.slow_query_ms` logs its rendered span tree at
    WARNING through `utils/log.py`.

Concurrency: the contextvars give every handler thread its own active
trace/span, so concurrent queries cannot interleave their trees; the
per-trace lock makes child-append and finish safe if a span IS opened
from another thread (the streaming producer thread deliberately sees no
active trace — a fresh thread starts with an empty context).
"""

from __future__ import annotations

import contextlib
import contextvars
import threading
import time
import uuid
from collections import OrderedDict
from typing import Any, Callable, Dict, List, Optional

from ..utils.log import get_logger

log = get_logger("obs.trace")


# ---------------------------------------------------------------------------
# Span-name registry (the span-discipline lint pass GL11xx enforces that
# every `span(...)` call in the exec/resilience/serving modules names one
# of these constants — add the constant HERE first, then use it)
# ---------------------------------------------------------------------------

SPAN_QUERY = "query"  # root span of every trace
SPAN_ADMISSION = "admission"  # waiting for an admission slot
SPAN_PLAN = "plan"  # parse + plan (or plan-cache lookup)
SPAN_EXECUTE = "execute"  # device/fallback execution umbrella
SPAN_LOWER = "lower"  # query lowering + segment scoping
SPAN_H2D = "h2d"  # host->device column placement for one batch
SPAN_SEGMENT_DISPATCH = "segment_dispatch"  # one fused program dispatch
SPAN_DEVICE_FETCH = "device_fetch"  # blocking host fetch of partials
SPAN_COLLECTIVE_MERGE = "collective_merge"  # mesh dispatch + ICI-merged fetch
SPAN_FINALIZE = "finalize"  # host-side result materialization
SPAN_FALLBACK = "fallback"  # host interpreter run
SPAN_FALLBACK_DECODE = "fallback_decode"  # fallback table materialization
SPAN_RETRY = "retry"  # one transient-failure re-attempt
SPAN_DEGRADED = "degraded"  # breaker/failure degradation to the fallback
SPAN_SPARSE_DISPATCH = "sparse_dispatch"  # sort-compaction tier dispatch
SPAN_ADAPTIVE_PROBE = "adaptive_probe"  # adaptive phase-A presence pass
SPAN_STREAM_CHUNK = "stream_chunk"  # one streaming chunk dispatch
SPAN_INGEST = "ingest"  # one streamed append (ingest tier, ISSUE 6)
SPAN_INGEST_ENCODE = "ingest_encode"  # dictionary encode of an append batch
SPAN_COMPACT = "compact"  # delta -> historical roll of one datasource
SPAN_PARTIAL = "partial"  # deadline-bounded best-effort answer (coverage)
SPAN_STREAM_FLUSH = "stream_flush"  # one progressive-response refinement
SPAN_FUSED_BATCH = "fused_batch"  # one micro-batch fused execution (serve/)
SPAN_LANE = "lane"  # waiting for a priority-lane slot (serve/lanes.py)
SPAN_PREFETCH = "prefetch"  # async h2d issue overlapped behind compute
SPAN_WAL_APPEND = "wal_append"  # fsync'd journal write of one append batch
SPAN_WAL_REPLAY = "wal_replay"  # boot-time WAL replay of one datasource
SPAN_SNAPSHOT_FLUSH = "snapshot_flush"  # persistent segment snapshot commit
SPAN_ROLLUP = "rollup"  # ingest-time pre-aggregation of an append batch
SPAN_ARENA_BUILD = "arena_build"  # segment-stacked arena assembly (exec/arena.py)
SPAN_SCATTER = "scatter"  # broker: replica fetches in flight (cluster/)
SPAN_GATHER = "gather"  # broker: decode + coverage of gathered replies
SPAN_CLUSTER_MERGE = "cluster_merge"  # broker: ⊕ fold of replica states
SPAN_CLUSTER_RPC = "cluster_rpc"  # broker: ONE replica attempt (pool thread)

SPAN_NAMES = frozenset(
    {
        SPAN_QUERY,
        SPAN_ADMISSION,
        SPAN_PLAN,
        SPAN_EXECUTE,
        SPAN_LOWER,
        SPAN_H2D,
        SPAN_SEGMENT_DISPATCH,
        SPAN_DEVICE_FETCH,
        SPAN_COLLECTIVE_MERGE,
        SPAN_FINALIZE,
        SPAN_FALLBACK,
        SPAN_FALLBACK_DECODE,
        SPAN_RETRY,
        SPAN_DEGRADED,
        SPAN_SPARSE_DISPATCH,
        SPAN_ADAPTIVE_PROBE,
        SPAN_STREAM_CHUNK,
        SPAN_INGEST,
        SPAN_INGEST_ENCODE,
        SPAN_COMPACT,
        SPAN_PARTIAL,
        SPAN_STREAM_FLUSH,
        SPAN_FUSED_BATCH,
        SPAN_LANE,
        SPAN_PREFETCH,
        SPAN_WAL_APPEND,
        SPAN_WAL_REPLAY,
        SPAN_SNAPSHOT_FLUSH,
        SPAN_ROLLUP,
        SPAN_ARENA_BUILD,
        SPAN_SCATTER,
        SPAN_GATHER,
        SPAN_CLUSTER_MERGE,
        SPAN_CLUSTER_RPC,
    }
)


def new_query_id() -> str:
    """Druid-shaped opaque query id (uuid4, the broker's own format)."""
    return str(uuid.uuid4())


# ---------------------------------------------------------------------------
# Spans
# ---------------------------------------------------------------------------


class Span:
    """One timed phase.  Start/end are tracer-clock readings (seconds);
    `attrs` carry small JSON-able facts (segment index, retry attempt);
    `events` are point-in-time observations inside the phase (the
    breaker state read at routing time) — a name, a clock reading, and
    small attrs, without opening a child span.

    `grafts` hold PRE-RENDERED remote subtrees (cluster/, ISSUE 19): a
    historical's already-serialized span tree splices under the broker's
    `cluster_rpc` span at render time.  Grafted nodes keep their REMOTE
    clock origin — `start_ms` inside a graft is relative to the remote
    root, not this trace's (cross-process clocks don't join); they carry
    `attrs.remote` so consumers can tell."""

    __slots__ = ("name", "start", "end", "attrs", "children", "events",
                 "grafts")

    def __init__(self, name: str, start: float, attrs: Optional[dict] = None):
        self.name = name
        self.start = start
        self.end: Optional[float] = None
        self.attrs = attrs or {}
        self.children: List["Span"] = []
        self.events: List[Dict[str, Any]] = []
        self.grafts: List[dict] = []

    @property
    def duration_ms(self) -> float:
        if self.end is None:
            return 0.0
        return (self.end - self.start) * 1e3

    def to_dict(self, origin: float, now: Optional[float] = None) -> dict:
        # `now` supports LIVE snapshots (obs/prof.py receipt builds
        # mid-query): an unfinished span measures to the provisional
        # clock reading instead of reporting zero
        dur = self.duration_ms
        if self.end is None and now is not None:
            dur = (now - self.start) * 1e3
        d: Dict[str, Any] = {
            "name": self.name,
            "start_ms": round((self.start - origin) * 1e3, 3),
            "duration_ms": round(dur, 3),
        }
        if self.attrs:
            d["attrs"] = dict(self.attrs)
        if self.events:
            d["events"] = [
                {
                    "name": e["name"],
                    "at_ms": round((e["at"] - origin) * 1e3, 3),
                    **(
                        {"attrs": dict(e["attrs"])} if e["attrs"] else {}
                    ),
                }
                for e in self.events
            ]
        if self.children or self.grafts:
            d["children"] = [
                c.to_dict(origin, now) for c in self.children
            ] + list(self.grafts)
        return d


class QueryTrace:
    """The span tree of ONE query, rooted at a `query` span."""

    def __init__(
        self,
        query_id: str,
        clock: Callable[[], float] = time.perf_counter,
        query_type: str = "",
    ):
        self.query_id = query_id
        self.query_type = query_type
        self._clock = clock
        self._lock = threading.Lock()
        self.root = Span(SPAN_QUERY, clock())
        # per-query cost receipt (obs/prof.py), stamped at trace close;
        # rides every to_dict so the ring doc, bench detail artifacts,
        # and /druid/v2/trace/{id} all carry it
        self.receipt: Optional[dict] = None
        # cross-process parentage (cluster/, ISSUE 19): a historical
        # serving a broker RPC records the broker's span id here so the
        # OTLP export joins both processes into one tree
        self.parent_span_id: str = ""

    def start_span(
        self, name: str, parent: Optional[Span], attrs: Optional[dict] = None
    ) -> Span:
        """INTERNAL pairing API — instrumented code must go through the
        `span(...)` context manager (span-discipline/GL1102): a manual
        begin/end pair leaks the span on every early return or raise."""
        s = Span(name, self._clock(), attrs)
        with self._lock:
            (parent or self.root).children.append(s)
        return s

    def end_span(self, s: Span) -> None:
        s.end = self._clock()

    def add_event(
        self, s: Span, name: str, attrs: Optional[dict] = None
    ) -> None:
        with self._lock:
            s.events.append(
                {"name": name, "at": self._clock(), "attrs": attrs or {}}
            )

    def graft(self, s: Span, subtree: dict) -> None:
        """Splice a PRE-RENDERED remote span subtree (a historical's
        `to_dict()["spans"]` or an `untraced` stub) under `s`.  Lock-safe
        like start_span — the scatter pool threads graft concurrently."""
        with self._lock:
            s.grafts.append(subtree)

    def finish(self) -> None:
        with self._lock:
            if self.root.end is None:
                self.root.end = self._clock()

    @property
    def total_ms(self) -> float:
        return self.root.duration_ms

    def to_dict(self) -> dict:
        d = {
            "query_id": self.query_id,
            "query_type": self.query_type,
            "total_ms": round(self.total_ms, 3),
            "spans": self.root.to_dict(self.root.start),
        }
        if self.parent_span_id:
            d["parent_span_id"] = self.parent_span_id
        if self.receipt is not None:
            d["receipt"] = self.receipt
        return d

    def to_dict_live(self) -> dict:
        """Provisional snapshot of a trace still in flight: unfinished
        spans (including the root) measure to 'now' under the tracer's
        own clock — what obs.prof.live_receipt folds into the receipt
        the response headers and df.attrs carry."""
        now = self._clock()
        root_end = self.root.end if self.root.end is not None else now
        return {
            "query_id": self.query_id,
            "query_type": self.query_type,
            "total_ms": round((root_end - self.root.start) * 1e3, 3),
            "spans": self.root.to_dict(self.root.start, now),
        }

    def render(self) -> str:
        """Indented phase/latency lines (the slow-query-log body)."""
        lines: List[str] = []

        def walk(s: Span, depth: int) -> None:
            attrs = (
                " " + " ".join(f"{k}={v}" for k, v in sorted(s.attrs.items()))
                if s.attrs
                else ""
            )
            lines.append(
                f"{'  ' * depth}{s.name:<20} {s.duration_ms:>9.2f}ms{attrs}"
            )
            for e in s.events:
                eattrs = " ".join(
                    f"{k}={v}" for k, v in sorted(e["attrs"].items())
                )
                lines.append(
                    f"{'  ' * (depth + 1)}@ {e['name']}"
                    f"{' ' + eattrs if eattrs else ''}"
                )
            for c in s.children:
                walk(c, depth + 1)

        walk(self.root, 0)
        return "\n".join(lines)


# ---------------------------------------------------------------------------
# Active-trace plumbing (contextvars: per-thread/per-context isolation)
# ---------------------------------------------------------------------------

_active_trace: contextvars.ContextVar[Optional[QueryTrace]] = (
    contextvars.ContextVar("sdol_active_trace", default=None)
)
_active_span: contextvars.ContextVar[Optional[Span]] = contextvars.ContextVar(
    "sdol_active_span", default=None
)


def current_trace() -> Optional[QueryTrace]:
    return _active_trace.get()


def current_query_id() -> str:
    tr = _active_trace.get()
    return tr.query_id if tr is not None else ""


def current_span() -> Optional[Span]:
    """The innermost open span of the active trace (None without one) —
    how the prof sync helpers annotate the span they fired inside."""
    return _active_span.get()


@contextlib.contextmanager
def span(name: str, **attrs):
    """Open a child span of the active trace; a no-op (one contextvar
    read) when no trace is active.  THE way instrumented code creates
    spans — every early return / raise path closes the span because the
    context manager owns the pairing (span-discipline/GL1102)."""
    tr = _active_trace.get()
    if tr is None:
        yield None
        return
    s = tr.start_span(name, _active_span.get(), attrs or None)
    token = _active_span.set(s)
    try:
        yield s
    finally:
        _active_span.reset(token)
        tr.end_span(s)


@contextlib.contextmanager
def span_in(trace: Optional[QueryTrace], parent: Optional[Span],
            name: str, **attrs):
    """Open a span on an EXPLICIT trace handle, under an explicit parent
    — the sanctioned pairing for pool threads, where the contextvar
    trace is invisible by design (a fresh thread starts with an empty
    context).  The broker's scatter workers (cluster/broker.py) thread
    (trace, scatter-span) through to here so every replica attempt gets
    its own `cluster_rpc` span.  Owns the begin/end pairing exactly like
    `span(...)` (span-discipline/GL1102, trace-propagation/GL2702: the
    name must be a registered SPAN_* constant).  No-op when `trace` is
    None (the caller ran without an active trace)."""
    if trace is None:
        yield None
        return
    s = trace.start_span(name, parent, attrs or None)
    try:
        yield s
    finally:
        trace.end_span(s)


def span_event(name: str, **attrs) -> None:
    """Attach a point-in-time event to the ACTIVE span (no child span,
    no duration): the routing layer records the breaker state it
    observed, retries note which error class struck.  A no-op (one
    contextvar read) when no trace is active."""
    tr = _active_trace.get()
    if tr is None:
        return
    s = _active_span.get()
    tr.add_event(s if s is not None else tr.root, name, attrs or None)


# ---------------------------------------------------------------------------
# Ring buffer + tracer
# ---------------------------------------------------------------------------


class TraceRing:
    """Bounded FIFO of finished traces, keyed by query_id.  A repeated
    query_id overwrites in place (Druid lets clients reuse ids); capacity
    evicts the OLDEST insertion."""

    def __init__(self, capacity: int = 64):
        self.capacity = max(1, int(capacity))
        self._lock = threading.Lock()
        self._traces: "OrderedDict[str, dict]" = OrderedDict()

    def put(self, trace_dict: dict) -> None:
        qid = trace_dict.get("query_id", "")
        with self._lock:
            if qid in self._traces:
                self._traces.pop(qid)
            self._traces[qid] = trace_dict
            while len(self._traces) > self.capacity:
                self._traces.popitem(last=False)

    def get(self, query_id: str) -> Optional[dict]:
        with self._lock:
            return self._traces.get(query_id)

    def ids(self) -> List[str]:
        with self._lock:
            return list(self._traces)

    def __len__(self) -> int:
        with self._lock:
            return len(self._traces)


class Tracer:
    """Owns the clock, the finished-trace ring, and trace lifecycle.

    `clock` is injectable so tests measure tracer overhead by counting
    calls under a deterministic clock instead of timing wall-clock; the
    ring capacity is `SessionConfig.trace_ring_capacity` when built by a
    TPUOlapContext."""

    def __init__(
        self,
        clock: Callable[[], float] = time.perf_counter,
        capacity: int = 64,
        otlp_path: Optional[str] = None,
        prof_sample_rate: float = 0.0,
    ):
        self.clock = clock
        self.ring = TraceRing(capacity)
        self.last: Optional[QueryTrace] = None
        # ROADMAP obs follow-up (d): emit-only OTLP export behind a
        # config flag — finished trace dicts append (OTLP/JSON
        # ResourceSpans, one per line) to this path; no collector, no
        # network, no tier-1 dependency
        self.otlp_path = otlp_path
        # performance attribution (obs/prof.py, ISSUE 9): every owned
        # trace arms a ProfScope; the sampler decides which queries pay
        # the honest-device-timing sync points.  Deterministic (no RNG)
        # and force-armable (`force_sample_next`) so a bench can collect
        # one honest receipt per query without perturbing its timed reps.
        from .prof import RateSampler

        self.sampler = RateSampler(prof_sample_rate)

    def force_sample_next(self) -> None:
        """Arm honest device timing for the NEXT owned trace regardless
        of the configured sample rate."""
        self.sampler.force_next()

    @contextlib.contextmanager
    def query_trace(
        self,
        query_id: Optional[str] = None,
        query_type: str = "",
        slow_ms: float = 0.0,
        parent_span_id: str = "",
    ):
        """Open (or join) the per-query trace.  The OUTERMOST scope wins,
        exactly like `resilience.deadline_scope`: the server boundary
        starts the trace and `ctx.sql` inside it joins rather than
        nesting a second root.  `parent_span_id` stamps cross-process
        parentage (a historical trace opened under a broker RPC span)."""
        existing = _active_trace.get()
        if existing is not None:
            yield existing
            return
        from . import prof as _prof

        tr = QueryTrace(
            query_id or new_query_id(), clock=self.clock,
            query_type=query_type,
        )
        if parent_span_id:
            tr.parent_span_id = str(parent_span_id)
        tok_t = _active_trace.set(tr)
        tok_s = _active_span.set(tr.root)
        ps = _prof.ProfScope(sampled=self.sampler.take())
        tok_p = _prof.activate(ps)
        try:
            yield tr
        finally:
            _active_span.reset(tok_s)
            _active_trace.reset(tok_t)
            tr.finish()
            self.last = tr
            doc = tr.to_dict()
            # per-query cost receipt (ISSUE 9): fold the finished span
            # tree + the prof scope's counters into the attribution doc
            # and feed the rolling workload profiler — both must never
            # fail a query
            try:
                tr.receipt = _prof.build_receipt(doc, ps)
                doc["receipt"] = tr.receipt
                _prof.workload_profiler().observe(doc, ps)
            except Exception:  # fault-ok: attribution must not fail queries
                log.warning("receipt build failed", exc_info=True)
            _prof.deactivate(tok_p)
            self.ring.put(doc)
            if self.otlp_path:
                from .otlp import append_otlp

                try:
                    append_otlp(self.otlp_path, doc)
                except OSError:  # fault-ok: export must never fail a query
                    log.warning(
                        "OTLP export to %s failed", self.otlp_path,
                        exc_info=True,
                    )
            if slow_ms and slow_ms > 0 and tr.total_ms >= slow_ms:
                log.warning(
                    "slow query %s: %.1fms >= %.0fms threshold\n%s",
                    tr.query_id, tr.total_ms, slow_ms, tr.render(),
                )

    def last_trace_dict(self) -> Optional[dict]:
        return self.last.to_dict() if self.last is not None else None


_default_tracer: Optional[Tracer] = None
_default_tracer_lock = threading.Lock()


def default_tracer() -> Tracer:
    """Process-default tracer for code running outside a TPUOlapContext
    (direct Engine use, tooling)."""
    global _default_tracer
    if _default_tracer is None:
        with _default_tracer_lock:
            if _default_tracer is None:
                _default_tracer = Tracer()
    return _default_tracer
