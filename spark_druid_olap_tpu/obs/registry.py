"""Process-wide metrics registry with Prometheus text exposition.

Reference parity: Druid nodes emit query/segment/jvm metrics through
pluggable emitters and modern deployments scrape them as Prometheus
series (SURVEY.md §5); the analog here is one process-global
`MetricsRegistry` every subsystem publishes into — the engines (query
counts by type/executor/outcome, per-phase latency histograms, h2d
bytes), the resilience layer (retries, breaker transitions, admission
queue depth), and the HTTP server (requests by route/code) — rendered
at `GET /status/metrics` in Prometheus text format and summarized
(with histogram p50/p95/p99) inside `GET /status`.

The registry is deliberately PROCESS-wide, not per-context: a scrape
must see the whole process exactly like a real exporter would, and
counters must be monotonic across context rebuilds.  Everything is
lock-guarded; label sets are fixed at family registration so exposition
stays stable.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, Iterable, List, Optional, Tuple

# per-phase latency buckets, ms: spans sub-ms cached-program queries up
# through minutes-long SF100 scans
DEFAULT_BUCKETS_MS = (
    1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 500.0,
    1000.0, 2500.0, 5000.0, 10000.0, 30000.0, 60000.0,
)


def _escape_label(v: str) -> str:
    return (
        str(v)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def _fmt_labels(names: Tuple[str, ...], values: Tuple[str, ...]) -> str:
    if not names:
        return ""
    inner = ",".join(
        f'{n}="{_escape_label(v)}"' for n, v in zip(names, values)
    )
    return "{" + inner + "}"


class _Family:
    """One metric family: fixed name, help, label names; children keyed
    by label-value tuples."""

    kind = "untyped"

    def __init__(self, name: str, help_text: str, labels: Tuple[str, ...]):
        self.name = name
        self.help = help_text
        self.label_names = labels
        self._lock = threading.Lock()
        self._children: Dict[Tuple[str, ...], object] = {}

    def _child_key(self, kwargs: Dict[str, str]) -> Tuple[str, ...]:
        if set(kwargs) != set(self.label_names):
            raise ValueError(
                f"metric {self.name!r} takes labels {self.label_names}, "
                f"got {sorted(kwargs)}"
            )
        return tuple(str(kwargs[n]) for n in self.label_names)


class Counter(_Family):
    """Monotonic counter family.  Unlabeled families use `.inc()` on the
    family itself (a single implicit child)."""

    kind = "counter"

    def labels(self, **kwargs) -> "Counter._Child":
        key = self._child_key(kwargs)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = self._children[key] = Counter._Child(self)
        return child  # type: ignore[return-value]

    def inc(self, amount: float = 1.0) -> None:
        if self.label_names:
            raise ValueError(
                f"metric {self.name!r} is labeled; use .labels(...).inc()"
            )
        self.labels().inc(amount)

    @property
    def value(self) -> float:
        if self.label_names:
            raise ValueError(f"metric {self.name!r} is labeled")
        return self.labels().value

    class _Child:
        __slots__ = ("_family", "_value")

        def __init__(self, family: "Counter"):
            self._family = family
            self._value = 0.0

        def inc(self, amount: float = 1.0) -> None:
            if amount < 0:
                raise ValueError("counters only go up")
            with self._family._lock:
                self._value += amount

        @property
        def value(self) -> float:
            with self._family._lock:
                return self._value

    def render(self) -> List[str]:
        with self._lock:
            items = sorted(self._children.items())
            return [
                f"{self.name}{_fmt_labels(self.label_names, key)} "
                f"{child._value:g}"
                for key, child in items
            ]

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            return {
                ",".join(key) if key else "": child._value
                for key, child in self._children.items()
            }


class Gauge(_Family):
    """Settable gauge; `set_function` installs a live callback (read at
    render time) — how the admission pool exposes queue depth without a
    write on every acquire/release."""

    kind = "gauge"

    def __init__(self, name, help_text, labels):
        super().__init__(name, help_text, labels)
        self._fn: Optional[Callable[[], float]] = None

    def labels(self, **kwargs) -> "Gauge._Child":
        key = self._child_key(kwargs)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = self._children[key] = Gauge._Child(self)
        return child  # type: ignore[return-value]

    def set(self, value: float) -> None:
        if self.label_names:
            raise ValueError(
                f"metric {self.name!r} is labeled; use .labels(...).set()"
            )
        self.labels().set(value)

    def set_function(self, fn: Callable[[], float]) -> None:
        """Callback gauge (unlabeled): re-binding replaces the previous
        callback, so a rebuilt context simply takes over the series."""
        if self.label_names:
            raise ValueError("callback gauges are unlabeled")
        with self._lock:
            self._fn = fn

    class _Child:
        __slots__ = ("_family", "_value", "_fn")

        def __init__(self, family: "Gauge"):
            self._family = family
            self._value = 0.0
            self._fn: Optional[Callable[[], float]] = None

        def set(self, value: float) -> None:
            with self._family._lock:
                self._value = float(value)

        def set_function(self, fn: Callable[[], float]) -> None:
            """Per-series live callback (read at render time) — how the
            per-backend breakers export `sdol_breaker_state{backend=...}`
            without writing a gauge on every state transition.
            Re-binding replaces the callback (a rebuilt context takes
            over its series)."""
            with self._family._lock:
                self._fn = fn

        def _read(self) -> float:
            with self._family._lock:
                fn, v = self._fn, self._value
            if fn is None:
                return v
            try:
                return float(fn())
            except Exception:  # fault-ok: dead callback must not break a scrape
                return v

        @property
        def value(self) -> float:
            return self._read()

    def _read_fn(self) -> Optional[float]:
        with self._lock:
            fn = self._fn
        if fn is None:
            return None
        try:
            return float(fn())
        except Exception:  # fault-ok: a dead callback must not break a scrape
            return None

    def render(self) -> List[str]:
        v = self._read_fn()
        if v is not None:
            return [f"{self.name} {v:g}"]
        with self._lock:
            items = sorted(self._children.items())
        return [
            f"{self.name}{_fmt_labels(self.label_names, key)} "
            f"{child._read():g}"
            for key, child in items
        ]

    def snapshot(self) -> Dict[str, float]:
        v = self._read_fn()
        if v is not None:
            return {"": v}
        with self._lock:
            items = list(self._children.items())
        return {
            ",".join(key) if key else "": child._read()
            for key, child in items
        }


class Histogram(_Family):
    """Cumulative-bucket histogram (Prometheus semantics: `le` buckets,
    `_sum`, `_count`) with quantile estimation for the JSON summary."""

    kind = "histogram"

    def __init__(self, name, help_text, labels, buckets=DEFAULT_BUCKETS_MS):
        super().__init__(name, help_text, labels)
        self.buckets = tuple(sorted(float(b) for b in buckets))

    def labels(self, **kwargs) -> "Histogram._Child":
        key = self._child_key(kwargs)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = self._children[key] = Histogram._Child(self)
        return child  # type: ignore[return-value]

    def observe(self, value: float, exemplar: Optional[str] = None) -> None:
        if self.label_names:
            raise ValueError(
                f"metric {self.name!r} is labeled; use .labels(...).observe()"
            )
        self.labels().observe(value, exemplar=exemplar)

    class _Child:
        __slots__ = ("_family", "counts", "sum", "count", "exemplars")

        def __init__(self, family: "Histogram"):
            self._family = family
            self.counts = [0] * len(family.buckets)
            self.sum = 0.0
            self.count = 0
            # per-NATIVE-bucket exemplar: the last (trace_id, value)
            # observed in that bucket, +1 slot for the +Inf overflow —
            # the one-hop link from "the p99 bucket is hot" to the
            # query trace that landed there (ROADMAP obs follow-up (a))
            self.exemplars: List[Optional[Tuple[str, float]]] = (
                [None] * (len(family.buckets) + 1)
            )

        def observe(
            self, value: float, exemplar: Optional[str] = None
        ) -> None:
            v = float(value)
            with self._family._lock:
                self.sum += v
                self.count += 1
                native = len(self._family.buckets)
                for i, b in enumerate(self._family.buckets):
                    if v <= b:
                        self.counts[i] += 1
                        native = min(native, i)
                if exemplar:
                    self.exemplars[native] = (str(exemplar), v)

        def quantile(self, q: float) -> Optional[float]:
            """Bucket-interpolated quantile; None when empty.  Values past
            the last bucket clamp to it (the honest answer a bounded
            histogram can give)."""
            with self._family._lock:
                total = self.count
                if total == 0:
                    return None
                rank = q * total
                prev_cum = 0
                prev_edge = 0.0
                for edge, cum in zip(self._family.buckets, self.counts):
                    if cum >= rank:
                        in_bucket = cum - prev_cum
                        if in_bucket <= 0:
                            return edge
                        frac = (rank - prev_cum) / in_bucket
                        return prev_edge + frac * (edge - prev_edge)
                    prev_cum, prev_edge = cum, edge
                return self._family.buckets[-1]

    def render(self) -> List[str]:
        out: List[str] = []
        with self._lock:
            items = sorted(self._children.items())
            for key, child in items:
                for i, (edge, cum) in enumerate(
                    zip(self.buckets, child.counts)
                ):
                    lbls = _fmt_labels(
                        self.label_names + ("le",), key + (f"{edge:g}",)
                    )
                    out.append(f"{self.name}_bucket{lbls} {cum}")
                    ex = child.exemplars[i]
                    if ex is not None:
                        # exemplar as a comment line: the 0.0.4 text
                        # format has no native exemplar syntax and
                        # scrapers skip comments, so the trace link
                        # rides along without breaking any parser
                        out.append(
                            f"# exemplar {self.name}_bucket{lbls} "
                            f'trace_id="{_escape_label(ex[0])}" '
                            f"value={ex[1]:g}"
                        )
                lbls = _fmt_labels(
                    self.label_names + ("le",), key + ("+Inf",)
                )
                out.append(f"{self.name}_bucket{lbls} {child.count}")
                ex = child.exemplars[-1]
                if ex is not None:
                    out.append(
                        f"# exemplar {self.name}_bucket{lbls} "
                        f'trace_id="{_escape_label(ex[0])}" '
                        f"value={ex[1]:g}"
                    )
                base = _fmt_labels(self.label_names, key)
                out.append(f"{self.name}_sum{base} {child.sum:g}")
                out.append(f"{self.name}_count{base} {child.count}")
        return out

    def snapshot(self) -> Dict[str, dict]:
        out: Dict[str, dict] = {}
        with self._lock:
            # one acquisition: children plus their exemplar slots (the
            # quantile calls below take the lock themselves, so they
            # stay outside it)
            items = [
                (key, child, list(child.exemplars))
                for key, child in self._children.items()
            ]
        for key, child, exemplar_slots in items:
            entry = {
                "count": child.count,
                "sum_ms": round(child.sum, 3),
                "p50": child.quantile(0.50),
                "p95": child.quantile(0.95),
                "p99": child.quantile(0.99),
            }
            exemplars = {
                (f"{self.buckets[i]:g}" if i < len(self.buckets)
                 else "+Inf"): {"trace_id": ex[0], "value": ex[1]}
                for i, ex in enumerate(exemplar_slots)
                if ex is not None
            }
            if exemplars:
                entry["exemplars"] = exemplars
            out[",".join(key) if key else ""] = entry
        return out


class MetricsRegistry:
    """Name -> family table.  Registration is idempotent for identical
    (kind, labels) declarations — every subsystem declares what it
    publishes and the first declaration wins the help text."""

    def __init__(self):
        self._lock = threading.Lock()
        self._families: "Dict[str, _Family]" = {}

    def _register(self, cls, name, help_text, labels, **kw) -> _Family:
        labels = tuple(labels)
        with self._lock:
            fam = self._families.get(name)
            if fam is not None:
                if not isinstance(fam, cls) or fam.label_names != labels:
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{fam.kind}{fam.label_names}"
                    )
                return fam
            fam = cls(name, help_text, labels, **kw)
            self._families[name] = fam
            return fam

    def counter(
        self, name: str, help_text: str = "", labels: Iterable[str] = ()
    ) -> Counter:
        return self._register(Counter, name, help_text, labels)  # type: ignore[return-value]

    def gauge(
        self, name: str, help_text: str = "", labels: Iterable[str] = ()
    ) -> Gauge:
        return self._register(Gauge, name, help_text, labels)  # type: ignore[return-value]

    def histogram(
        self,
        name: str,
        help_text: str = "",
        labels: Iterable[str] = (),
        buckets: Iterable[float] = DEFAULT_BUCKETS_MS,
    ) -> Histogram:
        return self._register(
            Histogram, name, help_text, labels, buckets=tuple(buckets)
        )  # type: ignore[return-value]

    # -- exposition -----------------------------------------------------------

    def render_prometheus(self) -> str:
        """Prometheus text exposition format 0.0.4."""
        lines: List[str] = []
        with self._lock:
            fams = sorted(self._families.items())
        for name, fam in fams:
            if fam.help:
                lines.append(f"# HELP {name} {fam.help}")
            lines.append(f"# TYPE {name} {fam.kind}")
            lines.extend(fam.render())
        return "\n".join(lines) + "\n"

    def to_dict(self) -> dict:
        """JSON summary for `/status`: counter/gauge values plus
        histogram p50/p95/p99."""
        out: Dict[str, dict] = {}
        with self._lock:
            fams = sorted(self._families.items())
        for name, fam in fams:
            out[name] = {"type": fam.kind, "values": fam.snapshot()}
        return out


_registry: Optional[MetricsRegistry] = None
_registry_lock = threading.Lock()


def get_registry() -> MetricsRegistry:
    global _registry
    if _registry is None:
        with _registry_lock:
            if _registry is None:
                _registry = MetricsRegistry()
    return _registry


# ---------------------------------------------------------------------------
# Label-cardinality guard (ISSUE 6 obs satellite (b))
# ---------------------------------------------------------------------------

# free-form label values (datasource names arrive from CLIENTS on the
# ingest route) past the cap collapse into one overflow bucket — a
# hostile name-per-request stream can then grow the registry by at most
# `cap` children per family instead of one per request
LABEL_OVERFLOW = "__other__"

_label_guard_lock = threading.Lock()
_label_seen: Dict[str, set] = {}


def bounded_label(family: str, value: str, cap: int = 64) -> str:
    """Admit `value` as a label for `family` while the family's distinct
    admitted set stays under `cap`; return LABEL_OVERFLOW otherwise.
    First-come-first-admitted and process-global (series must stay
    stable across context rebuilds, like the registry itself)."""
    v = str(value) if value else "unknown"
    with _label_guard_lock:
        seen = _label_seen.get(family)
        if seen is None:
            seen = _label_seen[family] = set()
        if v in seen:
            return v
        if len(seen) >= max(1, int(cap)):
            return LABEL_OVERFLOW
        seen.add(v)
        return v


# ---------------------------------------------------------------------------
# The process metric catalog (engines + resilience publish through these)
# ---------------------------------------------------------------------------


def record_ingest(datasource: str, rows: int, outcome: str = "ok") -> None:
    """Publish one streamed append: request count by datasource/outcome
    plus appended rows — per-datasource labels ride through the
    cardinality guard (a hostile datasource-name stream cannot explode
    the registry)."""
    reg = get_registry()
    ds = bounded_label("ingest_datasource", datasource)
    reg.counter(
        "sdol_ingest_requests_total",
        "streamed ingest appends, by datasource / outcome",
        labels=("datasource", "outcome"),
    ).labels(datasource=ds, outcome=outcome).inc()
    if rows:
        reg.counter(
            "sdol_ingest_rows_total",
            "rows appended through the streamed ingest tier",
            labels=("datasource",),
        ).labels(datasource=ds).inc(rows)


def record_compaction(datasource: str, rows: int, delta_segments: int) -> None:
    """Publish one delta->historical compaction."""
    reg = get_registry()
    ds = bounded_label("ingest_datasource", datasource)
    reg.counter(
        "sdol_compactions_total",
        "delta->historical compactions, by datasource",
        labels=("datasource",),
    ).labels(datasource=ds).inc()
    if rows:
        reg.counter(
            "sdol_compacted_rows_total",
            "delta rows rolled into historical segments",
            labels=("datasource",),
        ).labels(datasource=ds).inc(rows)
    if delta_segments:
        reg.counter(
            "sdol_compacted_delta_segments_total",
            "delta segments consumed by compaction",
            labels=("datasource",),
        ).labels(datasource=ds).inc(delta_segments)


def record_wal_append(datasource: str, rows: int) -> None:
    """Publish one durable WAL journal write (storage tier, ISSUE 13):
    acked appends are exactly the journaled ones, so this series is the
    durability-side mirror of `sdol_ingest_rows_total`."""
    reg = get_registry()
    ds = bounded_label("ingest_datasource", datasource)
    reg.counter(
        "sdol_wal_appends_total",
        "fsync'd WAL journal writes, by datasource",
        labels=("datasource",),
    ).labels(datasource=ds).inc()
    if rows:
        reg.counter(
            "sdol_wal_rows_total",
            "rows journaled to the append WAL",
            labels=("datasource",),
        ).labels(datasource=ds).inc(rows)


def record_wal_replay(datasource: str, records: int, rows: int) -> None:
    """Publish one boot-time WAL replay (records past the snapshot
    watermark re-applied through the live append path)."""
    reg = get_registry()
    ds = bounded_label("ingest_datasource", datasource)
    reg.counter(
        "sdol_wal_replays_total",
        "boot-time WAL replay passes, by datasource",
        labels=("datasource",),
    ).labels(datasource=ds).inc()
    if records:
        reg.counter(
            "sdol_wal_replayed_records_total",
            "WAL records replayed at boot",
            labels=("datasource",),
        ).labels(datasource=ds).inc(records)
    if rows:
        reg.counter(
            "sdol_wal_replayed_rows_total",
            "rows re-applied from the WAL at boot",
            labels=("datasource",),
        ).labels(datasource=ds).inc(rows)


def record_snapshot_flush(datasource: str, segments: int) -> None:
    """Publish one persistent-snapshot commit (atomic rename landed)."""
    reg = get_registry()
    ds = bounded_label("ingest_datasource", datasource)
    reg.counter(
        "sdol_snapshot_flushes_total",
        "persistent segment snapshot commits, by datasource",
        labels=("datasource",),
    ).labels(datasource=ds).inc()
    if segments:
        reg.counter(
            "sdol_snapshot_segments_total",
            "segments written by snapshot flushes",
            labels=("datasource",),
        ).labels(datasource=ds).inc(segments)


def record_snapshot_sweep(flushed: int) -> None:
    """Publish one background snapshot-flush sweep pass (the timer
    fired and scanned for dirty datasources).  Per-datasource flush
    volume is already on `sdol_snapshot_flushes_total`; this counts the
    sweep itself plus how many tables it found dirty."""
    reg = get_registry()
    reg.counter(
        "sdol_snapshot_sweeps_total",
        "background snapshot-flush sweep passes",
    ).inc()
    if flushed:
        reg.counter(
            "sdol_snapshot_sweep_flushes_total",
            "datasources flushed by the background snapshot sweep",
        ).inc(flushed)


def record_rollup(datasource: str, rows_in: int, rows_out: int) -> None:
    """Publish one ingest-time rollup: input vs surviving rows.  The
    ratio is the fleet-level answer to "what does rollup actually buy"
    — Druid's own rollup-ratio metric."""
    reg = get_registry()
    ds = bounded_label("ingest_datasource", datasource)
    if rows_in:
        reg.counter(
            "sdol_rollup_input_rows_total",
            "append rows entering ingest-time rollup",
            labels=("datasource",),
        ).labels(datasource=ds).inc(rows_in)
    if rows_out:
        reg.counter(
            "sdol_rollup_output_rows_total",
            "pre-aggregated rows surviving ingest-time rollup",
            labels=("datasource",),
        ).labels(datasource=ds).inc(rows_out)


def record_storage_load(nbytes: int) -> None:
    """Publish one disk-tier column open (np.load mmap of a persisted
    column file): the DISK rung of the residency ladder, next to the
    h2d byte counters the device tiers publish."""
    reg = get_registry()
    reg.counter(
        "sdol_storage_column_opens_total",
        "lazy opens of persisted column files (disk residency tier)",
    ).inc()
    if nbytes:
        reg.counter(
            "sdol_storage_column_bytes_total",
            "logical bytes of persisted columns opened from disk "
            "(mmap-backed; pages fault in lazily on first touch)",
        ).inc(nbytes)


def record_partial(coverage, site: str = "", query_id: str = "") -> None:
    """Publish one deadline-bounded PARTIAL answer: a count by triggering
    site plus the coverage-fraction distribution (ISSUE 7 tentpole (a)).
    The coverage histogram is the fleet-level answer to "how much of the
    data do deadline-bounded dashboards actually see?"; the query_id
    rides along as the bucket exemplar, same as the latency series."""
    reg = get_registry()
    reg.counter(
        "sdol_partial_results_total",
        "queries answered with deadline-bounded partial results, by "
        "triggering checkpoint site",
        labels=("site",),
    ).labels(site=bounded_label("partial_site", site or "unknown")).inc()
    if coverage is not None:
        reg.histogram(
            "sdol_partial_coverage",
            "coverage fraction of deadline-bounded partial answers",
            buckets=(0.01, 0.05, 0.1, 0.25, 0.5, 0.75, 0.9, 0.99, 1.0),
        ).observe(float(coverage), exemplar=query_id or None)


def record_query_metrics(m, outcome: str = "ok") -> None:
    """Publish one finished execution's `QueryMetrics` into the process
    registry: the engines call this from their metrics-finish path, the
    api layer for fallback runs — replacing ad-hoc per-engine fields as
    the fleet-level aggregation (ISSUE 4 tentpole (2))."""
    if m is None:
        return
    reg = get_registry()
    reg.counter(
        "sdol_queries_total",
        "queries executed, by wire type / executor / outcome",
        labels=("query_type", "executor", "outcome"),
    ).labels(
        query_type=m.query_type or "unknown",
        executor=m.executor or "unknown",
        outcome=outcome,
    ).inc()
    # per-datasource traffic (obs satellite (b)): which table is hot is
    # the first question a dashboard fleet asks; the guard caps the
    # series a client-controlled name stream can mint
    ds_name = getattr(m, "datasource", "") or None
    if ds_name:
        reg.counter(
            "sdol_datasource_queries_total",
            "queries executed, by datasource / wire type",
            labels=("datasource", "query_type"),
        ).labels(
            datasource=bounded_label("query_datasource", ds_name),
            query_type=m.query_type or "unknown",
        ).inc()
    if m.retries:
        reg.counter(
            "sdol_query_retries_total",
            "transient-failure re-dispatches paid by queries",
        ).inc(m.retries)
    if m.rows_scanned:
        reg.counter(
            "sdol_rows_scanned_total", "rows scanned by query kernels"
        ).inc(m.rows_scanned)
    if m.h2d_bytes:
        reg.counter(
            "sdol_h2d_bytes_total",
            "bytes moved host->device on residency-cache misses",
        ).inc(m.h2d_bytes)
    hist = reg.histogram(
        "sdol_query_phase_ms",
        "per-phase query latency (ms)",
        labels=("phase",),
    )
    # the query_id rides along as the bucket's exemplar, linking the
    # latency distribution back to a concrete trace in the ring
    qid = getattr(m, "query_id", "") or None
    for phase, value in (
        ("h2d", m.h2d_ms),
        ("compile", m.compile_ms),
        ("device", m.device_ms),
        ("collective", m.est_collective_ms),
        ("finalize", m.finalize_ms),
        ("total", m.total_ms),
    ):
        if value > 0 or phase == "total":
            hist.labels(phase=phase).observe(value, exemplar=qid)


def record_cluster_rpc(
    node: str, outcome: str, ms: float = 0.0, query_id: str = "",
    hedged: bool = False, failover: bool = False,
) -> None:
    """Publish one broker->historical scatter RPC (cluster/, ISSUE 16):
    a per-node/per-outcome count, the RPC latency distribution, and the
    failover/hedge counters the chaos matrix reads.  Node ids pass the
    label-cardinality guard — a runaway membership churn collapses into
    `__other__` instead of exploding the registry."""
    reg = get_registry()
    labels = {
        "node": bounded_label("cluster_node", node or "unknown"),
        "outcome": bounded_label("cluster_outcome", outcome or "unknown"),
    }
    reg.counter(
        "sdol_cluster_scatter_total",
        "broker scatter RPCs to historicals, by node and outcome",
        labels=("node", "outcome"),
    ).labels(**labels).inc()
    if ms > 0:
        reg.histogram(
            "sdol_cluster_rpc_ms",
            "broker->historical RPC latency (one replica attempt)",
            buckets=(1.0, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 500.0,
                     1000.0, 5000.0),
        ).observe(float(ms), exemplar=query_id or None)
    if failover:
        reg.counter(
            "sdol_cluster_failover_total",
            "scatter attempts that failed over to another replica",
            labels=("node",),
        ).labels(node=labels["node"]).inc()
    if hedged:
        reg.counter(
            "sdol_cluster_hedge_total",
            "scatter fetches hedged to a second replica past the "
            "hedge threshold",
            labels=("node",),
        ).labels(node=labels["node"]).inc()


def record_cluster_health(
    live: int, total: int, epoch: int, deficit: int, lost: int = 0,
) -> None:
    """Publish the broker's cluster-health gauges: live historicals,
    the assignment epoch, and the replication deficit (segments below
    their replication factor; `lost` = segments with NO live replica,
    the coverage-stamped-partial zone)."""
    reg = get_registry()
    reg.gauge(
        "sdol_cluster_historicals_live",
        "historicals whose breaker admits traffic",
    ).set(int(live))
    reg.gauge(
        "sdol_cluster_historicals_total",
        "historicals in the broker's membership",
    ).set(int(total))
    reg.gauge(
        "sdol_cluster_assignment_epoch",
        "monotonic assignment epoch (bumps on membership change)",
    ).set(int(epoch))
    reg.gauge(
        "sdol_cluster_replication_deficit",
        "segments currently below their replication factor",
    ).set(int(deficit))
    reg.gauge(
        "sdol_cluster_segments_lost",
        "segments with zero live replicas (served as stamped partials)",
    ).set(int(lost))
