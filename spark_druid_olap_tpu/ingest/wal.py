"""Write-ahead log for streamed appends (ISSUE 13 tentpole (a)).

Druid hands append durability to its indexing-service task logs + deep
storage; the reference accelerator never persists anything itself
(SURVEY §5: "the state is the Druid index itself").  The local analog:
`ctx.append_rows` journals the NORMALIZED DOMAIN-VALUE batch — the
output of `ingest.delta._normalize_rows`, i.e. strings/numbers, never
rank codes — to a per-datasource append-only log BEFORE the delta
publish.  Codes are rank-assigned and shift whenever a dictionary
extends, so they are not a durable currency; domain values replayed
through the exact same `_append_encoded` path rebuild state
code-identical to what the pre-crash process published.

Record framing (little-endian, one record per append batch):

    MAGIC   4B  b"SDW1"
    len     u32 payload byte length
    seq     u64 monotone per-datasource record number
    crc32   u32 of the payload bytes
    payload len bytes

Payload: u32 JSON-header length + header + concatenated raw column
buffers.  Numeric columns ride as raw dtype bytes (header carries
dtype + nbytes); object/string columns ride as JSON value lists inside
the header (null-preserving).  No pickle anywhere — a WAL is an attack
surface and a compatibility surface at once.

Durability contract: a record is DURABLE once `append` returns —
write + flush + fsync happen before the caller may publish or ack.
Torn tails (a crash mid-write) are detected structurally on replay:
short header, short payload, or CRC mismatch at the tail truncates the
log to the last whole record — a batch is replayed fully or dropped
fully, never partially (the kill-and-restart matrix in
tests/test_storage.py proves this at every byte boundary).

Crash sites (`resilience.checkpoint`, armable via FaultInjector):
`wal.journal_write` before the record hits the file, `wal.pre_fsync`
after write/flush but before fsync, `wal.post_fsync_pre_publish` after
fsync — the three stages whose orderings the durability proof leans on.
"""

from __future__ import annotations

import json
import os
import struct
import threading
import zlib
from typing import Dict, Iterator, List, Optional, Tuple

import numpy as np

from ..resilience import checkpoint
from ..utils.log import get_logger

log = get_logger("ingest.wal")

MAGIC = b"SDW1"
_HEAD = struct.Struct("<4sIQI")  # magic, payload_len, seq, crc32


def encode_batch(datasource: str, cols: Dict[str, np.ndarray], n: int) -> bytes:
    """Serialize one normalized append batch into a WAL payload."""
    specs: List[dict] = []
    buffers: List[bytes] = []
    for name, arr in cols.items():
        a = np.asarray(arr)
        if a.dtype.kind == "O":
            vals = [None if _is_null(v) else _jsonable(v) for v in a]
            specs.append({"name": name, "enc": "json", "values": vals})
        else:
            raw = np.ascontiguousarray(a).tobytes()
            specs.append(
                {"name": name, "enc": "raw", "dtype": a.dtype.str,
                 "nbytes": len(raw)}
            )
            buffers.append(raw)
    header = json.dumps(
        {"datasource": datasource, "n": int(n), "cols": specs}
    ).encode()
    return struct.pack("<I", len(header)) + header + b"".join(buffers)


def decode_batch(payload: bytes) -> Tuple[str, Dict[str, np.ndarray], int]:
    """Inverse of `encode_batch`.  Raises ValueError on any structural
    damage — replay treats that as a torn tail."""
    if len(payload) < 4:
        raise ValueError("payload shorter than its header-length prefix")
    (hlen,) = struct.unpack_from("<I", payload, 0)
    if 4 + hlen > len(payload):
        raise ValueError("payload header truncated")
    header = json.loads(payload[4:4 + hlen].decode())
    cols: Dict[str, np.ndarray] = {}
    off = 4 + hlen
    for spec in header["cols"]:
        if spec["enc"] == "json":
            cols[spec["name"]] = np.asarray(spec["values"], dtype=object)
        else:
            nb = int(spec["nbytes"])
            if off + nb > len(payload):
                raise ValueError("payload column buffer truncated")
            cols[spec["name"]] = np.frombuffer(
                payload[off:off + nb], dtype=np.dtype(spec["dtype"])
            ).copy()  # frombuffer views are read-only; encoders may sort
            off += nb
    if off != len(payload):
        raise ValueError("payload carries trailing bytes")
    return header["datasource"], cols, int(header["n"])


def _is_null(v) -> bool:
    if v is None:
        return True
    try:
        import pandas as pd

        return bool(pd.isna(v))
    except (TypeError, ValueError):
        return False


def _jsonable(v):
    if isinstance(v, (np.integer,)):
        return int(v)
    if isinstance(v, (np.floating,)):
        return float(v)
    if isinstance(v, (np.str_, str)):
        return str(v)
    if isinstance(v, (np.bool_, bool)):
        return bool(v)
    return v


class WriteAheadLog:
    """One datasource's append journal.

    All mutation happens under the owning ingest buffer's lock (the WAL
    is part of the append critical section); the internal lock only
    guards the lazily opened file handle against interleaved writers in
    direct-use tests."""

    def __init__(self, path: str, fsync: bool = True):
        self.path = path
        self.fsync = bool(fsync)
        self._lock = threading.Lock()
        self._fh = None
        self._next_seq = 0
        if os.path.exists(path):
            # seed the sequence counter past the last whole record so a
            # restarted process never reuses a seq
            last = -1
            # graftlint: disable=storage-discipline -- seq-counter seeding at open: pure scan, no re-apply; a checkpoint here would consume fault fires armed for the REAL replay
            for seq, _, _, _ in self.scan():
                last = seq
            self._next_seq = last + 1

    @property
    def last_seq(self) -> int:
        """Seq of the last durable record; -1 when the log is empty."""
        with self._lock:
            return self._next_seq - 1

    def _handle(self):
        if self._fh is None or self._fh.closed:
            os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
            # append mode: the journal is the one legitimate non-atomic
            # file write in the storage tier (GL2002 exempts "a" modes)
            self._fh = open(self.path, "ab")
        return self._fh

    def append(self, datasource: str, cols: Dict[str, np.ndarray],
               n: int) -> int:
        """Journal one batch durably; returns its seq.  The caller may
        publish/ack only after this returns."""
        payload = encode_batch(datasource, cols, n)
        with self._lock:
            seq = self._next_seq
            record = _HEAD.pack(
                MAGIC, len(payload), seq, zlib.crc32(payload)
            ) + payload
            checkpoint("wal.journal_write")
            fh = self._handle()
            fh.write(record)
            fh.flush()
            checkpoint("wal.pre_fsync")
            if self.fsync:
                os.fsync(fh.fileno())
            checkpoint("wal.post_fsync_pre_publish")
            self._next_seq = seq + 1
            return seq

    # -- replay ---------------------------------------------------------------

    def scan(self) -> Iterator[Tuple[int, str, Dict[str, np.ndarray], int]]:
        """Yield (seq, datasource, cols, n) for every whole record; stop
        cleanly at the first torn/short/corrupt tail record.  Damage in
        the MIDDLE of the log (crc mismatch followed by more data) also
        stops the scan — everything after a corrupt record is
        unordered garbage by the framing contract."""
        if not os.path.exists(self.path):
            return
        with open(self.path, "rb") as fh:
            while True:
                head = fh.read(_HEAD.size)
                if len(head) < _HEAD.size:
                    return  # clean EOF or torn header
                magic, plen, seq, crc = _HEAD.unpack(head)
                if magic != MAGIC:
                    log.warning(
                        "wal %s: bad magic at offset %d; truncating scan",
                        self.path, fh.tell() - _HEAD.size,
                    )
                    return
                payload = fh.read(plen)
                if len(payload) < plen or zlib.crc32(payload) != crc:
                    return  # torn or corrupt tail record: drop it whole
                try:
                    ds, cols, n = decode_batch(payload)
                except ValueError:
                    return
                yield seq, ds, cols, n

    def replay_after(
        self, watermark: int
    ) -> Iterator[Tuple[int, str, Dict[str, np.ndarray], int]]:
        """Records with seq strictly greater than `watermark` (the
        snapshot's folded-through seq; -1 replays everything)."""
        for rec in self.scan():
            # replay is a per-record loop over arbitrarily large logs:
            # honor an armed deadline / fault site between records
            checkpoint("wal.replay_record")
            if rec[0] > watermark:
                yield rec

    # -- truncation (post-compaction space reclamation) -----------------------

    def truncate_through(self, watermark: int) -> int:
        """Drop records with seq <= watermark (they are folded into the
        persisted snapshot).  Pure space reclamation: replay filters by
        the snapshot watermark anyway, so a crash that skips this loses
        nothing.  Rewrites via tmp + os.replace — the log must never be
        mid-rewrite on disk.  Returns the records kept."""
        kept = 0
        tmp = self.path + ".tmp"
        with self._lock:
            if self._fh is not None and not self._fh.closed:
                self._fh.close()
            records: List[bytes] = []
            for seq, ds, cols, n in self.scan():
                checkpoint("wal.replay_record")
                if seq > watermark:
                    payload = encode_batch(ds, cols, n)
                    records.append(
                        _HEAD.pack(MAGIC, len(payload), seq,
                                   zlib.crc32(payload)) + payload
                    )
                    kept += 1
            os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
            with open(tmp, "wb") as fh:
                fh.write(b"".join(records))
                fh.flush()
                os.fsync(fh.fileno())
            os.replace(tmp, self.path)
        return kept

    def close(self) -> None:
        with self._lock:
            if self._fh is not None and not self._fh.closed:
                self._fh.close()
