"""Parallel sharded bulk ingest (ISSUE 6 tentpole layer (a)).

The serial seed path (`catalog.segment.build_datasource[_streamed]`)
dictionary-encodes every row of every string dimension by binary search
against the sorted value domain — O(rows · log(card)) *string* compares —
and runs one chunk at a time.  This module replaces the bulk-load path
with a two-phase sharded pipeline:

* **Phase 1 — dictionary build.**  Each (shard, dimension) worker
  factorizes its shard once (`pandas.factorize` / `numpy.unique`: one C
  hash pass -> local uniques + int inverse codes).  Local domains merge
  with a DETERMINISTIC sorted union (`merge_shard_values`) — the merged
  dictionary is a pure function of the row set, independent of shard
  count, worker scheduling, or arrival order — and each shard's inverse
  codes remap through a tiny per-shard LUT.  Per-row string work is gone:
  the only string comparisons left are over each shard's *unique* values.
* **Phase 2 — segment encode.**  Each shard (already `rows_per_segment`
  rows) feeds the EXISTING encoder (`catalog.segment.build_datasource`)
  with pre-encoded codes + the global dictionaries, producing the same
  padded, zone-mapped, tile-aligned segments the serial path builds —
  shards reassemble in order, so the output is row-identical to the
  serial result (modulo process-unique uids).

Workers are THREADS (`concurrent.futures.ThreadPoolExecutor`): the hot
loops are numpy C kernels that release the GIL, and threads sidestep the
fork-vs-live-JAX-backend deadlock hazard that keeps the old
`workloads.ssb` fork pool opt-in.  On a single-core host the pipeline
still wins on the factorize-once encode alone (measured ~6-10x on
string-heavy shards); on multi-core hosts shards overlap on top of that.
"""

from __future__ import annotations

import dataclasses
import os
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, Iterable, List, Mapping, Optional, Sequence, Tuple

import numpy as np

from ..catalog.segment import (
    DataSource,
    DimensionDict,
    NULL_ID,
    Segment,
    build_datasource,
)
from ..resilience import checkpoint
from ..utils.log import get_logger

log = get_logger("ingest.shard")

# shards a worker may hold finished ahead of the (ordered) consumer:
# bounds peak host memory at ~(workers + slack) encoded shards, the same
# one-chunk-peak contract build_datasource_streamed documents
_INFLIGHT_SLACK = 2


class _InlineExecutor:
    """Executor shim that runs submissions inline.  Used when the resolved
    worker count is 1: a real thread pool there buys no overlap (object-
    dtype factorize holds the GIL) and costs measurable handoff/GIL churn
    (~15% of a single-core bulk load) — the pipeline's single-core win is
    the factorize-once encode, not threads."""

    def __init__(self, max_workers=None):
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def submit(self, fn, *args):
        class _Done:
            __slots__ = ("_v",)

            def __init__(self, v):
                self._v = v

            def result(self):
                return self._v

        return _Done(fn(*args))


def sharded_ingest_workers(workers: Optional[int] = None) -> int:
    """Resolve the worker count: explicit arg > SD_INGEST_WORKERS env >
    cpu count.  Threads, so no fork-safety gate is needed."""
    if workers is not None and workers > 0:
        return int(workers)
    env = os.environ.get("SD_INGEST_WORKERS")
    if env is not None:
        try:
            n = int(env)
            if n > 0:
                return n
        except ValueError:
            pass
    return max(1, os.cpu_count() or 1)


def encode_dimension(arr: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Factorize ONE shard of one dimension: `(local_codes int32,
    local_values)` where `local_values` are the shard's distinct non-null
    values and `local_codes[i]` indexes into it (NULL_ID for nulls —
    None/NaN on object columns, negative raw values on integer columns,
    matching the serial encoder's null contract)."""
    import pandas as pd

    a = np.asarray(arr)
    if a.dtype.kind in ("i", "u"):
        uniq, inv = np.unique(a.astype(np.int64), return_inverse=True)
        codes = inv.astype(np.int32)
        n_neg = int(np.searchsorted(uniq, 0))  # negatives sort first
        if n_neg:
            codes = np.where(codes < n_neg, NULL_ID, codes - n_neg)
            uniq = uniq[n_neg:]
        return codes, uniq
    inv, uniq = pd.factorize(a)  # -1 for NaN/None: exactly NULL_ID
    return inv.astype(np.int32), np.asarray(uniq, dtype=object)


def global_codes(
    local_codes: np.ndarray, local_values, d: DimensionDict
) -> np.ndarray:
    """Remap a shard's local factorize codes into `d`'s global code space
    through a uniques-sized LUT — the only dictionary lookups paid are one
    per DISTINCT shard value, and those go through the dictionary's OWN
    vectorized encoders (searchsorted over the sorted domain), so the LUT
    build is O(uniques · log(card)), never a per-value linear scan.
    Values absent from `d` become NULL_ID (the serial encoder's
    out-of-domain contract)."""
    vals = np.asarray(local_values)
    if len(vals) == 0:
        lut = np.empty(1, dtype=np.int32)
    elif d.numeric_values is not None or (
        not d.values and vals.dtype.kind in "iu"
    ):
        lut = d.encode_numeric(vals.astype(np.int64))
    else:
        lut = d.encode(list(vals))
    out = np.where(
        local_codes >= 0, lut[np.maximum(local_codes, 0)], NULL_ID
    )
    return out.astype(np.int32)


def merge_shard_values(per_shard_values: Sequence) -> DimensionDict:
    """Deterministic dictionary merge: sorted union of the shards' local
    value domains — the same sorted-domain contract `DimensionDict.build`
    produces serially, independent of sharding."""
    seen: set = set()
    # graftlint: disable=ingest-discipline -- host set union over per-shard DISTINCT values, no row-scale work
    for vals in per_shard_values:
        for v in vals:
            if v is None or (isinstance(v, float) and v != v):
                continue
            seen.add(v)
    if seen and all(
        isinstance(v, (int, np.integer)) and not isinstance(v, bool)
        for v in seen
    ):
        return DimensionDict(values=tuple(sorted(int(v) for v in seen)))
    return DimensionDict(values=tuple(sorted(str(v) for v in seen)))


def _reshard(chunks: Iterable[Mapping], rows_per_shard: int):
    """Re-chunk an iterable of column mappings into exact
    `rows_per_shard`-row shards (tail shard may be short) — shard
    boundaries then coincide with segment boundaries, which is what makes
    the sharded output identical to the serial one."""
    buf: Optional[Dict[str, List[np.ndarray]]] = None
    buffered = 0
    # graftlint: disable=ingest-discipline -- zero-copy slicing/buffering only; every consumer checkpoints per shard
    for chunk in chunks:
        cols = {k: np.asarray(v) for k, v in chunk.items()}
        n = len(next(iter(cols.values()))) if cols else 0
        lo = 0
        while lo < n:
            take = min(n - lo, rows_per_shard - buffered)
            part = {k: v[lo:lo + take] for k, v in cols.items()}
            lo += take
            if buf is None and take == rows_per_shard:
                yield part  # zero-copy fast path: chunk aligned to shard
                continue
            if buf is None:
                buf = {k: [v] for k, v in part.items()}
            else:
                for k, v in part.items():
                    buf[k].append(v)
            buffered += take
            if buffered == rows_per_shard:
                yield {k: np.concatenate(v) for k, v in buf.items()}
                buf, buffered = None, 0
    if buf is not None:
        yield {k: np.concatenate(v) for k, v in buf.items()}


def _read_csv_file(path: str):
    """One CSV file -> (columns, per-file dicts): the native single-pass
    parse + dictionary encode (native/csv_decode.py) when the toolchain
    is built — string columns come back as int32 rank codes over the
    FILE's sorted domain — with the pandas decode as the always-available
    fallback (no prebuilt dictionaries)."""
    try:
        from ..native import csv_decode

        return csv_decode.read_csv_encoded(path)
    except Exception:  # fault-ok: pandas fallback below
        from ..catalog.ingest import to_columns

        return to_columns(path), {}


def build_datasource_from_csv(
    name: str,
    paths: Sequence[str],
    dimension_cols: Sequence[str],
    metric_cols: Sequence[str],
    time_col: Optional[str] = None,
    rows_per_segment: int = 1 << 22,
    dicts: Optional[Mapping[str, DimensionDict]] = None,
    workers: Optional[int] = None,
) -> DataSource:
    """Bulk-build a DataSource from CSV FILES, one file per phase-1 shard
    (ROADMAP 2(a) remainder: the native CSV decoder as a shard source).

    The native decoder's per-file output IS a finished phase-1 factorize:
    int32 rank codes over the file's sorted-unique domain — exactly the
    (local codes, local values) shape the sharded pipeline's factorize
    workers produce, so per-row string work never happens in Python at
    all.  Files parse in parallel (threads; the native parse and the
    pandas fallback both release the GIL in their hot loops), per-file
    domains merge with the same DETERMINISTIC sorted union as any other
    shard source, per-file codes remap through a uniques-sized LUT, and
    the remapped chunks feed `build_datasource_sharded` — output
    row/code/stats-identical to concatenating the files through the
    serial path.

    A dimension is taken on the pre-encoded fast path only when EVERY
    file produced a native dictionary for it; mixed-typed columns (and
    any column under a CALLER-supplied dictionary) decode back to domain
    values and re-encode through the normal phase-1 factorize, which is
    slower but always correct.  Time columns must already be numeric
    (epoch-ms), the same contract the dict/array ingest paths have."""
    workers = sharded_ingest_workers(workers)
    pool_cls = ThreadPoolExecutor if workers > 1 else _InlineExecutor
    paths = list(paths)
    if not paths:
        raise ValueError("csv ingest needs at least one file")
    dicts = dict(dicts) if dicts else {}
    with pool_cls(max_workers=workers) as pool:
        futs = [pool.submit(_read_csv_file, p) for p in paths]
        files = []
        for fut in futs:
            checkpoint("ingest.csv_file")
            files.append(fut.result())
    # dimensions every file pre-encoded (and no caller dict overrides):
    # merge the per-file domains and LUT-remap — phase 1 is already done
    native_dims = [
        d
        for d in dimension_cols
        if d not in dicts and all(d in fdicts for _, fdicts in files)
    ]
    for d in native_dims:
        dicts[d] = merge_shard_values(
            [fdicts[d].values for _, fdicts in files]
        )
    chunks: List[Dict[str, np.ndarray]] = []
    for cols, fdicts in files:
        cols = dict(cols)
        for d, fdict in fdicts.items():
            if d in native_dims:
                cols[d] = global_codes(
                    np.asarray(cols[d]),
                    np.asarray(fdict.values, dtype=object),
                    dicts[d],
                )
            else:
                # mixed typing across files, or a caller dictionary:
                # codes are ranks over THIS file's domain only — decode
                # to values and let phase 1 re-encode them correctly
                cols[d] = fdict.decode(np.asarray(cols[d]))
        chunks.append(cols)
    return build_datasource_sharded(
        name,
        chunks,
        dimension_cols=dimension_cols,
        metric_cols=metric_cols,
        time_col=time_col,
        rows_per_segment=rows_per_segment,
        dicts=dicts,
        workers=workers,
    )


def build_datasource_sharded(
    name: str,
    source,
    dimension_cols: Sequence[str],
    metric_cols: Sequence[str],
    time_col: Optional[str] = None,
    rows_per_segment: int = 1 << 22,
    dicts: Optional[Mapping[str, DimensionDict]] = None,
    workers: Optional[int] = None,
) -> DataSource:
    """Bulk-build a DataSource on the sharded two-phase pipeline.

    `source` is a single column mapping OR an iterable of column-mapping
    chunks (the streamed-ingest shape).  Missing dictionaries are built in
    phase 1 (parallel per-shard factorize + deterministic merge) — a
    capability the serial streamed path lacks entirely (it demands global
    dictionaries up front).  Output segments hold the same rows, codes,
    dictionaries, and zone maps as the serial `build_datasource` result."""
    workers = sharded_ingest_workers(workers)
    pool_cls = ThreadPoolExecutor if workers > 1 else _InlineExecutor
    if isinstance(source, Mapping):
        source = [source]
    shards: List[Optional[Dict[str, np.ndarray]]] = list(
        _reshard(source, rows_per_segment)
    )
    if not shards:
        raise ValueError("sharded ingest produced no rows")
    dicts = dict(dicts) if dicts else {}

    # phase 1: every dimension without a caller dictionary gets factorized
    # per shard and merged — integer dims included (a per-shard dictionary
    # would not share a code space across shards)
    need = [d for d in dimension_cols if d not in dicts]
    # string-typed dims WITH a caller dictionary also pre-encode here (the
    # factorize-once path beats the serial per-row encode); pre-encoded
    # integer code columns pass through untouched
    pre = [
        d for d in dimension_cols
        if d not in need and np.asarray(shards[0][d]).dtype.kind in "OUS"
    ]
    encoded: Dict[Tuple[int, str], np.ndarray] = {}
    if need or pre:
        with pool_cls(max_workers=workers) as pool:
            futs = {
                (si, d): pool.submit(encode_dimension, shards[si][d])
                for si in range(len(shards))
                for d in need + pre
            }
            local: Dict[Tuple[int, str], Tuple[np.ndarray, np.ndarray]] = {}
            for key, fut in futs.items():
                checkpoint("ingest.dict_shard")
                local[key] = fut.result()
        for d in need:
            dicts[d] = merge_shard_values(
                [local[(si, d)][1] for si in range(len(shards))]
            )
        with pool_cls(max_workers=workers) as pool:
            remap_futs = {
                key: pool.submit(global_codes, codes, uniq, dicts[key[1]])
                for key, (codes, uniq) in local.items()
            }
            for key, fut in remap_futs.items():
                checkpoint("ingest.remap_shard")
                encoded[key] = fut.result()
        del local

    first_meta: List = []

    def encode_shard(si: int) -> List[Segment]:
        cols = dict(shards[si])
        for d in need + pre:
            cols[d] = encoded.pop((si, d))
        part = build_datasource(
            name,
            cols,
            dimension_cols=list(dimension_cols),
            metric_cols=list(metric_cols),
            time_col=time_col,
            rows_per_segment=rows_per_segment,
            dicts=dicts,
        )
        shards[si] = None  # release the raw shard promptly
        if not first_meta:
            first_meta.append(part.columns)
        return list(part.segments)

    segments: List[Segment] = []
    with pool_cls(max_workers=workers) as pool:
        pending: List = []
        si = 0
        n_shards = len(shards)
        while si < n_shards or pending:
            # graftlint: disable=ingest-discipline -- non-blocking submit bookkeeping; the enclosing drain loop checkpoints per shard
            while si < n_shards and len(pending) < workers + _INFLIGHT_SLACK:
                pending.append(pool.submit(encode_shard, si))
                si += 1
            # ordered reassembly: shard i's segments precede shard i+1's
            checkpoint("ingest.encode_shard")
            # graftlint: disable=ingest-discipline -- segment-id restamp of an already-encoded shard; the blocking wait above checkpoints
            for s in pending.pop(0).result():
                segments.append(
                    dataclasses.replace(
                        s, segment_id=f"{name}_{len(segments):06d}"
                    )
                )
    log.info(
        "sharded ingest %s: %d rows -> %d segments (%d workers)",
        name, sum(s.num_rows for s in segments), len(segments), workers,
    )
    return DataSource(
        name=name,
        columns=first_meta[0],
        dicts=dicts,
        segments=tuple(segments),
        time_column=time_col,
    )
