"""Real-time ingestion tier (ISSUE 6 / ROADMAP direction 2).

Three cooperating layers, the Druid realtime-node analog rebuilt for a
TPU-resident catalog:

* `ingest.shard` — **parallel sharded bulk ingest**: per-shard,
  per-column workers feeding the existing dictionary encoder
  (`catalog/segment.py`), with a deterministic sorted-union dictionary
  merge across shards, so bulk load scales with cores AND the per-row
  encode cost drops (factorize-once instead of per-row string
  searchsorted).
* `ingest.delta` — **append-only delta segments**: `append_rows` encodes
  streamed rows into `DeltaSegment`s published through the catalog, so
  fresh rows are queryable immediately; every executor merges delta
  partials with historical partials through the same mergeable-aggregate
  machinery the mesh and fallback paths already use.
* `ingest.compact` — **versioned background compaction**: deltas roll
  into tiled, padded historical segments; each publish bumps the
  per-datasource segment-set version (`catalog.cache`), which result and
  program caches key on.
"""

from .compact import Compactor  # noqa: F401
from .delta import IngestManager  # noqa: F401
from .shard import (  # noqa: F401
    build_datasource_sharded,
    encode_dimension,
    merge_shard_values,
    sharded_ingest_workers,
)
