"""Append-only delta segments: streamed rows, queryable immediately.

ISSUE 6 tentpole layer (b) — the Druid realtime-node analog.  Rows
arrive via `TPUOlapContext.append_rows` (or the server's
`POST /druid/v2/ingest/{datasource}` route), are dictionary-encoded
against the datasource's GLOBAL dictionaries, and publish as
`DeltaSegment`s in a new immutable DataSource snapshot through
`MetadataCache.put` — so the very next `catalog.get()` (i.e. the very
next query) sees them.  Staleness is bounded by construction: zero
published-but-invisible rows, ever.

Why this is safe by construction: every aggregate in the engine is a
mergeable partial state (Partial Partial Aggregates, arXiv:2603.26698),
and every executor — fused dense programs, the sparse/adaptive tiers,
the SPMD mesh, the host fallback — already merges per-segment partials.
A delta segment is just one more (small) segment in scope, so delta and
historical partials merge through the same machinery with exact
semantics, device-side (the computation-pushdown argument of
arXiv:2312.15405: fresh rows are not punted to the host).

Appended values are DOMAIN VALUES (strings for string dimensions, the
actual numbers for numeric ones), never codes: codes are rank-assigned
and shift when dictionaries extend, so they are not a stable wire
currency.

Novel dimension values: dictionaries are datasource-global and sorted
(range pushdown and zone maps lean on code order), so a novel value
extends the dictionary via `catalog.segment.extend_dict` — a sorted
superset whose old->new LUT is strictly monotone — and historical (and
earlier delta) segments remap their codes through the LUT
(`remap_segment_codes`, an O(rows) int gather per affected dimension).
Remapped segments carry fresh uids, so device residency and compiled
programs can never serve stale codes; the dictionary's `content_key`
change invalidates every program/result cache keyed on the schema
signature.  Appends with known values (the steady state once
dictionaries converge) touch nothing historical.

Concurrency: one RLock per datasource buffer.  All delta mutation
happens under it (graftlint ingest-discipline/GL1501 enforces this);
queries are lock-free — they hold an immutable DataSource snapshot from
the catalog, so an append mid-query is simply not visible to it.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Dict, List, Mapping, Optional, Sequence, Tuple

import numpy as np

from ..catalog.cache import MetadataCache
from ..catalog.segment import (
    NULL_ID,
    ROW_PAD,
    DataSource,
    DimensionDict,
    Segment,
    as_delta,
    build_datasource,
    extend_dict,
    remap_segment_codes,
)
from ..obs import (
    SPAN_INGEST,
    SPAN_INGEST_ENCODE,
    SPAN_ROLLUP,
    record_ingest,
    record_rollup,
    span,
)
from ..resilience import checkpoint
from ..utils.granularity import granularity_period_ms
from ..utils.log import get_logger

log = get_logger("ingest.delta")


class _DeltaBuffer:
    """Per-datasource append serialization point: the RLock every delta
    mutation (append, dictionary extension, compaction swap) runs under,
    plus the monotonic delta sequence counter.  Fields mutate ONLY under
    `_lock` (graftlint ingest-discipline/GL1501)."""

    def __init__(self):
        self._lock = threading.RLock()
        self._next_seq = 0

    def next_seq(self) -> int:
        with self._lock:
            seq = self._next_seq
            self._next_seq += 1
            return seq


class IngestManager:
    """Owns streamed ingest for one context: per-datasource delta buffers,
    the append path, and the locking surface compaction shares.

    Mutation is serialized per datasource; publication goes through
    `MetadataCache.put` exclusively, so every visible change carries a
    bumped datasource version (graftlint ingest-discipline/GL1503)."""

    def __init__(self, catalog: MetadataCache, config=None):
        self.catalog = catalog
        self.config = config
        self._lock = threading.Lock()
        self._buffers: Dict[str, _DeltaBuffer] = {}
        # eviction hook: called with the uids of segments that left the
        # published set (the engine drops their device residency)
        self.on_segments_dropped = None
        # durable-storage hook (storage.DurableStorage, ISSUE 13): when
        # attached, every append journals its normalized batch to the
        # per-datasource WAL — fsync'd — BEFORE the publish below, so an
        # ack implies durability.  None = the pre-ISSUE-13 in-process
        # tier (nothing survives a restart).
        self.storage = None

    def _seal_rows(self) -> int:
        return int(getattr(self.config, "delta_seal_rows", 1 << 16) or 1 << 16)

    def buffer(self, name: str) -> _DeltaBuffer:
        with self._lock:
            buf = self._buffers.get(name)
            if buf is None:
                buf = self._buffers[name] = _DeltaBuffer()
            return buf

    def delta_rows(self, name: str) -> int:
        ds = self.catalog.get(name)
        return ds.delta_rows if ds is not None else 0

    def _dropped(self, uids) -> None:
        hook = self.on_segments_dropped
        if hook is not None and uids:
            try:
                hook(frozenset(uids))
            except Exception:  # fault-ok: eviction is advisory, never fatal
                log.warning("segment-drop hook failed", exc_info=True)

    # -- the append path -----------------------------------------------------

    def append_rows(self, name: str, rows) -> dict:
        """Append streamed rows to a registered datasource.

        `rows` is a list of row dicts (the wire shape) or a mapping of
        row-aligned columns.  Missing dimensions fill with null and
        missing metrics with 0; unknown columns are rejected — streamed
        rows cannot widen a schema.  Returns an ack carrying the appended
        row count and the new datasource version."""
        buf = self.buffer(name)
        with buf._lock, span(SPAN_INGEST, datasource=name):
            ds = self.catalog.get(name)
            if ds is None:
                raise KeyError(f"unknown datasource {name!r}")
            cols, n = _normalize_rows(ds, rows)
            if n == 0:
                return {
                    "appended": 0,
                    "datasourceVersion": ds.version,
                    "totalRows": ds.num_rows,
                }
            # ingest-time rollup BEFORE the journal point: the WAL stores
            # (and boot replays) the already-rolled batch, so the rollup
            # shrinks durable volume too, not just the delta scan
            cols, n_stored = rollup_batch(ds, cols, n)
            # journal-before-publish (storage-discipline/GL2001): once
            # this returns, the batch is fsync-durable — a crash at any
            # later point replays it; a crash before it never acked
            self._journal(name, cols, n_stored)
            with span(SPAN_INGEST_ENCODE, rows=n_stored):
                ds2, dropped = self._append_encoded(ds, cols, buf)
            published = self.catalog.put(ds2)
            self._dropped(dropped)
            record_ingest(name, n, "ok")
            return {
                "appended": n,
                "datasourceVersion": published.version,
                "totalRows": published.num_rows,
            }

    def _journal(self, name: str, cols: Dict[str, np.ndarray],
                 n: int) -> Optional[int]:
        """WAL journal point of the append path (no-op without an
        attached durable-storage tier).  Caller holds the buffer lock."""
        storage = self.storage
        if storage is None:
            return None
        return storage.journal_append(name, cols, n)

    def replay_batch(
        self, name: str, cols: Dict[str, np.ndarray]
    ) -> DataSource:
        """Boot-time WAL replay of one journaled batch: the exact
        `_append_encoded` path appends use — dictionary extension,
        remap, encode, seq stamping — WITHOUT re-journaling (the record
        is already durable) and without an ack.  Replayed state is
        therefore code-identical to what the pre-crash process
        published."""
        buf = self.buffer(name)
        with buf._lock:
            ds = self.catalog.get(name)
            if ds is None:
                raise KeyError(f"unknown datasource {name!r}")
            ds2, dropped = self._append_encoded(ds, cols, buf)
            published = self.catalog.put(ds2)
            self._dropped(dropped)
            return published

    def _append_encoded(
        self, ds: DataSource, cols: Dict[str, np.ndarray], buf: _DeltaBuffer
    ) -> Tuple[DataSource, frozenset]:
        """Encode one normalized batch into DeltaSegments spliced onto a
        new snapshot.  Returns (snapshot, uids of replaced segments) —
        the caller publishes and evicts.  Caller holds the buffer lock."""
        dim_names = [c.name for c in ds.columns if c.is_dimension]
        met_names = [c.name for c in ds.columns if c.is_metric]

        # dictionary extension first: novel values shift the code space,
        # and EVERY already-encoded segment (historical + delta) must
        # remap before the new rows encode against the extended dicts
        dicts = dict(ds.dicts)
        luts: Dict[str, np.ndarray] = {}
        for d in dim_names:
            new_dict, lut = extend_dict(
                dicts[d], _domain_values(cols[d], dicts[d])
            )
            if lut is not None:
                dicts[d] = new_dict
                luts[d] = lut
        segments: Tuple[Segment, ...] = ds.segments
        dropped: frozenset = frozenset()
        if luts:
            cards = {d: dicts[d].cardinality for d in luts}
            log.info(
                "append to %s extends dictionaries %s; remapping %d "
                "segments", ds.name, sorted(luts), len(segments),
            )
            remapped: List[Segment] = []
            for seg in segments:
                # O(segments) gather passes: honor an armed deadline
                # between segments, same as the query-side loops
                checkpoint("ingest.remap_segment")
                remapped.append(remap_segment_codes(seg, luts, cards))
            dropped = frozenset(s.uid for s in segments)
            segments = tuple(remapped)

        # encode VALUES -> codes explicitly (the int-with-dict fast path
        # in build_datasource means "already codes", which appended domain
        # values are not), then build padded delta segments through the
        # existing encoder's pre-encoded path
        enc = dict(cols)
        for d in dim_names:
            enc[d] = _encode_values(cols[d], dicts[d])
        part = build_datasource(
            ds.name,
            enc,
            dimension_cols=dim_names,
            metric_cols=met_names,
            time_col=ds.time_column,
            rows_per_segment=max(self._seal_rows(), ROW_PAD),
            dicts=dicts,
        )
        fresh = []
        # graftlint: disable=ingest-discipline -- per-segment seq stamping; the encode above is the real work
        for s in part.segments:
            seq = buf.next_seq()
            fresh.append(
                as_delta(
                    dataclasses.replace(
                        s, segment_id=f"{ds.name}_delta_{seq:06d}"
                    ),
                    seq=seq,
                )
            )
        return (
            dataclasses.replace(
                ds, dicts=dicts, segments=segments + tuple(fresh)
            ),
            dropped,
        )


def rollup_batch(
    ds: DataSource, cols: Dict[str, np.ndarray], n: int
) -> Tuple[Dict[str, np.ndarray], int]:
    """Pre-aggregate one normalized append batch under the datasource's
    declared rollup granularity (ISSUE 13 tentpole (d)).

    Time truncates to its granularity bucket; rows group by (every
    dimension, bucket); metrics SUM — the Druid ingest-spec `rollup`
    contract.  Runs BEFORE the WAL journal point, so durable volume and
    query-time delta scans both shrink.  Identity when no granularity is
    declared.  Deterministic (sorted group order), so a replayed WAL
    batch — journaled post-rollup — re-encodes byte-identically."""
    gran = getattr(ds, "rollup_granularity", None)
    if not gran or n == 0:
        return cols, n
    period = granularity_period_ms(gran)
    if period is None or ds.time_column is None:
        # calendar granularities and timeless tables are rejected at
        # registration; reaching here means the snapshot predates the
        # check — fail safe by storing exact rows
        return cols, n
    import pandas as pd

    with span(SPAN_ROLLUP, datasource=ds.name, rows_in=n):
        bucket = (
            np.asarray(cols[ds.time_column], dtype=np.int64) // period
        ) * period
        dim_names = [c.name for c in ds.columns if c.is_dimension]
        met_names = [c.name for c in ds.columns if c.is_metric]
        frame = {d: cols[d] for d in dim_names}
        frame["__bucket__"] = bucket
        mets = pd.DataFrame({m: cols[m] for m in met_names})
        keyed = pd.concat([pd.DataFrame(frame), mets], axis=1)
        grouped = keyed.groupby(
            dim_names + ["__bucket__"], dropna=False, sort=True,
            as_index=False,
        )[met_names].sum()
        out: Dict[str, np.ndarray] = {}
        for d in dim_names:
            a = grouped[d].to_numpy()
            if a.dtype.kind in "Of":
                src = np.asarray(cols[d])
                if src.dtype.kind == "O":
                    # groupby surfaces nulls as NaN; the encode path
                    # expects object columns with None
                    a = np.asarray(
                        [None if pd.isna(v) else v for v in a],
                        dtype=object,
                    )
                elif src.dtype.kind in "iu" and a.dtype.kind == "f":
                    a = a.astype(src.dtype)
            out[d] = a
        out[ds.time_column] = grouped["__bucket__"].to_numpy(np.int64)
        for m in met_names:
            a = grouped[m].to_numpy()
            src = np.asarray(cols[m])
            if a.dtype != src.dtype:
                a = a.astype(src.dtype)
            out[m] = a
        n_out = len(grouped)
        record_rollup(ds.name, n, n_out)
    return out, n_out


def _domain_values(col: np.ndarray, d: DimensionDict) -> list:
    """The distinct candidate domain values of an appended column (for
    novel-value detection): raw values for string dictionaries, int64
    values (negatives = null, excluded) for numeric ones."""
    if d.numeric_values is not None or (
        not d.values and np.asarray(col).dtype.kind in "iuf"
    ):
        a = _as_int64(col)
        return [int(v) for v in np.unique(a[a >= 0])]
    import pandas as pd

    arr = np.asarray(col, dtype=object)
    return [v for v in pd.unique(arr) if not pd.isna(v)]


def _encode_values(col: np.ndarray, d: DimensionDict) -> np.ndarray:
    """Appended domain values -> global int32 codes."""
    if d.numeric_values is not None or (
        not d.values and np.asarray(col).dtype.kind in "iuf"
    ):
        return d.encode_numeric(_as_int64(col))
    return d.encode(list(np.asarray(col, dtype=object)))


def _as_int64(col) -> np.ndarray:
    """Object/float/int column -> int64 with nulls as NULL_ID."""
    a = np.asarray(col)
    if a.dtype.kind == "O":
        import pandas as pd

        mask = pd.isna(a)
        out = np.full(len(a), NULL_ID, dtype=np.int64)
        if (~mask).any():
            out[~mask] = np.asarray(
                [int(v) for v in a[~mask]], dtype=np.int64
            )
        return out
    if a.dtype.kind == "f":
        out = np.where(np.isnan(a), NULL_ID, a).astype(np.int64)
        return out
    return a.astype(np.int64)


def _normalize_rows(
    ds: DataSource, rows
) -> Tuple[Dict[str, np.ndarray], int]:
    """Wire rows -> row-aligned columns covering the datasource schema.

    Accepts a list of row dicts or a mapping of columns.  Unknown column
    names raise (schema is fixed at registration); missing dimensions
    fill with null, missing metrics with 0, and a missing time column is
    an error when the datasource has one (interval pruning would
    misplace the rows)."""
    known = {c.name for c in ds.columns}
    if isinstance(rows, Mapping):
        cols_in = {k: np.asarray(v) for k, v in rows.items()}
        lens = {len(v) for v in cols_in.values()}
        if len(lens) > 1:
            raise ValueError(f"ragged append columns: lengths {sorted(lens)}")
        n = lens.pop() if lens else 0
    elif isinstance(rows, Sequence) and not isinstance(rows, (str, bytes)):
        keys: List[str] = []
        for r in rows:
            if not isinstance(r, Mapping):
                raise ValueError("append rows must be objects")
            for k in r:
                if k not in keys:
                    keys.append(k)
        n = len(rows)
        cols_in = {
            k: np.asarray([r.get(k) for r in rows], dtype=object)
            for k in keys
        }
    else:
        raise ValueError(
            f"unsupported append payload type {type(rows).__name__}"
        )
    unknown = sorted(set(cols_in) - known)
    if unknown:
        raise ValueError(
            f"append names unknown columns {unknown}; datasource "
            f"{ds.name!r} schema is fixed at registration"
        )
    if n == 0:
        return {}, 0  # empty append: an ack, not a schema error
    out: Dict[str, np.ndarray] = {}
    for c in ds.columns:
        v = cols_in.get(c.name)
        if c.kind == "time":
            if v is None:
                raise ValueError(
                    f"append is missing time column {c.name!r}"
                )
            out[c.name] = _coerce_time(v)
        elif c.is_metric:
            if v is None:
                v = np.zeros(n)
            a = np.asarray(v)
            if a.dtype.kind == "O":
                a = a.astype(np.float64)
            # match the REGISTERED metric dtype: a "long" metric appended
            # as floats must land int32 like its historical siblings, or
            # delta and historical partials would accumulate in different
            # arithmetic
            if c.dtype == "long" and a.dtype.kind == "f":
                a = np.where(np.isnan(a), 0, a).astype(np.int64)
            elif c.dtype == "double" and a.dtype.kind in "iu":
                a = a.astype(np.float64)
            out[c.name] = a
        else:  # dimension
            if v is None:
                d = ds.dicts.get(c.name)
                if d is not None and d.numeric_values is not None:
                    v = np.full(n, NULL_ID, dtype=np.int64)
                else:
                    v = np.full(n, None, dtype=object)
            out[c.name] = np.asarray(v)
    return out, n


def _coerce_time(v) -> np.ndarray:
    """Time values -> int64 epoch millis (ISO strings, datetimes, or raw
    millis — the shapes Druid ingest specs accept).  Null/unparseable
    values RAISE: a silently-NaT row would carry INT64_MIN millis and be
    permanently misplaced by interval pruning."""
    a = np.asarray(v)
    if a.dtype.kind == "O":
        import pandas as pd

        if pd.isna(a).any():
            raise ValueError("append has null values in the time column")
    if a.dtype.kind in ("i", "u"):
        return a.astype(np.int64)
    if a.dtype.kind == "f":
        if np.isnan(a).any():
            raise ValueError("append has null values in the time column")
        return a.astype(np.int64)
    if a.dtype.kind != "M":
        try:
            a = np.asarray(a, dtype="datetime64[ms]")
        except Exception as e:
            raise ValueError(f"unparseable time values in append: {e}")
    out = a.astype("datetime64[ms]").astype(np.int64)
    if np.isnat(a.astype("datetime64[ms]")).any():
        raise ValueError("append has null/NaT values in the time column")
    return out
