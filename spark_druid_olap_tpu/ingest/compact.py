"""Versioned background compaction (ISSUE 6 tentpole layer (c)).

Delta segments are deliberately small (appends must be cheap and visible
immediately), but a query over N tiny deltas pays N segments of dispatch
and padding overhead.  The compactor rolls a datasource's accumulated
`DeltaSegment`s into tiled, padded HISTORICAL segments — the same
`rows_per_segment`-sized, zone-mapped shards bulk ingest produces — and
publishes the swap through `MetadataCache.put`, which bumps the
datasource's monotonic segment-set version.  Result and program caches
key on that version / the segment uid set, so a compaction invalidates
exactly what it must (the hook ROADMAP direction 1's result cache
consumes), while the row set — and therefore every query answer — is
preserved verbatim.

Compaction runs under the SAME per-datasource ingest lock appends use:
an append and a compaction can never interleave their read-modify-write
of the segment list.  Queries never block — they hold immutable
snapshots.  Dropped delta uids feed the engine-eviction hook so device
residency is reclaimed promptly instead of waiting for LRU pressure.

The background worker is a daemon thread with a cooperative stop event;
every sweep honors deadline checkpoints (`resilience.checkpoint`) — the
graftlint ingest-discipline pass (GL1502) enforces that contract on
these loops.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import List, Optional, Tuple

import numpy as np

from ..catalog.segment import (
    DataSource,
    DeltaSegment,
    Segment,
    build_datasource,
)
from ..obs import SPAN_COMPACT, record_compaction, span
from ..resilience import checkpoint
from ..utils.log import get_logger
from .delta import IngestManager

log = get_logger("ingest.compact")


class Compactor:
    """Rolls delta segments into historical segments, with an optional
    background sweep thread."""

    def __init__(
        self,
        ingest: IngestManager,
        rows_per_segment: int = 1 << 19,
        min_delta_rows: int = 0,
        interval_s: float = 5.0,
        min_delta_segments: int = 64,
        sys_retention_s: float = 0.0,
    ):
        self.ingest = ingest
        self.rows_per_segment = int(rows_per_segment)
        self.min_delta_rows = int(min_delta_rows)
        # a trickle of tiny appends accretes SEGMENTS (each padded to
        # ROW_PAD) long before it accretes rows — the sweep must gate on
        # both, or a 1-row/s feed would pile up padded deltas forever
        # while staying under the row threshold
        self.min_delta_segments = max(1, int(min_delta_segments))
        self.interval_s = float(interval_s)
        # `__sys` telemetry retention (config.sys_retention_s): the
        # sweep drops whole aged rollup segments; 0 keeps everything
        self.sys_retention_s = float(sys_retention_s)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()
        self.compactions_total = 0
        # durable-storage hook (storage.DurableStorage, ISSUE 13): when
        # attached, a compaction flushes the folded snapshot to disk
        # (atomic rename), GCs retired segment files strictly AFTER the
        # rename commits, and truncates the WAL through the folded
        # watermark — all under the same per-datasource ingest lock.
        self.storage = None

    # -- one datasource ------------------------------------------------------

    def compact(self, name: str) -> dict:
        """Compact `name`'s delta segments now.  Returns a summary dict
        ({"compacted_rows": 0, ...} when there was nothing to do)."""
        buf = self.ingest.buffer(name)
        with buf._lock, span(SPAN_COMPACT, datasource=name):
            ds = self.ingest.catalog.get(name)
            if ds is None:
                raise KeyError(f"unknown datasource {name!r}")
            deltas = ds.delta_segments()
            if not deltas:
                return {
                    "datasource": name,
                    "compacted_rows": 0,
                    "delta_segments": 0,
                    "datasourceVersion": ds.version,
                }
            rolled, absorbed = self._roll(ds, deltas)
            keep = list(ds.historical_segments())
            if absorbed:  # _roll only ever absorbs the undersized tail
                keep = keep[: -len(absorbed)]
            base = len(keep)
            segments: List[Segment] = keep + [
                dataclasses.replace(
                    s, segment_id=f"{name}_{base + i:06d}"
                )
                for i, s in enumerate(rolled)
            ]
            published = self.ingest.catalog.put(
                dataclasses.replace(ds, segments=tuple(segments))
            )
            dropped = frozenset(
                s.uid for s in list(deltas) + list(absorbed)
            )
            self.ingest._dropped(dropped)
            if self.storage is not None:
                # still under the buffer lock: no append can extend the
                # WAL between "every delta is folded into `published`"
                # and the watermark the flush truncates through
                self.storage.flush_locked(name, published)
        with self._lock:
            self.compactions_total += 1
        n_rows = sum(s.num_rows for s in deltas)
        record_compaction(name, n_rows, len(deltas))
        log.info(
            "compacted %s: %d delta segments (%d rows) -> %d historical",
            name, len(deltas), n_rows, len(rolled),
        )
        return {
            "datasource": name,
            "compacted_rows": n_rows,
            "delta_segments": len(deltas),
            "historical_segments_out": len(rolled),
            "datasourceVersion": published.version,
        }

    def _roll(
        self, ds: DataSource, deltas: Tuple[DeltaSegment, ...]
    ) -> Tuple[List[Segment], List[Segment]]:
        """Concatenate delta rows (plus an undersized historical tail, so
        repeated append/compact cycles converge to full tiles instead of
        accreting slivers) and re-segment them at `rows_per_segment`.
        Codes are already global — this is pure array splicing, no
        re-encode.  Returns (new historical segments, absorbed tail)."""
        absorbed: List[Segment] = []
        hist = list(ds.historical_segments())
        if hist and hist[-1].num_rows < self.rows_per_segment // 2:
            absorbed.append(hist[-1])
        parts: List[Segment] = absorbed + list(deltas)
        dim_names = [c.name for c in ds.columns if c.is_dimension]
        met_names = [c.name for c in ds.columns if c.is_metric]
        cols = {}
        for name in dim_names + met_names:
            pieces = []
            for s in parts:
                # O(delta rows) splice: keep the deadline honest while a
                # large backlog drains (ingest-discipline/GL1502)
                checkpoint("compact.splice_segment")
                pieces.append(np.asarray(s.column(name))[s.valid])
            cols[name] = np.concatenate(pieces)
        if ds.time_column is not None:
            pieces = []
            for s in parts:
                checkpoint("compact.splice_segment")
                pieces.append(np.asarray(s.time)[s.valid])
            cols[ds.time_column] = np.concatenate(pieces)
        part = build_datasource(
            ds.name,
            cols,
            dimension_cols=dim_names,
            metric_cols=met_names,
            time_col=ds.time_column,
            rows_per_segment=self.rows_per_segment,
            dicts=dict(ds.dicts),
        )
        return list(part.segments), absorbed

    # -- age-based retention (`__sys` telemetry ring) ------------------------

    def retire_aged(
        self, name: str, retention_s: float,
        now_ms: Optional[int] = None,
    ) -> dict:
        """Drop every HISTORICAL segment of `name` whose newest row is
        older than `retention_s` seconds.  Whole segments only — the
        second-granularity `__sys` rollup makes segments time-local, so
        age-out never needs a partial rewrite; delta segments are left
        for normal compaction to fold first (dropping an unfolded delta
        would resurrect its rows from the WAL on recovery).  Runs under
        the same per-datasource ingest lock appends and compactions
        take, and flushes the shrunk snapshot through the storage tier's
        rename-then-GC commit protocol when one is attached."""
        if retention_s <= 0:
            return {"datasource": name, "dropped_segments": 0}
        if now_ms is None:
            now_ms = int(time.time() * 1e3)
        cutoff_ms = now_ms - retention_s * 1e3
        buf = self.ingest.buffer(name)
        with buf._lock:
            ds = self.ingest.catalog.get(name)
            if ds is None or ds.time_column is None:
                return {"datasource": name, "dropped_segments": 0}
            keep: List[Segment] = []
            drop = []
            for s in ds.segments:
                checkpoint("compact.sweep_datasource")
                t = s.time
                if t is None or isinstance(s, DeltaSegment):
                    keep.append(s)
                    continue
                tv = np.asarray(t)[s.valid]
                if tv.size and float(tv.max()) < cutoff_ms:
                    drop.append(s)
                else:
                    keep.append(s)
            if not drop:
                return {"datasource": name, "dropped_segments": 0}
            published = self.ingest.catalog.put(
                dataclasses.replace(ds, segments=tuple(keep))
            )
            self.ingest._dropped(frozenset(s.uid for s in drop))
            if self.storage is not None:
                self.storage.flush_locked(name, published)
        n_rows = sum(s.num_rows for s in drop)
        log.info(
            "retired %d aged segment(s) (%d rows) from %s "
            "(retention %.0fs)", len(drop), n_rows, name, retention_s,
        )
        return {
            "datasource": name,
            "dropped_segments": len(drop),
            "dropped_rows": n_rows,
            "datasourceVersion": published.version,
        }

    def _retire_sys(self) -> dict:
        from ..obs.telemetry import SYS_TABLE

        if self.ingest.catalog.get(SYS_TABLE) is None:
            return {"datasource": SYS_TABLE, "dropped_segments": 0}
        return self.retire_aged(SYS_TABLE, self.sys_retention_s)

    # -- background sweep ----------------------------------------------------

    def run_pending(self) -> List[dict]:
        """One sweep: compact every datasource whose delta backlog meets
        `min_delta_rows` OR whose delta SEGMENT count meets
        `min_delta_segments` (tiny-append trickles accrete padded
        segments, not rows).  Safe to call concurrently with appends."""
        out = []
        for name in self.ingest.catalog.tables():
            checkpoint("compact.sweep_datasource")
            ds = self.ingest.catalog.get(name)
            if ds is None:
                continue
            pending = ds.delta_rows
            n_segs = len(ds.delta_segments())
            if pending and (
                pending >= self.min_delta_rows
                or n_segs >= self.min_delta_segments
            ):
                try:
                    out.append(self.compact(name))
                except Exception:  # fault-ok: one table must not stop the sweep
                    log.warning(
                        "background compaction of %s failed", name,
                        exc_info=True,
                    )
        if self.sys_retention_s > 0:
            try:
                res = self._retire_sys()
                if res.get("dropped_segments"):
                    out.append(res)
            except Exception:  # fault-ok: retention must not stop the sweep
                log.warning("__sys retention sweep failed", exc_info=True)
        return out

    def start(self) -> "Compactor":
        """Start the background sweep thread (idempotent)."""
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return self
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, name="sdol-compactor", daemon=True
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=10)

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.run_pending()
            except Exception:  # fault-ok: the sweep must survive any table
                log.warning("compaction sweep failed", exc_info=True)
