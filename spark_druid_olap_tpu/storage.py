"""Durable storage tier orchestration (ISSUE 13 tentpole).

Druid splits durability across deep storage (immutable segment files),
the coordinator's metadata store (which segment set is current), and
the indexing service's task logs (appends in flight).  The local analog
collapses those into one per-datasource directory under
`SessionConfig.storage_dir`:

    <storage_dir>/<datasource>/
        wal.log          append journal (ingest/wal.py): fsync'd,
                         checksummed, monotone seqs — journaled BEFORE
                         the delta publish, so an ack implies durability
        snapshot.json    the commit point (catalog/persist.py): schema,
                         dicts, zone maps, star, datasource version,
                         and the WAL watermark folded into the files
        v*_s*__*.npy     one raw column per file, named by the PR 6
                         per-datasource version (generations never
                         collide); np.load(mmap_mode="r") restores them
                         as the DISK residency tier

Lifecycle:

* `journal_append` — called by `IngestManager.append_rows` under the
  per-datasource buffer lock, before the publish.
* `flush_locked` — called by `Compactor.compact` (same lock) and by
  registration: snapshot rename commits, THEN retired files GC, THEN
  the WAL truncates through the folded watermark.  A crash between any
  two steps recovers exactly (the order is what the `compact.retire` /
  `persist.snapshot_rename` fault sites prove).
* `recover` — boot: per datasource, seed the catalog version floor,
  publish the mmap-loaded snapshot (no re-encode), then replay WAL
  records past the watermark through the SAME encode/extend-dict path
  appends use.  Runs under the ingest admission pool, and queries are
  503'd (Retry-After) while `replay_in_progress` — a recovering node
  looks busy, not wedged.
"""

from __future__ import annotations

import os
import threading
from typing import Dict, List, Optional

from .catalog.persist import (
    gc_snapshot_files,
    load_snapshot,
    save_snapshot,
    SNAPSHOT_NAME,
)
from .ingest.wal import WriteAheadLog
from .obs import (
    SPAN_SNAPSHOT_FLUSH,
    SPAN_WAL_APPEND,
    SPAN_WAL_REPLAY,
    record_snapshot_flush,
    record_snapshot_sweep,
    record_wal_append,
    record_wal_replay,
    span,
)
from .resilience import checkpoint
from .utils.log import get_logger

log = get_logger("storage")


def _safe_name(name: str) -> str:
    """Datasource names arrive from clients (the ingest route); the
    directory they key must not traverse."""
    return "".join(c if (c.isalnum() or c in "_-.") else "_" for c in name)


class DurableStorage:
    """One context's durable tier: per-datasource WALs + snapshots."""

    def __init__(self, root: str, catalog, ingest, fsync: bool = True):
        self.root = root
        self.catalog = catalog
        self.ingest = ingest
        self.fsync = bool(fsync)
        self._lock = threading.Lock()
        self._wals: Dict[str, WriteAheadLog] = {}
        # on-disk snapshot version per datasource (health: "what would a
        # restart restore"); updated at flush/recover
        self._snap_versions: Dict[str, int] = {}
        self.replay_in_progress = False
        self.last_recovery: Optional[dict] = None
        # background flush sweep (ISSUE 14 satellite): a timer thread
        # that flushes dirty deltas so durability doesn't wait for the
        # next registration/compaction
        self._sweep_stop = threading.Event()
        self._sweep_thread: Optional[threading.Thread] = None
        self._sweep_interval_s = 0.0
        self.sweeps_total = 0
        os.makedirs(root, exist_ok=True)

    # -- paths / handles -----------------------------------------------------

    def dir_for(self, name: str) -> str:
        return os.path.join(self.root, _safe_name(name))

    def wal(self, name: str) -> WriteAheadLog:
        with self._lock:
            w = self._wals.get(name)
            if w is None:
                w = self._wals[name] = WriteAheadLog(
                    os.path.join(self.dir_for(name), "wal.log"),
                    fsync=self.fsync,
                )
            return w

    # -- append journal ------------------------------------------------------

    def journal_append(self, name: str, cols, n: int) -> int:
        """Journal one normalized (post-rollup) batch durably; the
        caller (append path, holding the buffer lock) publishes only
        after this returns."""
        with span(SPAN_WAL_APPEND, datasource=name, rows=n):
            seq = self.wal(name).append(name, cols, n)
        record_wal_append(name, n)
        return seq

    # -- snapshot flush ------------------------------------------------------

    def flush(self, name: str) -> dict:
        """Public flush: takes the per-datasource ingest lock (appends
        and compactions serialize against it) then commits."""
        buf = self.ingest.buffer(name)
        with buf._lock:
            return self.flush_locked(name)

    def flush_locked(self, name: str, ds=None) -> dict:
        """Snapshot the CURRENT published datasource; caller holds the
        per-datasource buffer lock.  Ordering (the crash contract):
        column files -> snapshot rename (commit) -> retired-file GC ->
        WAL truncate.  The watermark is the WAL's last seq — correct
        because under the lock every journaled record is visible in
        `ds` (as delta segments or folded rows)."""
        if ds is None:
            ds = self.catalog.get(name)
        if ds is None:
            raise KeyError(f"unknown datasource {name!r}")
        star = self.catalog.star_schema(name)
        wal = self.wal(name)
        watermark = wal.last_seq
        directory = self.dir_for(name)
        with span(SPAN_SNAPSHOT_FLUSH, datasource=name,
                  segments=len(ds.segments)):
            snap = save_snapshot(ds, directory, star, watermark)
            # retirement strictly AFTER the rename committed: a crash on
            # either side of this line loses neither old nor new state
            removed = gc_snapshot_files(directory)
            wal.truncate_through(watermark)
        with self._lock:
            self._snap_versions[name] = ds.version
        record_snapshot_flush(name, len(ds.segments))
        log.info(
            "flushed %s snapshot v%d (%d segments, wal watermark %d, "
            "%d retired files)", name, ds.version, len(ds.segments),
            watermark, len(removed),
        )
        return snap

    def snapshot_version(self, name: str) -> Optional[int]:
        """The datasource version of the LAST snapshot generation this
        process flushed or booted.  Unlike the live catalog version
        (which every republish bumps process-locally), this number is
        identical in every process sharing the directory at the same
        snapshot generation — it is the version the cluster tier pins
        in the assignment manifest and checks on scatter (GL2301)."""
        with self._lock:
            v = self._snap_versions.get(name)
        return int(v) if v is not None else None

    # -- background flush sweep ----------------------------------------------

    def _dirty(self, name: str) -> bool:
        """A datasource is dirty when a restart would have to REPLAY:
        its published version moved past the on-disk snapshot (delta
        appends, or a registration that raced the last flush)."""
        ds = self.catalog.get(name)
        if ds is None:
            return False
        with self._lock:
            snap = self._snap_versions.get(name)
        return snap is None or ds.version > snap

    def sweep_once(self) -> dict:
        """One deterministic sweep pass: flush every dirty datasource.
        The timer loop calls this; tests and tools can call it directly
        for a no-thread, no-sleep check of the same code path."""
        flushed: List[str] = []
        for name in list(self.catalog.tables()):
            if not self._dirty(name):
                continue
            try:
                self.flush(name)
                flushed.append(name)
            except Exception:  # fault-ok: one table must not stop the sweep
                log.warning(
                    "snapshot sweep flush of %s failed", name,
                    exc_info=True,
                )
        with self._lock:
            self.sweeps_total += 1
        record_snapshot_sweep(len(flushed))
        return {"flushed": flushed}

    def start_flush_sweep(self, interval_s: float) -> "DurableStorage":
        """Start the background snapshot-flush thread (idempotent)."""
        self._sweep_interval_s = float(interval_s)
        with self._lock:
            if (
                self._sweep_thread is not None
                and self._sweep_thread.is_alive()
            ):
                return self
            self._sweep_stop.clear()
            self._sweep_thread = threading.Thread(
                target=self._sweep_loop,
                name="sdol-snapshot-flush",
                daemon=True,
            )
            self._sweep_thread.start()
        return self

    def stop_flush_sweep(self) -> None:
        self._sweep_stop.set()
        t = self._sweep_thread
        if t is not None:
            t.join(timeout=10)

    def _sweep_loop(self) -> None:
        while not self._sweep_stop.wait(self._sweep_interval_s):
            try:
                self.sweep_once()
            except Exception:  # fault-ok: the sweep must survive any table
                log.warning("snapshot flush sweep failed", exc_info=True)

    # -- boot recovery -------------------------------------------------------

    def _snapshot_dirs(self) -> List[str]:
        out = []
        for entry in sorted(os.listdir(self.root)):
            d = os.path.join(self.root, entry)
            if os.path.isdir(d) and os.path.exists(
                os.path.join(d, SNAPSHOT_NAME)
            ):
                out.append(d)
        return out

    def recover(self, resilience=None) -> List[str]:
        """Restore every persisted datasource: mmap snapshot load (no
        re-encode), catalog version seeding, then WAL replay through the
        live append path.  Returns the restored names."""
        restored: List[str] = []
        totals = {"datasources": 0, "replayed_records": 0,
                  "replayed_rows": 0}
        self.replay_in_progress = True
        try:
            for directory in self._snapshot_dirs():
                name = self._recover_one(directory, resilience, totals)
                if name is not None:
                    restored.append(name)
        finally:
            self.replay_in_progress = False
            self.last_recovery = totals
        return restored

    def _recover_one(self, directory: str, resilience, totals) -> Optional[str]:
        try:
            ds, star, watermark = load_snapshot(directory)
        except (OSError, ValueError) as e:
            log.warning("snapshot load failed for %s: %s", directory, e)
            return None
        name = ds.name
        with span(SPAN_WAL_REPLAY, datasource=name):
            # version floor FIRST: the republish below must stamp a
            # version strictly above anything the pre-crash process
            # acked, or restart-spanning caches could alias
            self.catalog.seed_version(name, ds.version)
            published = self.catalog.put(ds, star)
            buf = self.ingest.buffer(name)
            with buf._lock:
                # delta seq floor: snapshot-carried delta segments keep
                # their pre-crash seqs; replayed/new appends must not
                # collide with them in segment ids
                max_seq = max(
                    (s.seq for s in published.delta_segments()), default=-1
                )
                buf._next_seq = max(buf._next_seq, max_seq + 1)
            wal = self.wal(name)
            replayed = rows = 0
            # boot replay takes an ingest admission slot: a recovering
            # node's replay competes with (and is visible as) ingest
            # load, and the query routes 503 off replay_in_progress
            pool = getattr(resilience, "ingest_admission", None)
            acquired = pool.acquire() if pool is not None else False
            try:
                for seq, _, cols, n in wal.replay_after(watermark):
                    checkpoint("storage.replay_batch")
                    self.ingest.replay_batch(name, cols)
                    replayed += 1
                    rows += n
            finally:
                if acquired:
                    pool.release()
        with self._lock:
            self._snap_versions[name] = ds.version
        totals["datasources"] += 1
        totals["replayed_records"] += replayed
        totals["replayed_rows"] += rows
        record_wal_replay(name, replayed, rows)
        log.info(
            "recovered %s: snapshot v%d + %d WAL records (%d rows)",
            name, ds.version, replayed, rows,
        )
        return name

    # -- health --------------------------------------------------------------

    def state(self) -> dict:
        """The /status/health storage section: WAL sequence, last
        snapshot version, replay-in-progress, dirty-delta counts."""
        with self._lock:
            snap_versions = dict(self._snap_versions)
            wals = dict(self._wals)
        datasources = {}
        for name in self.catalog.tables():
            ds = self.catalog.get(name)
            if ds is None:
                continue
            wal = wals.get(name)
            datasources[name] = {
                "wal_last_seq": wal.last_seq if wal is not None else -1,
                "snapshot_version": snap_versions.get(name),
                # delta segments published since the last flush: what a
                # restart would REPLAY rather than mmap
                "dirty_delta_segments": len(ds.delta_segments()),
                "dirty_delta_rows": ds.delta_rows,
            }
        return {
            "enabled": True,
            "root": self.root,
            "replay_in_progress": self.replay_in_progress,
            "datasources": datasources,
            "last_recovery": self.last_recovery,
            "flush_sweep": {
                "running": (
                    self._sweep_thread is not None
                    and self._sweep_thread.is_alive()
                ),
                "interval_s": self._sweep_interval_s,
                "sweeps_total": self.sweeps_total,
            },
        }

    def close(self) -> None:
        # join the sweep BEFORE taking the lock: a mid-flush sweep pass
        # needs `self._lock` to stamp the snapshot version
        self.stop_flush_sweep()
        with self._lock:
            # graftlint: disable=storage-discipline -- metadata-only: closes O(datasources) file handles
            for w in self._wals.values():
                w.close()
