"""Approximate quantiles on TPU — per-group bottom-K random-priority value
samples (the `quantilesDoublesSketch` / APPROX_QUANTILE analog).

Reference parity: Druid's DataSketches quantiles aggregator
(`quantilesDoublesSketch` + `quantilesDoublesSketchToQuantile` post-agg,
SURVEY.md §2 aggregation-family row `[U]`) gives rank-error-bounded
quantile estimates with mergeable per-segment sketches.  The TPU-native
state here is simpler than KLL but has the same merge algebra: each row
draws a pseudo-random priority (hash of row position mixed with the value
bits — independent of the value's magnitude), and each group keeps the K
rows with the smallest priorities.  Bottom-K-by-random-priority is a
uniform sample without replacement, and the bottom-K of a union equals the
union of bottom-Ks re-trimmed to K — so per-segment partials merge exactly
like theta sketches (concat + sort-by-priority + take-K), across segments,
streams, and mesh devices alike.  Rank error ~ O(sqrt(p(1-p)/K)): K=1024
gives ~±1.5% rank error at the median.

TPU-first shape (SURVEY.md §7 hard-part #3 applies unchanged): no per-row
hash-table scatter — one lexsort by (group, priority), ranks from
searchsorted against group starts, a unique-index scatter into the [G, K]
state.  The state packs (priority, value-bits) into one int32[G, K+1, 2]
array — rows [0, K) are the sample, row K carries the TRUE per-group row
count N in its first component (counts sum on merge, so the finalized
sketch column reports N exactly, matching Druid's sketch finalization) —
so every existing plumbing layer (device_get pytrees, sketch-state dicts,
all_gather merges) handles it untouched.

When a group holds <= K rows the "sample" is the whole group and the
quantile is exact — the common OLAP case after selective filters.
"""

from __future__ import annotations

import functools
from typing import Mapping

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.hashing import hash_column

# int32 priority domain [0, 2^31); empty slots carry the max value so they
# sort last and never displace a real sample row
SENTINEL_P = np.int32(0x7FFFFFFF)


@functools.partial(jax.jit, static_argnames=("num_groups", "k"))
def _bottom_k_pairs(
    prio: jnp.ndarray,
    val: jnp.ndarray,
    gid: jnp.ndarray,
    mask: jnp.ndarray,
    num_groups: int,
    k: int,
) -> jnp.ndarray:
    """Keep the K (priority, value) pairs with smallest priority per group.

    Unlike theta's _bottom_k there is NO dedup: equal priorities are
    distinct rows and both belong in the sample."""
    R = prio.shape[0]
    ok = mask & (gid >= 0) & (gid < num_groups)
    g = jnp.where(ok, gid, num_groups)  # masked rows to trash group
    p = jnp.where(ok, prio, SENTINEL_P)
    order = jnp.lexsort((p, g))
    gs = g[order]
    ps = p[order]
    vs = val[order]
    starts = jnp.searchsorted(gs, jnp.arange(num_groups + 1, dtype=gs.dtype))
    rank = jnp.arange(R, dtype=jnp.int32) - starts[
        jnp.clip(gs, 0, num_groups)
    ].astype(jnp.int32)
    keep = (rank < k) & (gs < num_groups) & (ps != SENTINEL_P)
    flat = jnp.where(keep, gs * k + rank, num_groups * k)
    pout = (
        jnp.full((num_groups * k,), SENTINEL_P, jnp.int32)
        .at[flat]
        .set(ps, mode="drop")
    )
    vbits = jax.lax.bitcast_convert_type(vs, jnp.int32)
    vout = (
        jnp.zeros((num_groups * k,), jnp.int32).at[flat].set(
            vbits, mode="drop"
        )
    )
    sample = jnp.stack(
        [pout.reshape(num_groups, k), vout.reshape(num_groups, k)], axis=-1
    )
    # true per-group row count from the group boundaries (trash rows sort
    # past starts[G], so they never contribute)
    counts = (starts[1:] - starts[:-1]).astype(jnp.int32)[:num_groups]
    extra = jnp.stack(
        [counts, jnp.zeros((num_groups,), jnp.int32)], axis=-1
    )[:, None, :]
    return jnp.concatenate([sample, extra], axis=1)  # [G, K+1, 2]


def partial_quantiles(
    agg, cols: Mapping[str, jnp.ndarray], gid, mask, num_groups: int
) -> jnp.ndarray:
    """Per-group sample state int32[G, K+1, 2] for one segment/shard (rows
    [0, K) sample, row K the exact N counter)."""
    val = jnp.asarray(cols[agg.field_name]).astype(jnp.float32)
    R = val.shape[0]
    # priority must be independent of the value's magnitude but distinct
    # across (position, value) pairs: identical positions recur in every
    # segment/chunk (arange), so mixing in the value bits keeps repeated
    # layouts from sampling the same positions everywhere
    pos = jnp.arange(R, dtype=jnp.int32)
    h = hash_column(pos, seed=11) ^ hash_column(val, seed=13)
    prio = (h >> jnp.uint32(1)).astype(jnp.int32)
    return _bottom_k_pairs(prio, val, gid, mask, num_groups, agg.size)


@functools.partial(jax.jit, static_argnames=("k",))
def merge_states(a: jnp.ndarray, b: jnp.ndarray, k: int) -> jnp.ndarray:
    """Union-merge two int32[G, K+1, 2] states: bottom-K by priority of the
    concatenated samples (exactly the global bottom-K, the KMV merge
    property); the N counters in row K add."""
    cat = jnp.concatenate([a[:, :k, :], b[:, :k, :]], axis=1)  # [G, 2K, 2]
    order = jnp.argsort(cat[..., 0], axis=1)
    merged = jnp.take_along_axis(cat, order[..., None], axis=1)[:, :k, :]
    counts = a[:, k:, :] + b[:, k:, :]
    return jnp.concatenate([merged, counts], axis=1)


def merge_many(states, k: int) -> jnp.ndarray:
    acc = states[0]
    for s in states[1:]:
        acc = merge_states(acc, s, k)
    return acc


def sample_values(state: np.ndarray) -> np.ndarray:
    """float64[..., K] sample values with empty slots as NaN (drops the
    trailing N-counter row)."""
    s = np.asarray(state)[..., :-1, :]
    valid = s[..., 0] != SENTINEL_P
    vals = s[..., 1].astype(np.int32).view(np.float32).astype(np.float64)
    return np.where(valid, vals, np.nan)


def estimate(state: np.ndarray, fraction: float) -> np.ndarray:
    """Per-group quantile estimate from the sample (NaN for empty groups).

    Linear interpolation over the sorted sample — matches numpy's default
    quantile definition, so parity tests compare directly at n <= K."""
    vals = sample_values(state)
    with np.errstate(all="ignore"):
        import warnings

        with warnings.catch_warnings():
            warnings.simplefilter("ignore")  # all-NaN rows -> NaN quantile
            return np.nanquantile(vals, float(fraction), axis=-1)


def count(state: np.ndarray) -> np.ndarray:
    """TRUE rows aggregated per group (the sketch's N, exact — carried in
    the state's trailing counter row and summed across merges)."""
    s = np.asarray(state)
    return s[..., -1, 0].astype(np.int64)
