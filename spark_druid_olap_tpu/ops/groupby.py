"""Partial GroupBy aggregation on TPU — the engine the reference outsourced.

Reference parity: in spark-druid-olap the GroupBy work happens inside external
Druid historicals (per-segment partial aggregates) and the broker merges
partials (SURVEY.md §2 scatter-gather row, §3.3 `[U]`).  This module is the
per-device *historical*: it computes partial aggregate states for one shard of
rows.  `parallel/merge.py` is the *broker*: it merges partials across devices
with ICI collectives.

TPU-first design (SURVEY.md §7 hard-part #1 — "TPUs hate scatter"):

* **Dense one-hot matmul strategy** (default, the common OLAP case): group
  keys are dictionary codes with known cardinality, so the combined group id
  lives in a dense domain [0, G).  A row-block's one-hot matrix
  ``onehot[B, G] = (gid[:, None] == iota(G))`` contracted with the value block
  ``values[B, M]`` on the MXU gives exact per-group sums — an einsum, not a
  scatter.  `lax.scan` over row blocks keeps peak memory at B*G while XLA
  pipelines HBM reads.  min/max use the same match matrix with a masked
  where+reduce (VPU).  This is the standard TPU trick for segment reductions
  and maps 100% of the FLOPs onto the MXU.
* **Segment-scatter strategy** (fallback for very large G where a B×G block
  would blow VMEM/HBM): `jax.ops.segment_sum/min/max` — XLA scatter; slower
  per-row but memory-linear.  The cost model (plan/cost.py) picks the
  strategy from G; see `choose_block_rows`.

Determinism / parity (SURVEY.md §7 hard-part #2): block order inside the scan
is fixed and the matmul reduction order per block is fixed by XLA, so a given
(shard, block size) always produces bit-identical float sums; cross-device
merge order is fixed by the collective.  Tests compare against a float64 numpy
oracle with tight rtol; counts/min/max are exact.
"""

from __future__ import annotations

import functools
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

# One f32 VMEM tile is (8, 128); one-hot blocks are multiples of both.
_LANE = 128

# Above this combined cardinality the one-hot block no longer fits comfortably
# and we fall back to scatter.  2^17 groups * 1024 rows * 4B = 512MB/block at
# B=1024 — still too big, so the real bound is applied via choose_block_rows;
# this constant bounds G for the dense strategy overall.
DENSE_MAX_GROUPS = 1 << 17

# ESTIMATED dense-vs-scatter crossover for a v5e-class chip (no committed
# TPU artifact backs this yet — see BENCH_r*.json history; rounds 1-2 never
# reached the hardware).  The estimate follows the cost-model formula
# (G/128 <= 4 * scatter_cost_per_row); `plan/calibrate.py` replaces it with
# a measured value the first time it runs on the real backend, and the
# calibrated crossover is what the planner actually uses
# (SessionConfig.load_calibrated).
SCATTER_CUTOVER = 4096


def combine_group_ids(
    codes: Sequence[jnp.ndarray], cards: Sequence[int]
) -> Tuple[jnp.ndarray, int]:
    """Row-major combine N dictionary-code columns into one dense group id.

    gid = ((c0 * card1) + c1) * card2 + c2 ...   Null codes (-1) are clamped
    into slot 0 and must be masked by the caller (the engine adds a
    `code >= 0` conjunct to the filter mask unless nulls are grouped).
    """
    G = 1
    for c in cards:
        G *= int(c)
    gid = None
    for code, card in zip(codes, cards):
        # width choke point: codes may be STORED at int8/int16
        # (catalog.segment.code_dtype); every combined gid is int32
        c = jnp.maximum(code.astype(jnp.int32), 0)
        gid = c if gid is None else gid * jnp.int32(card) + c
    if gid is None:
        gid = jnp.zeros((), jnp.int32)
    return gid, G


def choose_block_rows(num_rows: int, num_groups: int,
                      vmem_budget_bytes: int = 32 << 20) -> int:
    """Pick the scan block size so the one-hot block fits the VMEM budget.

    B*G*4 bytes <= budget, B a multiple of 1024 (ROW_PAD), clamped to
    [1024, num_rows]."""
    b = vmem_budget_bytes // max(4 * num_groups, 1)
    b = max(1024, (b // 1024) * 1024)
    return int(min(b, max(num_rows, 1024)))


@functools.partial(
    jax.jit,
    static_argnames=("num_groups", "block_rows", "num_min", "num_max"),
)
def dense_partial_aggregate(
    gid: jnp.ndarray,  # int32[R]
    mask: jnp.ndarray,  # bool[R] — filter ∧ validity
    sum_values: jnp.ndarray,  # f32[R, Ms] — per-agg masked values (0 if excluded)
    minmax_values: jnp.ndarray,  # f32[R, Mn+Mx] — raw values for min/max aggs
    minmax_masks: jnp.ndarray,  # bool[R, Mn+Mx] — per-agg masks for min/max
    num_groups: int,
    block_rows: int,
    num_min: int,
    num_max: int,
):
    """One-hot-matmul partial aggregation over row blocks.

    Returns (sums[G, Ms], mins[G, Mn], maxs[G, Mx]).  `sum_values` columns are
    pre-masked by the caller (value * mask, and FilteredAgg extra masks), so
    the matmul with the bool one-hot is exact.  Count aggs pass a pre-masked
    ones column.  Empty groups: sums 0, mins +inf, maxs -inf (finalizer maps
    them to null).
    """
    R = gid.shape[0]
    assert R % block_rows == 0, (R, block_rows)
    nb = R // block_rows
    Ms = sum_values.shape[1]
    Mnx = minmax_values.shape[1]

    gid_b = gid.reshape(nb, block_rows)
    mask_b = mask.reshape(nb, block_rows)
    sumv_b = sum_values.reshape(nb, block_rows, Ms)
    mmv_b = minmax_values.reshape(nb, block_rows, Mnx)
    mmm_b = minmax_masks.reshape(nb, block_rows, Mnx)

    iota = lax.iota(jnp.int32, num_groups)

    init = (
        jnp.zeros((num_groups, Ms), jnp.float32),
        jnp.full((num_groups, num_min), jnp.inf, jnp.float32),
        jnp.full((num_groups, num_max), -jnp.inf, jnp.float32),
    )

    def body(carry, xs):
        sums, mins, maxs = carry
        g, m, sv, mmv, mmm = xs
        match = (g[:, None] == iota[None, :]) & m[:, None]  # bool[B, G]
        onehot = match.astype(jnp.float32)
        # MXU: [G, B] @ [B, Ms] with f32 accumulation.
        sums = sums + lax.dot(
            onehot.T, sv, precision=lax.Precision.HIGHEST,
            preferred_element_type=jnp.float32,
        )
        if num_min:
            v = mmv[:, :num_min]
            mm = m[:, None] & mmm[:, :num_min]
            # [B, G, Mn] masked-where then reduce rows — VPU, B*G*Mn elems.
            # inf fills are dtype-matched: a weak Python float promotes the
            # select to f64 under x64 (graftlint dtype-x64/GL303)
            w = jnp.where(
                match[:, :, None] & mm[:, None, :], v[:, None, :],
                jnp.asarray(jnp.inf, dtype=v.dtype),
            )
            mins = jnp.minimum(mins, w.min(axis=0))
        if num_max:
            v = mmv[:, num_min:]
            mm = m[:, None] & mmm[:, num_min:]
            w = jnp.where(
                match[:, :, None] & mm[:, None, :], v[:, None, :],
                jnp.asarray(-jnp.inf, dtype=v.dtype),
            )
            maxs = jnp.maximum(maxs, w.max(axis=0))
        return (sums, mins, maxs), None

    (sums, mins, maxs), _ = lax.scan(
        body, init, (gid_b, mask_b, sumv_b, mmv_b, mmm_b)
    )
    return sums, mins, maxs


@functools.partial(
    jax.jit, static_argnames=("num_groups", "num_min", "num_max")
)
def scatter_partial_aggregate(
    gid: jnp.ndarray,
    mask: jnp.ndarray,
    sum_values: jnp.ndarray,
    minmax_values: jnp.ndarray,
    minmax_masks: jnp.ndarray,
    num_groups: int,
    num_min: int = 0,
    num_max: int = 0,
):
    """Fallback strategy: XLA scatter (`segment_sum`) — memory-linear in G.

    Used when G is too large for one-hot blocks (cost model decision,
    the analog of the reference's cost-model broker-vs-historicals choice)."""
    # no-op guard (producers are int32 today): a narrow gid would wrap on
    # this trash-slot write, so widen before it
    seg = jnp.where(mask, gid.astype(jnp.int32), num_groups)
    sums = jax.ops.segment_sum(
        sum_values, seg, num_segments=num_groups + 1
    )[:num_groups]
    mins = jnp.zeros((num_groups, num_min), jnp.float32)
    maxs = jnp.zeros((num_groups, num_max), jnp.float32)
    if num_min + num_max:
        Mn = num_min
        # dtype-matched inf fills (weak floats promote to f64 under x64 —
        # graftlint dtype-x64/GL303)
        pos = jnp.asarray(jnp.inf, dtype=minmax_values.dtype)
        if Mn:
            v = jnp.where(minmax_masks[:, :Mn], minmax_values[:, :Mn], pos)
            mins = jax.ops.segment_min(v, seg, num_segments=num_groups + 1)[
                :num_groups
            ]
        Mx = minmax_values.shape[1] - Mn
        if Mx:
            v = jnp.where(minmax_masks[:, Mn:], minmax_values[:, Mn:], -pos)
            maxs = jax.ops.segment_max(v, seg, num_segments=num_groups + 1)[
                :num_groups
            ]
    return sums, mins, maxs


def resolve_strategy(
    strategy: str, num_groups: int, pallas_ok: bool = True
) -> str:
    """Single source of truth for 'auto' strategy resolution (shared by this
    dispatcher and Engine's program-cache keying)."""
    if strategy != "auto":
        return strategy
    if num_groups > SCATTER_CUTOVER:
        return "segment"
    from .pallas_groupby import pallas_available

    if pallas_ok and pallas_available():
        return "pallas"
    return "dense"


def partial_aggregate(
    gid,
    mask,
    sum_values,
    minmax_values,
    minmax_masks,
    num_groups: int,
    num_min: int,
    num_max: int,
    strategy: str = "auto",
    block_rows: Optional[int] = None,
):
    """Strategy dispatcher.  'auto' uses the Pallas kernel on TPU (dense
    one-hot in VMEM) up to SCATTER_CUTOVER groups (the XLA dense scan on
    non-TPU backends), and the scatter/segment path above it.

    Every current producer (combine_group_ids, the lowering codes_fns)
    already yields int32 gids; the astype below is a free no-op guard so a
    FUTURE narrow-width producer cannot wrap in trash-slot writes like
    `where(mask, gid, num_groups)`."""
    gid = gid.astype(jnp.int32)
    if strategy == "auto":
        strategy = resolve_strategy("auto", num_groups)
    if strategy == "pallas":
        from .pallas_groupby import pallas_available, pallas_partial_aggregate

        interpret = not pallas_available()
        return pallas_partial_aggregate(
            gid, mask, sum_values, minmax_values, minmax_masks,
            num_groups=num_groups, num_min=num_min, num_max=num_max,
            interpret=interpret,
        )
    if strategy in ("dense", "onehot"):
        br = block_rows or choose_block_rows(gid.shape[0], num_groups)
        # shrink to divide R (segments are ROW_PAD-padded so 1024 always divides)
        R = gid.shape[0]
        while R % br:
            br -= 1024
        br = max(br, 1024)
        return dense_partial_aggregate(
            gid, mask, sum_values, minmax_values, minmax_masks,
            num_groups=num_groups, block_rows=br,
            num_min=num_min, num_max=num_max,
        )
    if strategy in ("segment", "scatter"):
        return scatter_partial_aggregate(
            gid, mask, sum_values, minmax_values, minmax_masks,
            num_groups=num_groups, num_min=num_min, num_max=num_max,
        )
    raise ValueError(f"unknown groupby strategy {strategy!r}")
