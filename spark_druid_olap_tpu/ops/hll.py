"""HyperLogLog on TPU — per-group register arrays, max-merge everywhere.

Reference parity: Druid's `hyperUnique` / `cardinality` aggregators, which the
reference's AggregateTransform emits for approx_count_distinct (SURVEY.md §2
`[U]`); Druid historicals build per-segment HLL states and the broker merges
them by register-max — exactly the shape we reproduce: per-device states in
HBM merged with a `pmax` collective (parallel/merge.py), so an ICI allreduce
makes the pod one wide HLL builder (BASELINE.json north star).

Kernel shape (SURVEY.md §7 hard-part #3 — "HLL register update is a
scatter-max by hash bucket"): hash each row (uint32), low p bits pick the
bucket, rho = leading-zero-count of the high window + 1, and the scatter-max
runs as one `segment_max` over combined (group, bucket) indices — a single
XLA scatter of int32, not a per-row loop.  State: int32[G, 2^p] (int8 would
do; int32 avoids TPU sub-word scatter penalties; the state is tiny next to
the row data).

Estimation (host-side, classic Flajolet HLL on 32-bit hashes): alpha_m * m² /
sum(2^-M_j), with linear counting below 2.5m and the 32-bit large-range
correction.
"""

from __future__ import annotations

from typing import Mapping

import jax
import jax.numpy as jnp
import numpy as np

from ..models import aggregations as A
from ..utils.hashing import combine_hashes, hash_column


def _rho(h: jnp.ndarray, p: int) -> jnp.ndarray:
    """rho = #leading zeros of the (32-p)-bit window (h >> p) + 1, in [1, 33-p]."""
    w = (h >> p).astype(jnp.uint32)
    nbits = 32 - p
    # floor(log2(w)) via float32 exponent — exact for w < 2^24 (p >= 8 ⇒ w < 2^24)
    lg = jnp.floor(jnp.log2(jnp.maximum(w, 1).astype(jnp.float32)))
    rho = nbits - lg.astype(jnp.int32)
    return jnp.where(w == 0, nbits + 1, rho)


def partial_hll(
    agg,
    cols: Mapping[str, jnp.ndarray],
    gid: jnp.ndarray,
    mask: jnp.ndarray,
    num_groups: int,
) -> jnp.ndarray:
    """Partial HLL state int32[num_groups, 2^p] for one row shard."""
    p = agg.precision
    m = 1 << p
    if isinstance(agg, A.CardinalityAgg):
        hs = [hash_column(cols[f], seed=0) for f in agg.field_names]
        h = combine_hashes(hs) if agg.by_row else hs[0]
        if not agg.by_row and len(hs) > 1:
            # non-byRow multi-field: distinct over the union of values —
            # emulate by folding each field separately into the same registers
            states = [
                _fold_registers(hh, gid, mask, num_groups, p) for hh in hs
            ]
            out = states[0]
            for s in states[1:]:
                out = jnp.maximum(out, s)
            return out
    else:
        h = hash_column(cols[agg.field_name], seed=0)
    return _fold_registers(h, gid, mask, num_groups, p)


def _fold_registers(h, gid, mask, num_groups, p):
    m = 1 << p
    bucket = (h & jnp.uint32(m - 1)).astype(jnp.int32)
    rho = _rho(h, p)
    # group-sharded callers pass shifted gids that may fall outside [0, G)
    ok = mask & (gid >= 0) & (gid < num_groups)
    rho = jnp.where(ok, rho, 0)
    idx = jnp.where(ok, gid * m + bucket, num_groups * m)  # trash slot
    regs = jax.ops.segment_max(
        rho, idx, num_segments=num_groups * m + 1
    )[: num_groups * m]
    # segment_max fills empty segments with the dtype min — clamp to 0
    regs = jnp.maximum(regs, 0)
    return regs.reshape(num_groups, m)


def estimate(registers: np.ndarray) -> np.ndarray:
    """HLL cardinality estimate per group.  registers: int[..., m]."""
    regs = np.asarray(registers, dtype=np.float64)
    m = regs.shape[-1]
    if m >= 128:
        alpha = 0.7213 / (1 + 1.079 / m)
    elif m == 64:
        alpha = 0.709
    elif m == 32:
        alpha = 0.697
    else:
        alpha = 0.673
    est = alpha * m * m / np.sum(np.exp2(-regs), axis=-1)
    zeros = np.sum(regs == 0, axis=-1)
    # small-range: linear counting
    with np.errstate(divide="ignore"):
        lc = m * np.log(np.where(zeros > 0, m / np.maximum(zeros, 1), 1.0))
    est = np.where((est <= 2.5 * m) & (zeros > 0), lc, est)
    # large-range correction for 32-bit hash space
    two32 = 2.0**32
    est = np.where(
        est > two32 / 30.0, -two32 * np.log1p(-est / two32), est
    )
    return est
