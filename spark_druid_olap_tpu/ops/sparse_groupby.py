"""Sort-compaction GroupBy for high-cardinality group domains.

TPUs hate scatter: above ~4k groups the engine's fallback is
`jax.ops.segment_sum`, whose serialized conflicting updates make it ~5-10x
slower than the dense one-hot kernel (measured on SSB q3_x/q4_3, SURVEY.md
§7 hard-part #1).  But the OLAP reality those queries embody is a *huge
combined domain with few distinct groups actually present* (city x city x
year = 437k cells, ~700 populated after filters).  So: compact first, then
go dense.

    gid in [0, G)  --jnp.unique(size=SLOTS)-->  slot in [0, SLOTS)
                   --dense/Pallas one-hot over SLOTS--> [SLOTS, M] partials
                   + uniq[SLOTS] mapping slot -> original gid

The sort inside `unique` is TPU-friendly (bitonic, no scatter), and the
one-hot matmul over <=4096 slots rides the MXU like any low-cardinality
query.  Partial states stay sparse across segment merges (concat + re-unique
+ tiny scatter over 2*SLOTS rows).  If a block holds more distinct groups
than SLOTS, `unique` would silently truncate — every row whose gid got
dropped maps to a wrong slot — so each kernel also emits an `overflow` flag
(any row whose slot doesn't round-trip to its gid); the engine checks it at
fetch time and reruns the query on the scatter path.  Sparse states use
gid = -1 for empty/trash slots.

The reference has no analog (Druid's historicals do hash aggregation in
JVM); this is the TPU-native replacement for that engine interior.
"""

from __future__ import annotations

import functools
from typing import Dict, Optional

import jax
import jax.numpy as jnp
from jax import lax

from .groupby import partial_aggregate

SPARSE_SLOTS = 4096

# Slot-capacity rungs for the HIGH-POPULATED tier (VERDICT r3 #2: the
# sort-agg half of SURVEY.md §7 hard-part #1).  Up to SPARSE_SLOTS the inner
# aggregation is the dense/Pallas one-hot over slots; past it, the
# segmented-reduce-over-ranks kernel below scales to ~2M genuinely populated
# groups.  Past the top rung the engine falls back to raw scatter.
SLOTS_LADDER = (SPARSE_SLOTS, 1 << 15, 1 << 18, 1 << 21)

# Row capacity of the filter-compaction stage: selective queries (the normal
# OLAP case that reaches the sparse path — think city-level predicates over a
# nation) compact surviving rows into this many slots BEFORE the sort, so the
# bitonic sort network runs over 128K rows instead of the full segment.  A
# multiple of 1024 (ROW_PAD) so the inner one-hot blocks divide evenly.
ROW_CAPACITY = 1 << 17

# Capacity rungs.  The engine picks the INITIAL rung from the planner's
# selectivity estimate (x2 headroom) — a q3_2-class segment with ~700
# survivors sorts 4K slots, not 128K (the fixed 128K floor cost ~35 ms of
# sort PER SEGMENT, which at SF100's ~1000 segments was the whole sparse
# budget).  On overflow the kernel's exact survivor count (`n_rows`) picks
# the smallest adequate rung (full-segment sort only past the top): sort
# cost grows roughly linearly with capacity (an ESTIMATE from the
# O(n log n) sort bound — no committed TPU artifact backs a measured
# number yet).
ROW_CAPACITY_LADDER = (
    1 << 12, 1 << 14, 1 << 17, 1 << 18, 1 << 19, 1 << 20, 1 << 21
)


def compact_rows(
    gid: jnp.ndarray,
    mask: jnp.ndarray,
    sum_values: jnp.ndarray,
    minmax_values: jnp.ndarray,
    minmax_masks: jnp.ndarray,
    capacity: int,
):
    """Pack rows where mask is True into `capacity` slots (stable order).

    TPU-idiomatic: one cumsum + one vectorized binary search + gathers — no
    R-sized scatter, no sort.  Slot i holds the i-th surviving row (the first
    position whose running count reaches i+1).  Slots past the survivor count
    duplicate an arbitrary row with their mask cleared, so downstream
    aggregation ignores them.  Returns (*compacted arrays, row_overflow, n)
    — row_overflow set when survivors exceed capacity (the caller must rerun
    at a bigger capacity; compacted state would silently drop rows), and n
    is the exact survivor count so the engine can pick that capacity from
    ROW_CAPACITY_LADDER without guessing."""
    R = gid.shape[0]
    c = jnp.cumsum(mask.astype(jnp.int32))
    n = c[-1]
    row_overflow = n > capacity
    idx = jnp.searchsorted(
        c, jnp.arange(1, capacity + 1, dtype=jnp.int32), side="left"
    )
    idx = jnp.minimum(idx, R - 1)
    new_mask = jnp.arange(capacity, dtype=jnp.int32) < n
    return (
        gid[idx],
        new_mask,
        sum_values[idx],
        minmax_values[idx],
        minmax_masks[idx],
        row_overflow,
        n,
    )


@functools.partial(
    jax.jit,
    static_argnames=("capacity", "block_rows", "num_min", "num_max"),
)
def segmented_reduce_sorted(
    slot: jnp.ndarray,  # i32[R] run index per SORTED row: nondecreasing, +<=1/row
    mask: jnp.ndarray,  # bool[R]
    sum_values: jnp.ndarray,  # f32[R, Ms] pre-masked
    minmax_values: jnp.ndarray,  # f32[R, Mnx]
    minmax_masks: jnp.ndarray,  # bool[R, Mnx]
    capacity: int,
    block_rows: int,
    num_min: int,
    num_max: int,
):
    """Per-run aggregation over rows already sorted by group — the sort-agg
    tier of SURVEY.md §7 hard-part #1, for group domains too populated for a
    one-hot over slots (> SPARSE_SLOTS distinct present).

    TPU-first: because `slot` (the run index from the caller's sort) is
    nondecreasing and grows by at most 1 per row, any B consecutive rows
    span at most B distinct runs.  So each B-row block one-hot-matmuls
    against its LOCAL run offsets (a [B, B] MXU contraction — no scatter)
    and accumulates into the output window [base, base+B) with a contiguous
    dynamic-slice read-modify-write.  A run straddling two blocks is summed
    by both partial windows — addition/min/max identities make that exact.
    Total MXU work is B FLOPs/row/agg regardless of how many groups exist.

    Returns (sums[capacity, Ms], mins[capacity, Mn], maxs[capacity, Mx]).
    The caller guarantees slot < capacity (clamped); rows whose run was
    clamped land in the last slot, which the caller treats as overflow.
    """
    R = slot.shape[0]
    B = block_rows
    pad_rows = (-R) % B
    if pad_rows:
        # repeat the final slot (keeps the nondecreasing invariant) with
        # mask off so padding never contributes
        slot = jnp.concatenate(
            [slot, jnp.broadcast_to(slot[-1], (pad_rows,))]
        )
        mask = jnp.concatenate([mask, jnp.zeros(pad_rows, jnp.bool_)])
        sum_values = jnp.concatenate(
            [sum_values, jnp.zeros((pad_rows,) + sum_values.shape[1:],
                                   sum_values.dtype)]
        )
        minmax_values = jnp.concatenate(
            [minmax_values,
             jnp.zeros((pad_rows,) + minmax_values.shape[1:],
                       minmax_values.dtype)]
        )
        minmax_masks = jnp.concatenate(
            [minmax_masks,
             jnp.zeros((pad_rows,) + minmax_masks.shape[1:], jnp.bool_)]
        )
        R += pad_rows
    nb = R // B
    Ms = sum_values.shape[1]

    slot_b = slot.reshape(nb, B)
    mask_b = mask.reshape(nb, B)
    sumv_b = sum_values.reshape(nb, B, Ms)
    mmv_b = minmax_values.reshape(nb, B, -1)
    mmm_b = minmax_masks.reshape(nb, B, -1)

    iota = lax.iota(jnp.int32, B)
    padded = capacity + B  # windows near the tail stay in-bounds
    init = (
        jnp.zeros((padded, Ms), jnp.float32),
        jnp.full((padded, num_min), jnp.inf, jnp.float32),
        jnp.full((padded, num_max), -jnp.inf, jnp.float32),
    )

    def body(carry, xs):
        sums, mins, maxs = carry
        s, m, sv, mmv, mmm = xs
        base = s[0]
        z = jnp.zeros((), base.dtype)  # start indices must share one dtype
        local = s - base  # in [0, B): nondecreasing, +<=1 over B rows
        match = (local[:, None] == iota[None, :]) & m[:, None]  # [B, B]
        onehot = match.astype(jnp.float32)
        block_sums = lax.dot(
            onehot.T, sv, precision=lax.Precision.HIGHEST,
            preferred_element_type=jnp.float32,
        )
        win = lax.dynamic_slice(sums, (base, z), (B, Ms))
        sums = lax.dynamic_update_slice(sums, win + block_sums, (base, z))
        if num_min:
            v = mmv[:, :num_min]
            mm = m[:, None] & mmm[:, :num_min]
            # dtype-matched inf fill (weak floats promote to f64 under x64
            # — graftlint dtype-x64/GL303)
            w = jnp.where(
                match[:, :, None] & mm[:, None, :], v[:, None, :],
                jnp.asarray(jnp.inf, dtype=v.dtype),
            ).min(axis=0)
            win = lax.dynamic_slice(mins, (base, z), (B, num_min))
            mins = lax.dynamic_update_slice(
                mins, jnp.minimum(win, w), (base, z)
            )
        if num_max:
            v = mmv[:, num_min:]
            mm = m[:, None] & mmm[:, num_min:]
            w = jnp.where(
                match[:, :, None] & mm[:, None, :], v[:, None, :],
                jnp.asarray(-jnp.inf, dtype=v.dtype),
            ).max(axis=0)
            win = lax.dynamic_slice(maxs, (base, z), (B, num_max))
            maxs = lax.dynamic_update_slice(
                maxs, jnp.maximum(win, w), (base, z)
            )
        return (sums, mins, maxs), None

    (sums, mins, maxs), _ = lax.scan(
        body, init, (slot_b, mask_b, sumv_b, mmv_b, mmm_b)
    )
    return sums[:capacity], mins[:capacity], maxs[:capacity]


def sparse_partial_aggregate(
    gid: jnp.ndarray,
    mask: jnp.ndarray,
    sum_values: jnp.ndarray,
    minmax_values: jnp.ndarray,
    minmax_masks: jnp.ndarray,
    *,
    num_groups: int,
    num_min: int,
    num_max: int,
    slots: int = SPARSE_SLOTS,
    inner_strategy: str = "auto",
    row_capacity: Optional[int] = None,
) -> Dict[str, jnp.ndarray]:
    """Compact gids to slots, aggregate dense over slots.

    With `row_capacity`, surviving rows are first packed through
    `compact_rows` so the sort network covers `row_capacity` rows instead of
    R (the selective-filter fast path); `row_overflow` in the result tells
    the engine the capacity was exceeded and the state is unusable.

    Returns {"gids": i32[slots] (-1 = empty/trash), "sums": f32[slots, Ms],
    "mins": f32[slots, Mn], "maxs": f32[slots, Mx], "overflow": bool[],
    "row_overflow": bool[], "n_rows": i32[] exact survivor count}.
    """
    G = num_groups
    gid = gid.astype(jnp.int32)  # no-op guard: see partial_aggregate
    row_overflow = jnp.zeros((), jnp.bool_)
    if row_capacity is not None and row_capacity < gid.shape[0]:
        (
            gid, mask, sum_values, minmax_values, minmax_masks,
            row_overflow, n_rows,
        ) = compact_rows(
            gid, mask, sum_values, minmax_values, minmax_masks,
            row_capacity,
        )
    else:
        n_rows = jnp.sum(mask.astype(jnp.int32))
    R = gid.shape[0]
    n_state = slots + 1  # + 1 so the masked-row trash run never eats a slot
    g = jnp.where(mask, gid, jnp.int32(G))  # trash value for masked rows
    # TPU-idiomatic compaction: one argsort, then ONLY gathers — no R-sized
    # scatter (what jnp.unique's return_inverse would cost us).  The row
    # values ride the permutation instead of the slot ids riding an inverse.
    order = jnp.argsort(g)
    sg = g[order]
    firsts = jnp.concatenate(
        [jnp.ones((1,), jnp.bool_), sg[1:] != sg[:-1]]
    )
    ranks = jnp.cumsum(firsts.astype(jnp.int32)) - 1  # run index per row
    n_distinct = ranks[-1] + 1
    # the trash run (all gid==G) sorts last, so it never displaces a real
    # group; capacity is `slots` REAL groups exactly
    n_real = n_distinct - (sg[-1] == G).astype(jnp.int32)
    overflow = n_real > slots  # clipped slots hold garbage -> rerun
    slot_sorted = jnp.minimum(ranks, n_state - 1)
    # first sorted position of each run -> that slot's gid
    pos = jnp.nonzero(firsts, size=n_state, fill_value=R)[0]
    uniq = jnp.where(
        pos < R, sg[jnp.minimum(pos, R - 1)], jnp.int32(G)
    )
    if slots > SPARSE_SLOTS and inner_strategy not in ("segment", "scatter"):
        # high-populated tier: a one-hot over `slots` would blow VMEM; the
        # rows are already sorted by run, so segmented-reduce them
        sums, mins, maxs = segmented_reduce_sorted(
            slot_sorted,
            mask[order],
            sum_values[order],
            minmax_values[order],
            minmax_masks[order],
            capacity=n_state,
            block_rows=1024,
            num_min=num_min,
            num_max=num_max,
        )
    else:
        sums, mins, maxs = partial_aggregate(
            slot_sorted,
            mask[order],
            sum_values[order],
            minmax_values[order],
            minmax_masks[order],
            num_groups=n_state,
            num_min=num_min,
            num_max=num_max,
            strategy=inner_strategy,
        )
    gids = jnp.where(uniq >= G, jnp.int32(-1), uniq.astype(jnp.int32))
    return {
        "gids": gids,
        "sums": sums,
        "mins": mins,
        "maxs": maxs,
        "overflow": overflow,
        "row_overflow": row_overflow,
        "n_rows": n_rows,
        # exact distinct-present count (when not overflowed): the engine's
        # slot-ladder rung selector reads it instead of guessing
        "n_real": n_real,
    }


@functools.partial(jax.jit, static_argnames=("num_groups",))
def merge_sparse_states(
    a: Dict[str, jnp.ndarray],
    b: Dict[str, jnp.ndarray],
    num_groups: int,
) -> Dict[str, jnp.ndarray]:
    """Merge two sparse partial states (same slot count) into one.

    concat -> re-unique -> scatter over 2*n_state rows (tiny, scatter is
    fine at this size).  Empty slots carry the merge identities
    (+inf/-inf/0), so they never contaminate a real slot they get co-mapped
    with.  State arrays are slots+1 long (see sparse_partial_aggregate), so
    `slots` real gids plus the shared empty/trash sentinel always fit —
    round-trip mismatch therefore fires exactly when real distinct > slots."""
    n_state = a["gids"].shape[0]
    G = num_groups
    cg = jnp.concatenate([a["gids"], b["gids"]])
    cg = jnp.where(cg < 0, jnp.int32(G), cg)  # sentinel back to sortable form
    uniq, inv = jnp.unique(
        cg, size=n_state, fill_value=jnp.int32(G), return_inverse=True
    )
    inv = inv.reshape(cg.shape)
    overflow = (
        a["overflow"] | b["overflow"] | jnp.any(uniq[inv] != cg)
    )
    sums = (
        jnp.zeros((n_state,) + a["sums"].shape[1:], a["sums"].dtype)
        .at[inv]
        .add(jnp.concatenate([a["sums"], b["sums"]]))
    )
    mins = (
        jnp.full((n_state,) + a["mins"].shape[1:], jnp.inf, a["mins"].dtype)
        .at[inv]
        .min(jnp.concatenate([a["mins"], b["mins"]]))
    )
    maxs = (
        jnp.full((n_state,) + a["maxs"].shape[1:], -jnp.inf, a["maxs"].dtype)
        .at[inv]
        .max(jnp.concatenate([a["maxs"], b["maxs"]]))
    )
    gids = jnp.where(uniq >= G, jnp.int32(-1), uniq.astype(jnp.int32))
    # distinct-present in the merged state: exact from the unique when it
    # fit.  When truncation makes the exact count unknowable, report
    # max(a, b) — a LOWER bound.  (ADVICE r4: the a+b upper bound inflated
    # by up to N over N same-group segments, making the rung selector skip
    # workable SLOTS_LADDER rungs or decline outright; with a lower bound
    # the engine ladders up one rung at a time instead — see
    # exec/sparse_exec.fetch_slot_laddered.)
    exact = jnp.sum((uniq < G).astype(jnp.int32))
    n_real = jnp.where(
        overflow, jnp.maximum(a["n_real"], b["n_real"]), exact
    )
    return {
        "gids": gids,
        "sums": sums,
        "mins": mins,
        "maxs": maxs,
        "overflow": overflow,
        "row_overflow": a["row_overflow"] | b["row_overflow"],
        # max, not sum: capacity is per-segment, so the rung the engine picks
        # must cover the worst single segment
        "n_rows": jnp.maximum(a["n_rows"], b["n_rows"]),
        "n_real": n_real,
    }
