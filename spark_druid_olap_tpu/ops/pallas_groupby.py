"""Pallas TPU kernel: fused one-hot GroupBy partial aggregation.

This is the hand-scheduled version of ops/groupby.py's dense strategy — the
hot kernel of the whole framework (the role Druid's historical aggregation
engine plays in the reference, SURVEY.md §2 native-components note `[U]`).

Why Pallas beats the XLA scan here: the scan body materializes each one-hot
block ``(B, G)`` through HBM before the matmul reads it back — for B=1M rows
that is gigabytes of pure intermediate traffic.  The kernel builds each
one-hot tile *in VMEM* with `broadcasted_iota` + compare and feeds the MXU
directly; HBM sees only the raw row data (once) and the [G, M] aggregate
state.  min/max ride the same match tile on the VPU.

Layout choices (pallas_guide.md tiling rules):
  * rows are the sublane dim of ``(BLOCK_R, BLOCK_G)`` match tiles;
  * aggregate outputs are stored transposed ``(M, G)`` so the small M axis
    pads to 8 sublanes instead of 128 lanes;
  * grid is (groups-tile, rows-tile) with rows innermost, so each group
    tile's accumulator stays VMEM-resident across the whole row sweep
    (TPU grids execute sequentially — accumulation is race-free).

The kernel covers sum-class and min/max aggregations (sketch partials stay in
XLA — scatter-shaped, see ops/hll.py).  `interpret=True` under CPU tests.

The pallas_call <-> kernel contract (grid arity vs index_map signatures,
BlockSpec ranks vs ref indexing, spec count vs kernel refs, dtype-matched
fills) is enforced statically by graftlint's pallas-shape pass (GL7xx),
which resolves `kernel`/`grid`/`*_specs` through local assignments and
`functools.partial` — keep those shapes statically spellable.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pallas TPU backend (absent on some CPU-only installs)
    from jax.experimental.pallas import tpu as pltpu

    _HAS_PLTPU = True
except Exception:  # pragma: no cover
    pltpu = None
    _HAS_PLTPU = False

_NEG = -jnp.inf
_POS = jnp.inf


def _enable_x64_compat(flag: bool):
    """`jax.enable_x64` across JAX versions: top-level on new releases,
    `jax.experimental.enable_x64` on older ones (this container's 0.4.37)
    — same degrade-to-available-API convention as
    parallel.mesh.shard_map_compat."""
    if hasattr(jax, "enable_x64"):
        return jax.enable_x64(flag)
    from jax.experimental import enable_x64 as _enable_x64

    return _enable_x64(flag)


def _kernel(
    gid_ref,
    mask_ref,
    sumv_ref,
    minv_ref,
    maxv_ref,
    out_sum_ref,
    out_min_ref,
    out_max_ref,
    *,
    block_g: int,
    num_min: int,
    num_max: int,
):
    i = pl.program_id(1)  # row tile (inner)
    j = pl.program_id(0)  # group tile (outer)

    @pl.when(i == 0)
    def _init():
        out_sum_ref[:] = jnp.zeros_like(out_sum_ref)
        if num_min:
            out_min_ref[:] = jnp.full_like(out_min_ref, _POS)
        if num_max:
            out_max_ref[:] = jnp.full_like(out_max_ref, _NEG)

    gid = gid_ref[:, 0] - j * block_g  # (BR,) relative to this group tile
    mask = mask_ref[:, 0] != 0
    br = gid.shape[0]
    iota = jax.lax.broadcasted_iota(jnp.int32, (br, block_g), 1)
    match = (gid[:, None] == iota) & mask[:, None]  # (BR, BG) bool, VMEM-only

    onehot = match.astype(jnp.float32)
    # MXU: (Ms, BR) @ (BR, BG) -> (Ms, BG); sum values are pre-masked so the
    # bool one-hot contraction is exact.  HIGHEST precision keeps f32 inputs
    # f32 on the MXU (default would truncate to bf16 and break parity with
    # the XLA dense path).
    out_sum_ref[:] += jax.lax.dot(
        sumv_ref[:], onehot,
        precision=jax.lax.Precision.HIGHEST,
        preferred_element_type=jnp.float32,
    )

    # VPU: masked min/max over the same match tile, one agg column at a time.
    # The +/-inf fill is materialized AT THE REF DTYPE: a bare Python float
    # here is weak-typed, and under x64 the old-jax interpret-mode lowering
    # promotes the select to f64 ('func.call' operand mismatch, the seed
    # pallas failure) — dtype-matched selects never promote.
    for m in range(num_min):
        pos = jnp.asarray(_POS, dtype=out_min_ref.dtype)
        w = jnp.where(match, minv_ref[m, :][:, None], pos)  # (BR, BG)
        out_min_ref[m, :] = jnp.minimum(out_min_ref[m, :], w.min(axis=0))
    for m in range(num_max):
        neg = jnp.asarray(_NEG, dtype=out_max_ref.dtype)
        w = jnp.where(match, maxv_ref[m, :][:, None], neg)
        out_max_ref[m, :] = jnp.maximum(out_max_ref[m, :], w.max(axis=0))


@functools.partial(
    jax.jit,
    static_argnames=(
        "num_groups", "num_min", "num_max", "block_rows", "block_groups",
        "interpret",
    ),
)
def pallas_partial_aggregate(
    gid: jnp.ndarray,  # int32[R]
    mask: jnp.ndarray,  # bool[R]
    sum_values: jnp.ndarray,  # f32[R, Ms] pre-masked
    minmax_values: jnp.ndarray,  # f32[R, Mn+Mx] raw
    minmax_masks: jnp.ndarray,  # bool[R, Mn+Mx]
    num_groups: int,
    num_min: int,
    num_max: int,
    block_rows: int = 1024,
    block_groups: int = 4096,
    interpret: bool = False,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Same contract as ops.groupby.dense_partial_aggregate, hand-scheduled.

    Returns (sums[G, Ms], mins[G, Mn], maxs[G, Mx]); empty groups are 0 /
    +inf / -inf exactly like the XLA path.

    Block tuning (ESTIMATED for a v5e-class VMEM budget; not yet validated
    on hardware — rounds 1-2 never reached the TPU, see BENCH_r*.json):
    every extra group tile re-reads the whole
    row stream, so the group-block default spans all groups up to 4096 (one
    tile); the row block shrinks to 512 when the group block is wide so the
    (BR, BG) match tile stays within VMEM."""
    R = gid.shape[0]
    Ms = sum_values.shape[1]
    bg = min(block_groups, max(128, -(-num_groups // 128) * 128))
    g_pad = -(-num_groups // bg) * bg
    # the row-block size must divide R exactly (same contract as the dense
    # path; engine rows are always ROW_PAD=1024-multiples)
    br = min(block_rows if bg <= 1024 else 512, R)
    while br >= 8 and R % br:
        br -= 8
    if br < 8 or R % br:
        raise ValueError(
            f"row count {R} must be divisible by a multiple-of-8 block size"
        )

    # transpose value blocks to (M, R): M pads to sublanes (8) not lanes (128)
    sum_t = sum_values.T  # (Ms, R)
    mn_t = (
        jnp.where(
            mask[:, None] & minmax_masks[:, :num_min],
            minmax_values[:, :num_min],
            jnp.asarray(_POS, dtype=minmax_values.dtype),
        ).T
        if num_min
        else jnp.zeros((1, R), jnp.float32)
    )
    mx_t = (
        jnp.where(
            mask[:, None] & minmax_masks[:, num_min:],
            minmax_values[:, num_min:],
            jnp.asarray(_NEG, dtype=minmax_values.dtype),
        ).T
        if num_max
        else jnp.zeros((1, R), jnp.float32)
    )

    grid = (g_pad // bg, R // br)

    kernel = functools.partial(
        _kernel, block_g=bg, num_min=num_min, num_max=num_max
    )
    out_shapes = (
        jax.ShapeDtypeStruct((Ms, g_pad), jnp.float32),
        jax.ShapeDtypeStruct((max(num_min, 1), g_pad), jnp.float32),
        jax.ShapeDtypeStruct((max(num_max, 1), g_pad), jnp.float32),
    )
    in_specs = [
        pl.BlockSpec((br, 1), lambda j, i: (i, 0)),  # gid
        pl.BlockSpec((br, 1), lambda j, i: (i, 0)),  # mask (int32)
        pl.BlockSpec((Ms, br), lambda j, i: (0, i)),  # sum values (Ms, BR)
        pl.BlockSpec((max(num_min, 1), br), lambda j, i: (0, i)),
        pl.BlockSpec((max(num_max, 1), br), lambda j, i: (0, i)),
    ]
    out_specs = (
        pl.BlockSpec((Ms, bg), lambda j, i: (0, j)),
        pl.BlockSpec((max(num_min, 1), bg), lambda j, i: (0, j)),
        pl.BlockSpec((max(num_max, 1), bg), lambda j, i: (0, j)),
    )
    # Mosaic cannot legalize the i64 grid-index arithmetic that x64 mode
    # injects (func.return (i32, i64) fails on real TPUs) — trace the kernel
    # in 32-bit mode.  All operands are already concrete i32/f32 arrays, so
    # semantics are unchanged.
    with _enable_x64_compat(False):
        sums_t, mins_t, maxs_t = pl.pallas_call(
            kernel,
            grid=grid,
            in_specs=in_specs,
            out_specs=out_specs,
            out_shape=out_shapes,
            interpret=interpret,
        )(
            gid.reshape(R, 1),
            mask.astype(jnp.int32).reshape(R, 1),
            sum_t,
            mn_t,
            mx_t,
        )
    sums = sums_t[:, :num_groups].T
    mins = (
        mins_t[:num_min, :num_groups].T
        if num_min
        else jnp.zeros((num_groups, 0), jnp.float32)
    )
    maxs = (
        maxs_t[:num_max, :num_groups].T
        if num_max
        else jnp.zeros((num_groups, 0), jnp.float32)
    )
    return sums, mins, maxs


def pallas_available() -> bool:
    """True when a TPU backend is present (the kernel also runs anywhere via
    interpret=True, which tests use)."""
    try:
        return jax.devices()[0].platform in ("tpu", "axon") and _HAS_PLTPU
    except Exception:  # pragma: no cover
        return False
