"""Theta (KMV) sketches on TPU — bottom-K hash sets per group, union-merge.

Reference parity: Druid's DataSketches `thetaSketch` aggregator (the other
approx-distinct the reference can push down, SURVEY.md §2 / BASELINE config #5
`[U]`).  Per-segment partial sketches union on the broker; here per-shard
partial states union across devices via all_gather + re-sort
(`merge_op="union"`, parallel/merge.py).

TPU-first shape (SURVEY.md §7 hard-part #3: "theta union needs sorted-unique —
do as sort + segmented ops"): no per-row hash-table scatter.  A shard's rows
are (group, hash) pairs; one `lexsort` groups them and orders hashes within
each group; duplicate hashes collapse to a sentinel; ranks within each group
come from a searchsorted against group starts; rows with rank < K land in the
state via a *unique-index* scatter (XLA handles unique scatters efficiently).

State: uint32[G, K], ascending, padded with SENTINEL (0xFFFFFFFF).
Estimate: count < K ⇒ exact distinct-hash count; else (K-1) / (kth / 2^32).
32-bit hash space ⇒ ~n²/2³³ collision under-count (~1% at n=10⁸); acceptable
for approx_count_distinct, noted for parity tests.
"""

from __future__ import annotations

import functools
from typing import Mapping

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.hashing import hash_column

# np scalar, not jnp: a module-level jnp constant executes a device
# computation at IMPORT time, instantiating the XLA backend before
# multihost rendezvous can run (jax.distributed.initialize refuses once
# the backend exists — parallel/multihost.py).  Inside traces a numpy
# uint32 scalar converts identically.
SENTINEL = np.uint32(0xFFFFFFFF)


@functools.partial(jax.jit, static_argnames=("num_groups", "k"))
def _bottom_k(h: jnp.ndarray, gid: jnp.ndarray, mask: jnp.ndarray,
              num_groups: int, k: int) -> jnp.ndarray:
    R = h.shape[0]
    # group-sharded callers pass shifted gids that may fall outside [0, G)
    ok = mask & (gid >= 0) & (gid < num_groups)
    g = jnp.where(ok, gid, num_groups)  # masked rows to trash group
    hh = jnp.where(ok, h, SENTINEL)
    # sort by (group, hash) — jnp.lexsort: last key is primary
    order = jnp.lexsort((hh, g))
    gs = g[order]
    hs = hh[order]
    # collapse duplicate (group, hash) pairs
    dup = jnp.zeros(R, jnp.bool_).at[1:].set(
        (gs[1:] == gs[:-1]) & (hs[1:] == hs[:-1])
    )
    hs = jnp.where(dup, SENTINEL, hs)
    # re-sort within group so sentinels sink to the end
    order2 = jnp.lexsort((hs, gs))
    gs2 = gs[order2]
    hs2 = hs[order2]
    starts = jnp.searchsorted(gs2, jnp.arange(num_groups + 1, dtype=gs2.dtype))
    rank = jnp.arange(R, dtype=jnp.int32) - starts[
        jnp.clip(gs2, 0, num_groups)
    ].astype(jnp.int32)
    keep = (rank < k) & (gs2 < num_groups) & (hs2 != SENTINEL)
    out = jnp.full((num_groups * k,), SENTINEL, dtype=jnp.uint32)
    flat_idx = jnp.where(keep, gs2 * k + rank, num_groups * k)
    out = out.at[flat_idx].set(hs2, mode="drop")
    return out.reshape(num_groups, k)


def partial_theta(
    agg, cols: Mapping[str, jnp.ndarray], gid, mask, num_groups: int
) -> jnp.ndarray:
    h = hash_column(cols[agg.field_name], seed=7)
    return _bottom_k(h, gid, mask, num_groups, agg.size)


@functools.partial(jax.jit, static_argnames=("k",))
def merge_states(a: jnp.ndarray, b: jnp.ndarray, k: int) -> jnp.ndarray:
    """KMV union: concat, sort, dedupe, keep bottom-K. a,b: uint32[G, K]."""
    cat = jnp.concatenate([a, b], axis=1)
    s = jnp.sort(cat, axis=1)
    dup = jnp.zeros(s.shape, jnp.bool_).at[:, 1:].set(s[:, 1:] == s[:, :-1])
    s = jnp.where(dup, SENTINEL, s)
    s = jnp.sort(s, axis=1)
    return s[:, :k]


def merge_many(states, k: int) -> jnp.ndarray:
    acc = states[0]
    for s in states[1:]:
        acc = merge_states(acc, s, k)
    return acc


def estimate(state: np.ndarray) -> np.ndarray:
    """Distinct estimate per group from uint32[..., K] KMV state."""
    s = np.asarray(state)
    k = s.shape[-1]
    valid = s != np.uint32(0xFFFFFFFF)
    count = valid.sum(axis=-1)
    kth = s[..., -1].astype(np.float64)  # largest kept hash
    frac = (kth + 1.0) / 2.0**32
    full = count >= k
    with np.errstate(divide="ignore", invalid="ignore"):
        est = np.where(full, (k - 1) / np.maximum(frac, 1e-12), count)
    return est


def set_op_estimate(fn: str, states) -> np.ndarray:
    """Estimate |A ∪ B|, |A ∩ B|, or |A \\ B...| per group from KMV states.

    Standard KMV set semantics: clip every sketch to the smallest common
    threshold theta (the inclusion probability both samples share), apply the
    set operation on the retained hash samples, scale by 1/theta.  Host-side
    numpy over result rows (G is result-sized here, not kernel-sized)."""
    states = [np.asarray(s) for s in states]
    if len(states) == 0:
        raise ValueError("set_op_estimate needs at least one state")
    sent = np.uint32(0xFFFFFFFF)

    def theta_of(s):
        k = s.shape[-1]
        count = (s != sent).sum(axis=-1)
        kth = s[..., -1].astype(np.float64)
        return np.where(count >= k, (kth + 1.0) / 2.0**32, 1.0)

    th = np.minimum.reduce([theta_of(s) for s in states])
    G = states[0].shape[0]
    out = np.zeros(G, dtype=np.float64)
    for g in range(G):
        limit = th[g] * 2.0**32
        sets = [
            {int(h) for h in s[g] if h != sent and h < limit} for s in states
        ]
        if fn == "UNION":
            acc = set.union(*sets)
        elif fn == "INTERSECT":
            acc = set.intersection(*sets)
        elif fn == "NOT":
            acc = sets[0].difference(*sets[1:])
        else:
            raise ValueError(f"theta set op {fn!r}")
        out[g] = len(acc) / max(th[g], 1e-12)
    return out
