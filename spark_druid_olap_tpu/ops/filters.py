"""Compile Filter spec trees into jittable boolean-mask functions.

Reference parity: in spark-druid-olap, `FilterSpec`s travel to Druid which
evaluates them against its bitmap indexes inside historicals (SURVEY.md §2
ProjectFilterTransform row `[U]`).  Here the planner-produced spec tree
compiles into a fused element-wise mask over device-resident columns; XLA
fuses the whole predicate into the aggregation kernel's first pass, so a
filter costs one pass over the (already HBM-resident) filtered columns.

Dictionary tricks (all host-side, per-query, O(dictionary) not O(rows)):
* Selector / In   -> int equality / isin on codes.
* Bound on string -> because dictionaries are sorted (catalog/segment.py),
  lexicographic bounds become integer range tests on codes.
* Regex / Like    -> run the regex over dictionary values once; the matching
  code set becomes an isin — strictly cheaper than Druid's per-row regex.
"""

from __future__ import annotations

import re
from typing import Callable, Mapping

import jax.numpy as jnp
import numpy as np

from ..catalog.segment import DataSource
from ..models import filters as F
from ..plan.expr import coerce_str_literal, compile_expr


def _bound_literal(v) -> float | None:
    """Numeric value of a Bound literal: numbers pass through; ISO
    date/timestamp strings become epoch ms (the reference's spark-datetime
    predicates produce exactly these against long time columns — VERDICT r1
    weak #2: `float('1995-03-15')` used to crash here)."""
    if v is None:
        return None
    if isinstance(v, (int, float)) and not isinstance(v, bool):
        return float(v)
    return coerce_str_literal(str(v))

def numeric_dict_code_bounds(f, nv: np.ndarray):
    """Code-space [lo, hi] (either side possibly None) for a numeric Bound
    over a SORTED numeric dictionary, or None when numeric ordering cannot
    apply (explicit lexicographic, or a non-numeric literal).  Shared by
    the kernel compile (`bound_numdict`) and zone-map segment pruning
    (exec/engine.py) — one translation, so the two can never drift."""
    if f.ordering == "lexicographic":
        return None
    lo_f = _bound_literal(f.lower)
    hi_f = _bound_literal(f.upper)
    if (f.lower is not None and lo_f is None) or (
        f.upper is not None and hi_f is None
    ):
        return None
    lo_code = hi_code = None
    if lo_f is not None:
        side = "right" if f.lower_strict else "left"
        lo_code = int(np.searchsorted(nv, lo_f, side=side))
    if hi_f is not None:
        side = "left" if f.upper_strict else "right"
        hi_code = int(np.searchsorted(nv, hi_f, side=side)) - 1
    return lo_code, hi_code


MaskFn = Callable[[Mapping[str, jnp.ndarray]], jnp.ndarray]


class DecodedView:
    """Column mapping for *expression* evaluation: numeric-dictionary
    dimension codes decode back to their integer values (a device gather the
    compiler fuses/DCEs); all other columns pass through.  Filters, by
    contrast, are translated into code space at compile time and read the raw
    mapping — the two views share the same underlying device arrays."""

    def __init__(self, cols: Mapping, dicts: Mapping):
        self._cols = cols
        self._dicts = dicts

    def __getitem__(self, name):
        c = self._cols[name]
        d = self._dicts[name] if name in self._dicts else None
        if d is not None and d.numeric_values is not None:
            nv = jnp.asarray(d.numeric_values)
            # null codes (-1) decode to -1, matching the raw-value
            # convention; the sentinel is int64 because numeric dictionary
            # values may be int64 (times)
            # graftlint: disable=dtype-x64 -- null sentinel must match int64 dict values
            return jnp.where(c >= 0, nv[jnp.maximum(c, 0)], jnp.int64(-1))
        return c

    def __contains__(self, name):
        return name in self._cols

    def raw(self, name):
        """Undecoded column (dictionary codes for dims) — null guards in
        compiled expressions read this to exclude -1 codes exactly."""
        return self._cols[name]

    def get(self, name, default=None):
        return self[name] if name in self._cols else default


def _like_to_regex(pattern: str) -> str:
    out = []
    for ch in pattern:
        if ch == "%":
            out.append(".*")
        elif ch == "_":
            out.append(".")
        else:
            out.append(re.escape(ch))
    return "^" + "".join(out) + "$"


def like_match_codes(d, pattern: str, is_regex: bool = False) -> np.ndarray:
    """int32 codes of the dictionary values matching a LIKE (or anchored
    regex) pattern — the one dictionary->code-set translation shared by the
    filter layer and expression compilation (plan/expr.py), so LIKE
    semantics cannot drift between WHERE and CASE positions."""
    rx = re.compile(pattern if is_regex else _like_to_regex(pattern))
    return np.array(
        [i for i, v in enumerate(d.values) if rx.search(str(v))],
        dtype=np.int32,
    )


def compile_filter(f: F.Filter, ds: DataSource) -> MaskFn:
    """Returns fn(cols) -> bool[R]: the KLEENE TRUE mask — rows where the
    predicate is definitely true.  `cols` maps column name -> device array
    (dimension codes, metric values, and "__time").

    Three-valued semantics (round-3 fix: the 2-valued compile made
    `NOT <anything>` over a NULL-holding dimension match the NULL rows —
    SQL says NOT UNKNOWN = UNKNOWN = excluded): leaves report a per-row
    UNKNOWN mask (null dimension codes / NaN metrics), combinators apply
    Kleene algebra, and only definitely-TRUE rows survive."""
    fn3 = compile_filter3(f, ds)
    return lambda cols: fn3(cols)[0]


def compile_filter3(f: F.Filter, ds: DataSource):
    """fn(cols) -> (true_mask, unknown_mask) under Kleene algebra."""
    if isinstance(f, F.And):
        fns = [compile_filter3(x, ds) for x in f.fields]

        def and3(cols, fns=fns):
            pairs = [fn(cols) for fn in fns]
            t = _fold_pairs(jnp.logical_and, [p[0] for p in pairs])
            fmask = _fold_pairs(
                jnp.logical_or, [~p[0] & ~p[1] for p in pairs]
            )
            return t, ~t & ~fmask

        return and3
    if isinstance(f, F.Or):
        fns = [compile_filter3(x, ds) for x in f.fields]

        def or3(cols, fns=fns):
            pairs = [fn(cols) for fn in fns]
            t = _fold_pairs(jnp.logical_or, [p[0] for p in pairs])
            fmask = _fold_pairs(
                jnp.logical_and, [~p[0] & ~p[1] for p in pairs]
            )
            return t, ~t & ~fmask

        return or3
    if isinstance(f, F.Not):
        fn = compile_filter3(f.field, ds)

        def not3(cols, fn=fn):
            t, u = fn(cols)
            return ~t & ~u, u

        return not3
    t_fn = _leaf_true(f, ds)
    u_fn = _leaf_unknown(f, ds)
    return lambda cols: (t_fn(cols), u_fn(cols))


def _null_mask_fn(dim: str, ds: DataSource):
    """Per-row SQL-NULL mask of a column: dictionary dims use the -1 null
    code; float metrics use NaN; everything else (time, int metrics) has
    no null representation."""
    if dim in ds.dicts:
        return lambda cols: cols[dim] == jnp.int32(-1)

    def nf(cols, dim=dim):
        c = cols[dim]
        if c.dtype in (jnp.float32, jnp.float64):
            return jnp.isnan(c)
        return jnp.zeros(c.shape, jnp.bool_)

    return nf


def _leaf_unknown(f: F.Filter, ds: DataSource):
    """UNKNOWN mask of a leaf predicate: its operand column is NULL —
    except IS NULL itself (two-valued) and time-interval filters (time is
    never null).  ExpressionFilter stays 2-valued (its expression compile
    owns null coalescing; the planner keeps NOT inside the expression)."""
    if isinstance(f, F.Selector) and f.value is None:
        return lambda cols: jnp.zeros(
            jnp.shape(cols[f.dimension]), jnp.bool_
        )
    if isinstance(f, F.InFilter) and f.null_in_values:
        # the original list held a literal NULL: `x IN (..., NULL)` is
        # UNKNOWN for every non-member (x = NULL might have matched), so
        # the unknown mask is the complement of the definite-member mask
        t_fn = _leaf_true(f, ds)
        return lambda cols: ~t_fn(cols)
    if isinstance(
        f, (F.Selector, F.InFilter, F.Bound, F.Regex, F.LikeFilter)
    ):
        return _null_mask_fn(f.dimension, ds)

    def fconst(cols):
        some = next(iter(cols.values()))
        return jnp.zeros(jnp.shape(some), jnp.bool_)

    return fconst


def _leaf_true(f: F.Filter, ds: DataSource) -> MaskFn:
    """The definitely-TRUE mask of a LEAF predicate (nulls never match any
    of these by construction: code-space tests exclude -1, NaN compares
    false)."""

    if isinstance(f, F.Selector):
        dim = f.dimension
        if dim in ds.dicts:
            d = ds.dicts[dim]
            if f.value is None:
                return lambda cols: cols[dim] == jnp.int32(-1)
            code = d.code_of(f.value)
            if code is None:
                return lambda cols: jnp.zeros(cols[dim].shape, jnp.bool_)
            return lambda cols: cols[dim] == jnp.int32(code)
        if f.value is None:
            # IS NULL on a non-dictionary column — same null
            # representation the unknown masks use
            return _null_mask_fn(dim, ds)
        # numeric column equality
        v = float(f.value)  # type: ignore[arg-type]
        return lambda cols: cols[dim] == v

    if isinstance(f, F.InFilter):
        dim = f.dimension
        if dim in ds.dicts:
            d = ds.dicts[dim]
            codes = np.array(
                [c for c in (d.code_of(v) for v in f.values) if c is not None],
                dtype=np.int32,
            )
        else:
            codes = np.asarray([float(v) for v in f.values])
        if len(codes) == 0:
            return lambda cols: jnp.zeros(cols[dim].shape, jnp.bool_)
        return lambda cols: jnp.isin(cols[dim], codes)

    if isinstance(f, F.Bound):
        dim = f.dimension
        nv = ds.dicts[dim].numeric_values if dim in ds.dicts else None
        if nv is not None:
            # numeric dictionary: value bounds -> dense-code bounds (sound:
            # codes are the numeric rank, so value order == code order).
            # Honors an explicit lexicographic ordering, and falls back to
            # lexicographic when a bound literal isn't numeric.
            cb = numeric_dict_code_bounds(f, np.asarray(nv))
            if cb is not None:
                lo_code, hi_code = cb

                def bound_numdict(cols, lo=lo_code, hi=hi_code, dim=dim):
                    c = cols[dim]
                    m = c >= 0
                    if lo is not None:
                        m = m & (c >= lo)
                    if hi is not None:
                        m = m & (c <= hi)
                    return m

                return bound_numdict
            # lexicographic semantics over a numerically-sorted domain: the
            # two orders differ, so compare stringified values per code and
            # push the matching code set (O(dictionary), like Regex)
            vals = np.asarray([str(v) for v in ds.dicts[dim].values], dtype=str)
            ok = np.ones(len(vals), dtype=bool)
            # Druid coerces bound literals to strings on the wire — accept
            # numeric literals under lexicographic ordering the same way
            if f.lower is not None:
                lo_s = str(f.lower)
                ok &= (vals > lo_s) if f.lower_strict else (vals >= lo_s)
            if f.upper is not None:
                hi_s = str(f.upper)
                ok &= (vals < hi_s) if f.upper_strict else (vals <= hi_s)
            codes = np.nonzero(ok)[0].astype(np.int32)
            if len(codes) == 0:
                return lambda cols: jnp.zeros(cols[dim].shape, jnp.bool_)
            return lambda cols: jnp.isin(cols[dim], codes)
        if dim in ds.dicts and f.ordering == "lexicographic":
            vals = np.asarray(ds.dicts[dim].values, dtype=str)
            lo_code = hi_code = None
            if f.lower is not None:
                side = "right" if f.lower_strict else "left"
                lo_code = int(np.searchsorted(vals, f.lower, side=side))
            if f.upper is not None:
                side = "left" if f.upper_strict else "right"
                hi_code = int(np.searchsorted(vals, f.upper, side=side)) - 1

            def bound_dict(cols, lo=lo_code, hi=hi_code, dim=dim):
                c = cols[dim]
                m = c >= 0
                if lo is not None:
                    m = m & (c >= lo)
                if hi is not None:
                    m = m & (c <= hi)
                return m

            return bound_dict

        from ..utils.floatcmp import f32_adjusted_compare

        lo = _bound_literal(f.lower)
        hi = _bound_literal(f.upper)
        if (f.lower is not None and lo is None) or (
            f.upper is not None and hi is None
        ):
            raise ValueError(
                f"Bound on numeric column {dim!r} has a non-numeric, non-date "
                f"literal: lower={f.lower!r} upper={f.upper!r}"
            )
        # f32-exact comparators precompiled once (shared helper with expr.py);
        # the f64 fallback handles int64 columns (time ms exceeds f32 precision)
        lo_op = ">" if f.lower_strict else ">="
        hi_op = "<" if f.upper_strict else "<="
        lo32 = f32_adjusted_compare(lo_op, lo) if lo is not None else None
        hi32 = f32_adjusted_compare(hi_op, hi) if hi is not None else None

        def bound_num(cols, lo=lo, hi=hi, f=f, dim=dim):
            c = cols[dim]
            is_f32 = c.dtype == jnp.float32
            m = jnp.ones(c.shape, jnp.bool_)
            if lo is not None:
                m = m & (
                    lo32(c) if is_f32
                    else ((c > lo) if f.lower_strict else (c >= lo))
                )
            if hi is not None:
                m = m & (
                    hi32(c) if is_f32
                    else ((c < hi) if f.upper_strict else (c <= hi))
                )
            return m

        return bound_num

    if isinstance(f, (F.Regex, F.LikeFilter)):
        dim = f.dimension
        codes = like_match_codes(
            ds.dicts[dim], f.pattern, is_regex=isinstance(f, F.Regex)
        )
        if len(codes) == 0:
            return lambda cols: jnp.zeros(cols[dim].shape, jnp.bool_)
        return lambda cols: jnp.isin(cols[dim], codes)

    if isinstance(f, F.IntervalFilter):
        dim = f.dimension
        ivs = f.intervals

        def interval(cols, ivs=ivs, dim=dim):
            t = cols[dim]
            m = jnp.zeros(t.shape, jnp.bool_)
            for a, b in ivs:
                m = m | ((t >= a) & (t < b))
            return m

        return interval

    if isinstance(f, F.ExpressionFilter):
        fn = compile_expr(f.expression, ds.dicts)
        dicts = ds.dicts
        return lambda cols: jnp.asarray(
            fn(DecodedView(cols, dicts))
        ).astype(jnp.bool_)

    raise TypeError(f"cannot compile filter {f!r}")


def _fold_pairs(op, masks):
    acc = masks[0]
    for m in masks[1:]:
        acc = op(acc, m)
    return acc
