"""Query specs — the compact execution contract between planner and engine.

Reference parity: `QuerySpec` hierarchy (GroupBy / TopN / Timeseries / Select /
Search / Scan), `HavingSpec`, `LimitSpec`, `OrderByColumnSpec` — SURVEY.md §2
query-model row, expected `org/sparklinedata/druid/DruidQuery.scala` `[U]`.
In the reference these serialize to JSON and travel over HTTP to a Druid
broker; here the same objects are *kernel launch specs* consumed by
`exec/engine.py` (and they still serialize to Druid-wire JSON via
`to_druid()`, preserving the option of differential testing against a real
Druid, per SURVEY.md §7 L-spec).

Specificity order for planner choice (reference: Timeseries ⊂ TopN ⊂ GroupBy,
SURVEY.md §3.2): a Timeseries is a GroupBy whose only dimension is the time
bucket; a TopN is a single-dimension GroupBy with a metric-ordered limit.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

from .aggregations import Aggregation, PostAggregation
from .dimensions import DimensionSpec
from .filters import Filter, _ms_to_iso


@dataclasses.dataclass(frozen=True)
class VirtualColumn:
    """Derived per-row scalar column computed on device before aggregation
    (e.g. `l_extendedprice * (1 - l_discount)`).  Compiled by
    `ops/expressions.py` into fused XLA element-wise ops — the TPU-native
    replacement for the reference's JS-codegen virtual metrics."""

    name: str
    expression: Any  # plan.expr.Expr
    dtype: str = "double"

    def to_druid(self):
        return {
            "type": "expression",
            "name": self.name,
            "expression": str(self.expression),
            "outputType": "DOUBLE" if self.dtype == "double" else "LONG",
        }


@dataclasses.dataclass(frozen=True)
class OrderByColumnSpec:
    dimension: str
    direction: str = "ascending"  # ascending | descending

    def to_druid(self):
        return {"dimension": self.dimension, "direction": self.direction}


@dataclasses.dataclass(frozen=True)
class LimitSpec:
    limit: Optional[int]
    columns: Tuple[OrderByColumnSpec, ...] = ()
    offset: int = 0

    def to_druid(self):
        d: Dict[str, Any] = {"type": "default"}
        if self.limit is not None:
            d["limit"] = self.limit
        if self.offset:
            d["offset"] = self.offset
        d["columns"] = [c.to_druid() for c in self.columns]
        return d


class QueryValidationError(ValueError):
    """A decoded query names something the datasource cannot satisfy
    (unknown orderBy column, time ordering on a timeless table) — a CLIENT
    error (HTTP 400), distinct from internal ValueErrors (500)."""


class Having:
    def to_druid(self) -> Dict[str, Any]:
        raise NotImplementedError


@dataclasses.dataclass(frozen=True)
class HavingCompare(Having):
    """aggregate <op> value, op in {>, <, ==, >=, <=, !=}."""

    aggregation: str
    op: str
    value: float

    def to_druid(self):
        m = {">": "greaterThan", "<": "lessThan", "==": "equalTo"}
        if self.op in m:
            return {
                "type": m[self.op],
                "aggregation": self.aggregation,
                "value": self.value,
            }
        inner = {">=": "lessThan", "<=": "greaterThan", "!=": "equalTo"}[self.op]
        return {
            "type": "not",
            "havingSpec": {
                "type": inner,
                "aggregation": self.aggregation,
                "value": self.value,
            },
        }


@dataclasses.dataclass(frozen=True)
class HavingAnd(Having):
    specs: Tuple[Having, ...]

    def to_druid(self):
        return {"type": "and", "havingSpecs": [s.to_druid() for s in self.specs]}


@dataclasses.dataclass(frozen=True)
class HavingOr(Having):
    specs: Tuple[Having, ...]

    def to_druid(self):
        return {"type": "or", "havingSpecs": [s.to_druid() for s in self.specs]}


@dataclasses.dataclass(frozen=True)
class HavingNot(Having):
    """Druid `not` havingSpec — needed to decode wire queries whose NOT
    wraps a compound spec (our own serializer only emits NOT around
    compares, which fold into >=/<=/!=)."""

    spec: Having

    def to_druid(self):
        return {"type": "not", "havingSpec": self.spec.to_druid()}


def _ivs(intervals):
    return [f"{_ms_to_iso(a)}/{_ms_to_iso(b)}" for a, b in intervals] or [
        "0000-01-01T00:00:00.000Z/3000-01-01T00:00:00.000Z"
    ]


class QuerySpec:
    """Base of all query specs."""

    datasource: str

    def to_druid(self) -> Dict[str, Any]:
        raise NotImplementedError


@dataclasses.dataclass(frozen=True)
class GroupByQuery(QuerySpec):
    datasource: str
    dimensions: Tuple[DimensionSpec, ...]
    aggregations: Tuple[Aggregation, ...]
    post_aggregations: Tuple[PostAggregation, ...] = ()
    filter: Optional[Filter] = None
    having: Optional[Having] = None
    limit_spec: Optional[LimitSpec] = None
    intervals: Tuple[Tuple[int, int], ...] = ()
    granularity: str = "all"
    virtual_columns: Tuple[VirtualColumn, ...] = ()
    # grouping-set support (GROUP BY CUBE/ROLLUP/GROUPING SETS): each entry is
    # a bitmask over `dimensions` marking which dims are active in that set.
    subtotals: Tuple[Tuple[int, ...], ...] = ()

    def to_druid(self):
        d: Dict[str, Any] = {
            "queryType": "groupBy",
            "dataSource": self.datasource,
            "granularity": self.granularity,
            "dimensions": [x.to_druid() for x in self.dimensions],
            "aggregations": [a.to_druid() for a in self.aggregations],
            "intervals": _ivs(self.intervals),
        }
        if self.virtual_columns:
            d["virtualColumns"] = [v.to_druid() for v in self.virtual_columns]
        if self.post_aggregations:
            d["postAggregations"] = [p.to_druid() for p in self.post_aggregations]
        if self.filter is not None:
            d["filter"] = self.filter.to_druid()
        if self.having is not None:
            d["having"] = self.having.to_druid()
        if self.limit_spec is not None:
            d["limitSpec"] = self.limit_spec.to_druid()
        if self.subtotals:
            d["subtotalsSpec"] = [
                [self.dimensions[i].name for i in s] for s in self.subtotals
            ]
        return d


@dataclasses.dataclass(frozen=True)
class TopNQuery(QuerySpec):
    datasource: str
    dimension: DimensionSpec
    metric: str  # aggregation/post-agg name to rank by
    threshold: int
    aggregations: Tuple[Aggregation, ...]
    post_aggregations: Tuple[PostAggregation, ...] = ()
    filter: Optional[Filter] = None
    intervals: Tuple[Tuple[int, int], ...] = ()
    granularity: str = "all"
    virtual_columns: Tuple[VirtualColumn, ...] = ()
    descending: bool = True

    def _metric_to_druid(self):
        """Druid wire metric spec.  Ranking by the dimension's own value is
        recognized by name — but only when no aggregation/post-agg claims
        that name (an aggregate deliberately named like the dimension must
        stay a numeric metric spec).  Descending dimension order uses
        Druid's inverted-wrapped lexicographic form; ascending aggregates
        the inverted wrapper."""
        agg_names = {a.name for a in self.aggregations} | {
            p.name for p in self.post_aggregations
        }
        if self.metric == self.dimension.name and self.metric not in agg_names:
            dim_spec = {"type": "dimension", "ordering": "lexicographic"}
            if self.descending:
                return {"type": "inverted", "metric": dim_spec}
            return dim_spec
        if self.descending:
            return self.metric
        return {"type": "inverted", "metric": self.metric}

    def to_druid(self):
        d: Dict[str, Any] = {
            "queryType": "topN",
            "dataSource": self.datasource,
            "granularity": self.granularity,
            "dimension": self.dimension.to_druid(),
            "metric": self._metric_to_druid(),
            "threshold": self.threshold,
            "aggregations": [a.to_druid() for a in self.aggregations],
            "intervals": _ivs(self.intervals),
        }
        if self.virtual_columns:
            d["virtualColumns"] = [v.to_druid() for v in self.virtual_columns]
        if self.post_aggregations:
            d["postAggregations"] = [p.to_druid() for p in self.post_aggregations]
        if self.filter is not None:
            d["filter"] = self.filter.to_druid()
        return d


@dataclasses.dataclass(frozen=True)
class TimeseriesQuery(QuerySpec):
    datasource: str
    granularity: str  # "hour", "day", ... or ISO period "PT1H"
    aggregations: Tuple[Aggregation, ...]
    post_aggregations: Tuple[PostAggregation, ...] = ()
    filter: Optional[Filter] = None
    intervals: Tuple[Tuple[int, int], ...] = ()
    virtual_columns: Tuple[VirtualColumn, ...] = ()
    descending: bool = False
    skip_empty_buckets: bool = True
    # result column for the bucket timestamp: "timestamp" is Druid's wire
    # name; SQL carries the user's alias (SELECT date_trunc(...) AS mo)
    output_name: str = "timestamp"

    def to_druid(self):
        d: Dict[str, Any] = {
            "queryType": "timeseries",
            "dataSource": self.datasource,
            "granularity": self.granularity,
            "aggregations": [a.to_druid() for a in self.aggregations],
            "intervals": _ivs(self.intervals),
            "descending": self.descending,
        }
        if self.virtual_columns:
            d["virtualColumns"] = [v.to_druid() for v in self.virtual_columns]
        if self.post_aggregations:
            d["postAggregations"] = [p.to_druid() for p in self.post_aggregations]
        if self.filter is not None:
            d["filter"] = self.filter.to_druid()
        if self.skip_empty_buckets:
            d["context"] = {"skipEmptyBuckets": True}
        if self.output_name != "timestamp":
            # not Druid wire vocabulary, but the serialized form is also the
            # program/result cache identity — two queries differing only in
            # the SQL alias must not collide
            d.setdefault("context", {})["outputName"] = self.output_name
        return d


@dataclasses.dataclass(frozen=True)
class ScanQuery(QuerySpec):
    """Row scan (the reference's Select/Scan path for non-aggregate queries,
    gated by its `nonAggregateQueryHandling` option)."""

    datasource: str
    columns: Tuple[str, ...]
    filter: Optional[Filter] = None
    intervals: Tuple[Tuple[int, int], ...] = ()
    limit: Optional[int] = None
    virtual_columns: Tuple[VirtualColumn, ...] = ()
    # Druid scan `orderBy` (column-value ordering) + result offset; an
    # ordering the engine cannot honor must be a planner error, never a
    # silent drop — unsorted rows under LIMIT are wrong rows
    order_by: Tuple["OrderByColumnSpec", ...] = ()
    offset: int = 0
    # Druid scan resultFormat: "list" (events as dicts) or "compactedList"
    # (events as positional value arrays) — a WIRE-shape concern only
    result_format: str = "list"

    def to_druid(self):
        d: Dict[str, Any] = {
            "queryType": "scan",
            "dataSource": self.datasource,
            "columns": list(self.columns),
            "intervals": _ivs(self.intervals),
        }
        if self.result_format != "list":
            d["resultFormat"] = self.result_format
        if self.virtual_columns:
            d["virtualColumns"] = [v.to_druid() for v in self.virtual_columns]
        if self.filter is not None:
            d["filter"] = self.filter.to_druid()
        if self.limit is not None:
            d["limit"] = self.limit
        if self.order_by:
            d["orderBy"] = [
                {"columnName": c.dimension, "order": c.direction}
                for c in self.order_by
            ]
        if self.offset:
            d["offset"] = self.offset
        return d


@dataclasses.dataclass(frozen=True)
class SearchQuery(QuerySpec):
    """Dimension-value search (Druid `search`): find dimension values matching
    a substring/regex.  On TPU this is pure host-side dictionary work."""

    datasource: str
    dimensions: Tuple[str, ...]
    query: str  # case-insensitive contains
    filter: Optional[Filter] = None
    intervals: Tuple[Tuple[int, int], ...] = ()
    limit: int = 1000

    def to_druid(self):
        return {
            "queryType": "search",
            "dataSource": self.datasource,
            "searchDimensions": list(self.dimensions),
            "query": {"type": "insensitive_contains", "value": self.query},
            "intervals": _ivs(self.intervals),
            "limit": self.limit,
        }


@dataclasses.dataclass(frozen=True)
class DataSourceMetadataQuery(QuerySpec):
    """Druid `dataSourceMetadata`: the newest ingested event time.  The
    reference's coordinator client polled this family of endpoints for
    freshness (SURVEY.md §3.1 metadata path); answered from segment
    metadata, no kernel dispatch."""

    datasource: str

    def to_druid(self):
        return {
            "queryType": "dataSourceMetadata",
            "dataSource": self.datasource,
        }


@dataclasses.dataclass(frozen=True)
class TimeBoundaryQuery(QuerySpec):
    """Druid `timeBoundary`: min/max event time of a datasource.  The
    reference's metadata path issues these to size intervals; locally it is
    answered from segment metadata (no kernel dispatch)."""

    datasource: str
    bound: Optional[str] = None  # None -> both | "minTime" | "maxTime"

    def to_druid(self):
        d: Dict[str, Any] = {
            "queryType": "timeBoundary",
            "dataSource": self.datasource,
        }
        if self.bound:
            d["bound"] = self.bound
        return d


@dataclasses.dataclass(frozen=True)
class SegmentMetadataQuery(QuerySpec):
    """Druid `segmentMetadata`: per-segment column analysis (types,
    cardinalities, row counts).  The reference's DruidMetadataCache boots
    from exactly this query (SURVEY.md §3.1); locally the catalog IS that
    metadata, so this renders it in Druid's wire shape."""

    datasource: str
    intervals: Tuple[Tuple[int, int], ...] = ()

    def to_druid(self):
        d: Dict[str, Any] = {
            "queryType": "segmentMetadata",
            "dataSource": self.datasource,
        }
        if self.intervals:
            d["intervals"] = _ivs(self.intervals)
        return d
