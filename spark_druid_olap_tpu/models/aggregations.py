"""Aggregation + post-aggregation spec families — Druid JSON mirror.

Reference parity: `AggregationSpec` family (count, long/double sum/min/max,
hyperUnique, cardinality, javascript, filtered) and `PostAggregationSpec`
family (arithmetic, fieldAccess, constant, hyperUniqueCardinality) —
SURVEY.md §2 query-model row, expected `org/sparklinedata/druid/DruidQuery.scala`
`[U]`.  The reference maps Spark aggregate functions onto these in
`AggregateTransform` (AVG becomes sum+count plus an arithmetic post-agg;
approx_count_distinct becomes cardinality/hyperUnique) — our planner does the
same mapping in `plan/transforms.py`.

Each aggregator here is also the *merge contract* for the distributed engine:
`merge_op` names the ICI collective used to combine per-device partial states
("psum" for sums/counts, "pmin"/"pmax" for extrema and HLL registers,
"union" for theta sketches / TopN candidates) — see `parallel/merge.py`.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

from .filters import Filter


class Aggregation:
    name: str

    def to_druid(self) -> Dict[str, Any]:
        raise NotImplementedError

    @property
    def merge_op(self) -> str:
        raise NotImplementedError


@dataclasses.dataclass(frozen=True)
class Count(Aggregation):
    name: str

    def to_druid(self):
        return {"type": "count", "name": self.name}

    merge_op = "psum"


@dataclasses.dataclass(frozen=True)
class LongSum(Aggregation):
    name: str
    field_name: str

    def to_druid(self):
        return {"type": "longSum", "name": self.name, "fieldName": self.field_name}

    merge_op = "psum"


@dataclasses.dataclass(frozen=True)
class DoubleSum(Aggregation):
    name: str
    field_name: str

    def to_druid(self):
        return {"type": "doubleSum", "name": self.name, "fieldName": self.field_name}

    merge_op = "psum"


@dataclasses.dataclass(frozen=True)
class LongMin(Aggregation):
    name: str
    field_name: str

    def to_druid(self):
        return {"type": "longMin", "name": self.name, "fieldName": self.field_name}

    merge_op = "pmin"


@dataclasses.dataclass(frozen=True)
class LongMax(Aggregation):
    name: str
    field_name: str

    def to_druid(self):
        return {"type": "longMax", "name": self.name, "fieldName": self.field_name}

    merge_op = "pmax"


@dataclasses.dataclass(frozen=True)
class DoubleMin(Aggregation):
    name: str
    field_name: str

    def to_druid(self):
        return {"type": "doubleMin", "name": self.name, "fieldName": self.field_name}

    merge_op = "pmin"


@dataclasses.dataclass(frozen=True)
class DoubleMax(Aggregation):
    name: str
    field_name: str

    def to_druid(self):
        return {"type": "doubleMax", "name": self.name, "fieldName": self.field_name}

    merge_op = "pmax"


@dataclasses.dataclass(frozen=True)
class HyperUnique(Aggregation):
    """Approximate COUNT(DISTINCT) via HyperLogLog register arrays.

    Druid's `hyperUnique` aggregates a pre-built HLL metric; its `cardinality`
    aggregator builds HLL from dimension values at query time.  On TPU both are
    the same kernel (ops/hll.py): hash -> (bucket, rho) -> per-group
    register-max.  Partial state = uint8/int32 registers[G, 2^p]; merge =
    element-wise max (pmax over ICI).
    """

    name: str
    field_name: str
    precision: int = 11  # 2^11 = 2048 registers; ~2.3% relative std error

    def to_druid(self):
        return {"type": "hyperUnique", "name": self.name, "fieldName": self.field_name}

    merge_op = "pmax"


@dataclasses.dataclass(frozen=True)
class CardinalityAgg(Aggregation):
    """Druid `cardinality` aggregator (HLL over dimension values at query time)."""

    name: str
    field_names: tuple
    by_row: bool = False
    precision: int = 11

    def to_druid(self):
        return {
            "type": "cardinality",
            "name": self.name,
            "fields": list(self.field_names),
            "byRow": self.by_row,
        }

    merge_op = "pmax"


@dataclasses.dataclass(frozen=True)
class ThetaSketch(Aggregation):
    """KMV/theta sketch distinct-count: keep the K smallest 64-bit hashes.

    Partial state = sorted uint hashes[G, K]; merge = concat + sort + take-K
    (set union in the KMV sense) — `merge_op = "union"`, implemented with an
    all_gather + re-sort in `parallel/merge.py` (Druid merges theta sketches
    on the broker the same way, SURVEY.md §2 scatter-gather row `[U]`).
    """

    name: str
    field_name: str
    size: int = 4096  # K

    def to_druid(self):
        return {
            "type": "thetaSketch",
            "name": self.name,
            "fieldName": self.field_name,
            "size": self.size,
        }

    merge_op = "union"


@dataclasses.dataclass(frozen=True)
class FilteredAgg(Aggregation):
    """Druid `filtered` aggregator: inner aggregation under an extra predicate
    (how `SUM(x) FILTER (WHERE p)` / conditional counts push down)."""

    filter: Filter
    aggregator: Aggregation

    @property
    def name(self):
        return self.aggregator.name

    def to_druid(self):
        return {
            "type": "filtered",
            "filter": self.filter.to_druid(),
            "aggregator": self.aggregator.to_druid(),
        }

    @property
    def merge_op(self):
        return self.aggregator.merge_op


@dataclasses.dataclass(frozen=True)
class ExpressionAgg(Aggregation):
    """Aggregate over a derived scalar expression (virtual column) — the
    TPU-native replacement for the reference's JavaScript aggregator
    (SURVEY.md L0 `[U]`): the expression compiles to fused XLA element-wise
    ops feeding the aggregation kernel, instead of JS source for Druid.
    `base` is the underlying exact aggregator (sum/min/max) applied to the
    expression's value."""

    name: str
    expression: Any  # plan.expr.Expr
    base: str = "doubleSum"  # doubleSum | doubleMin | doubleMax | longSum

    def to_druid(self):
        return {
            "type": "javascript",  # wire-compat slot the reference would use
            "name": self.name,
            "expression": str(self.expression),
            "base": self.base,
        }

    @property
    def merge_op(self):
        return {"doubleSum": "psum", "longSum": "psum", "doubleMin": "pmin",
                "doubleMax": "pmax"}[self.base]


# ----------------------------------------------------------------------------
# Post-aggregations (computed host-side over merged aggregate outputs — tiny)
# ----------------------------------------------------------------------------


class PostAggregation:
    name: str

    def to_druid(self) -> Dict[str, Any]:
        raise NotImplementedError


@dataclasses.dataclass(frozen=True)
class FieldAccess(PostAggregation):
    name: str
    field_name: str

    def to_druid(self):
        return {"type": "fieldAccess", "name": self.name, "fieldName": self.field_name}


@dataclasses.dataclass(frozen=True)
class ConstantPost(PostAggregation):
    name: str
    value: float

    def to_druid(self):
        return {"type": "constant", "name": self.name, "value": self.value}


@dataclasses.dataclass(frozen=True)
class Arithmetic(PostAggregation):
    """fn in {+, -, *, /, quotient, pow}; fields are other post-aggs."""

    name: str
    fn: str
    fields: tuple  # Tuple[PostAggregation, ...]

    def to_druid(self):
        return {
            "type": "arithmetic",
            "name": self.name,
            "fn": self.fn,
            "fields": [f.to_druid() for f in self.fields],
        }


@dataclasses.dataclass(frozen=True)
class DimCodeMax(Aggregation):
    """max over a dimension's dictionary CODES — the carrier for
    functional-dependency grouping pruning.  When the planner drops a
    grouped column whose value is determined by another grouped column
    (declared FunctionalDependency, SURVEY.md §2 star-schema row), every
    row of a group shares one code for the pruned column, so max(code)
    recovers it; the API layer decodes code -> value host-side.  Internal
    wire extension type "dimCodeMax" (not part of Druid's dialect)."""

    name: str
    field_name: str

    def to_druid(self):
        return {
            "type": "dimCodeMax",
            "name": self.name,
            "fieldName": self.field_name,
        }

    merge_op = "pmax"


@dataclasses.dataclass(frozen=True)
class QuantilesSketch(Aggregation):
    """Approximate-quantile sketch (Druid `quantilesDoublesSketch` analog).

    State = per-group bottom-K random-priority value sample plus an exact
    N counter, int32[G, K+1, 2] (ops/quantiles.py); merge = concat +
    sort-by-priority + take-K, counters add (`merge_op = "union"`, same
    all_gather fold as theta).  The agg's own output column finalizes to
    the exact row count N (Druid's sketch finalization); quantile values
    come from the `QuantileFromSketch` post-agg
    (`APPROX_QUANTILE(col, p)` in SQL)."""

    name: str
    field_name: str
    size: int = 1024  # K; ~±1.5% rank error at the median

    def to_druid(self):
        return {
            "type": "quantilesDoublesSketch",
            "name": self.name,
            "fieldName": self.field_name,
            "k": self.size,
        }

    merge_op = "union"


@dataclasses.dataclass(frozen=True)
class ExpressionPost(PostAggregation):
    """Druid `expression` post-aggregator: an arbitrary scalar expression
    over the result row's columns (aggregate outputs and dimensions),
    evaluated host-side at finalize.  The wire form carries the expression
    as a string that re-parses under the SQL expression grammar — the same
    convention virtualColumns use."""

    name: str
    expression: Any  # plan.expr.Expr

    def to_druid(self):
        return {
            "type": "expression",
            "name": self.name,
            "expression": str(self.expression),
        }


@dataclasses.dataclass(frozen=True)
class HyperUniqueCardinality(PostAggregation):
    """Finalize an HLL state into a cardinality estimate."""

    name: str
    field_name: str

    def to_druid(self):
        return {
            "type": "hyperUniqueCardinality",
            "name": self.name,
            "fieldName": self.field_name,
        }


@dataclasses.dataclass(frozen=True)
class ThetaSketchEstimate(PostAggregation):
    name: str
    field_name: str

    def to_druid(self):
        return {
            "type": "thetaSketchEstimate",
            "name": self.name,
            "field": {"type": "fieldAccess", "fieldName": self.field_name},
        }


@dataclasses.dataclass(frozen=True)
class QuantileFromSketch(PostAggregation):
    """Finalize a quantiles-sketch state into the value at `fraction`
    (Druid `quantilesDoublesSketchToQuantile`)."""

    name: str
    field_name: str
    fraction: float

    def to_druid(self):
        return {
            "type": "quantilesDoublesSketchToQuantile",
            "name": self.name,
            "field": {"type": "fieldAccess", "fieldName": self.field_name},
            "fraction": self.fraction,
        }


@dataclasses.dataclass(frozen=True)
class ThetaSketchSetOp(PostAggregation):
    """Estimate of a set operation over theta sketch states (Druid's
    `thetaSketchSetOp` wrapped in `thetaSketchEstimate`): UNION / INTERSECT /
    NOT over the named thetaSketch aggregations in the same query.  Evaluated
    from raw per-group KMV states at finalize (ops/theta.py set_op_estimate)."""

    name: str
    fn: str  # "UNION" | "INTERSECT" | "NOT"
    field_names: Tuple[str, ...]

    def to_druid(self):
        return {
            "type": "thetaSketchEstimate",
            "name": self.name,
            "field": {
                "type": "thetaSketchSetOp",
                "name": f"{self.name}__setop",
                "func": self.fn,
                "fields": [
                    {"type": "fieldAccess", "fieldName": f}
                    for f in self.field_names
                ],
            },
        }
