"""Druid wire-JSON -> QuerySpec decoding (the inbound half of wire compat).

Reference parity: the reference *emits* this JSON for an external Druid to
interpret (SURVEY.md §2 query-model row `[U]`); our specs have carried
`to_druid()` since round 1 for differential testing.  This module closes the
loop: `query_from_druid` parses the same JSON back into executable specs, so
the L7 serving surface (server.py) can accept native Druid queries from
existing clients, and `q == query_from_druid(q.to_druid())` round-trips are
testable.

Limits (documented, loud): JavaScript aggregators/filters are accepted only
when their `expression` string re-parses under our SQL expression grammar
(the `to_druid()` printer emits exactly that form for everything except
CASE/IF trees); true JS source raises.

This module is the REGISTRY graftlint's wire-parity pass (GL10xx) reads:
every queryType branch in `query_from_druid` and every aggregator class
in `agg_from_druid` must be referenced by the device dispatch
(exec/engine.py), the wire result shaping (server.py), the device
lowering (exec/lowering.py), and the host fallback's WIRE_AGG_FALLBACK
translation table (exec/fallback.py).  Registering a new wire feature
here without teaching those surfaces fails the lint gate.
"""

from __future__ import annotations

from typing import Any, Dict, List, Tuple

import numpy as np

from . import aggregations as A
from . import query as Q
from .dimensions import (
    CaseExtraction,
    DimensionSpec,
    RegexExtraction,
    SubstringExtraction,
    TimeFieldExtraction,
    TimeFormatExtraction,
)
from .filters import Filter, filter_from_druid


class WireError(ValueError):
    pass


def _expr(source: str):
    from ..sql.lexer import LexError
    from ..sql.parser import ParseError, Parser

    try:
        p = Parser(source)
        e = p.expr()
        if p.peek().kind != "EOF":
            # a half-parsed expression ("s * 2 bogus") must be rejected,
            # not silently truncated to the parseable prefix
            raise WireError(
                f"expression {source!r} has trailing input at "
                f"{p.peek().value!r}"
            )
        return e
    except WireError:
        raise
    except (ParseError, LexError) as e:  # malformed CLIENT input -> 400;
        # anything else is an internal parser bug and stays a 500
        raise WireError(
            f"expression {source!r} does not re-parse under the SQL "
            f"expression grammar: {e}"
        ) from None


def agg_from_druid(d: Dict[str, Any]) -> A.Aggregation:
    t = d["type"]
    if t == "count":
        return A.Count(d["name"])
    simple = {
        "longSum": A.LongSum,
        "doubleSum": A.DoubleSum,
        "floatSum": A.DoubleSum,
        "longMin": A.LongMin,
        "doubleMin": A.DoubleMin,
        "floatMin": A.DoubleMin,
        "longMax": A.LongMax,
        "doubleMax": A.DoubleMax,
        "floatMax": A.DoubleMax,
    }
    if t in simple:
        return simple[t](d["name"], d["fieldName"])
    if t == "hyperUnique":
        return A.HyperUnique(d["name"], d["fieldName"], d.get("precision", 11))
    if t == "cardinality":
        fields = tuple(d.get("fields") or d.get("fieldNames") or ())
        return A.CardinalityAgg(
            d["name"], fields, d.get("byRow", False), d.get("precision", 11)
        )
    if t == "thetaSketch":
        return A.ThetaSketch(d["name"], d["fieldName"], d.get("size", 4096))
    if t == "quantilesDoublesSketch":
        return A.QuantilesSketch(d["name"], d["fieldName"], d.get("k", 1024))
    if t == "dimCodeMax":  # internal FD-pruning carrier (not Druid dialect)
        return A.DimCodeMax(d["name"], d["fieldName"])
    if t == "filtered":
        return A.FilteredAgg(
            filter_from_druid(d["filter"]), agg_from_druid(d["aggregator"])
        )
    if t == "javascript":
        return A.ExpressionAgg(
            d["name"], _expr(d["expression"]), d.get("base", "doubleSum")
        )
    raise WireError(f"unsupported aggregation type {t!r}")


def post_agg_from_druid(d: Dict[str, Any]) -> A.PostAggregation:
    t = d["type"]
    if t == "fieldAccess":
        return A.FieldAccess(d.get("name", d["fieldName"]), d["fieldName"])
    if t == "constant":
        return A.ConstantPost(d.get("name", "const"), d["value"])
    if t == "arithmetic":
        return A.Arithmetic(
            d["name"], d["fn"], tuple(post_agg_from_druid(f) for f in d["fields"])
        )
    if t == "hyperUniqueCardinality":
        return A.HyperUniqueCardinality(d.get("name", d["fieldName"]), d["fieldName"])
    if t == "thetaSketchEstimate":
        f = d.get("field", {})
        if f.get("type") == "thetaSketchSetOp":
            fn = f.get("func", f.get("fn"))
            fields = tuple(x["fieldName"] for x in f.get("fields", ()))
            if fn not in ("UNION", "INTERSECT", "NOT"):
                raise WireError(f"thetaSketchSetOp func {fn!r}")
            if not fields:
                raise WireError("thetaSketchSetOp requires fields")
            return A.ThetaSketchSetOp(d["name"], fn, fields)
        return A.ThetaSketchEstimate(d["name"], f.get("fieldName", d.get("fieldName")))
    if t == "expression":
        return A.ExpressionPost(d["name"], _expr(d["expression"]))
    if t == "quantilesDoublesSketchToQuantile":
        f = d.get("field", {})
        return A.QuantileFromSketch(
            d["name"], f.get("fieldName", d.get("fieldName")), d["fraction"]
        )
    raise WireError(f"unsupported postAggregation type {t!r}")


def _extraction_from_druid(d: Dict[str, Any]):
    t = d["type"]
    if t == "substring":
        return SubstringExtraction(d["index"], d.get("length"))
    if t == "upper":
        return CaseExtraction(upper=True)
    if t == "lower":
        return CaseExtraction(upper=False)
    if t == "regex":
        return RegexExtraction(d["expr"], d.get("index", 1))
    if t == "lookup":
        from .dimensions import LookupExtraction

        lk = d.get("lookup", {})
        if lk.get("type") != "map":
            raise WireError(f"unsupported lookup type {lk.get('type')!r}")
        return LookupExtraction.from_mapping(
            d.get("name", "wire"),
            lk.get("map") or {},
            retain_missing=bool(d.get("retainMissingValue", False)),
            replace_missing=d.get("replaceMissingValueWith"),
        )
    if t == "stringFormat":
        from .dimensions import FormatExtraction

        fmt = d.get("format", "%s")
        # protect escaped %% before locating the single %s conversion
        guarded = fmt.replace("%%", "\x00")
        if guarded.count("%s") != 1:
            raise WireError(
                f"stringFormat must contain exactly one %s: {fmt!r}"
            )
        pre, suf = (
            p.replace("\x00", "%") for p in guarded.split("%s", 1)
        )
        return FormatExtraction(pre, suf)
    if t == "strlen":
        from .dimensions import StrlenExtraction

        return StrlenExtraction()
    if t == "cascade":
        from .dimensions import CascadeExtraction

        return CascadeExtraction(
            tuple(
                _extraction_from_druid(f) for f in d.get("extractionFns", ())
            )
        )
    if t == "timeFormat":
        fmt = d.get("format", "%Y")
        # field-shaped formats decode to the int-valued EXTRACT dimension
        for field, f in TimeFieldExtraction._FORMATS.items():
            if fmt == f:
                return TimeFieldExtraction(field)
        return TimeFormatExtraction(fmt, d.get("granularity"))
    raise WireError(f"unsupported extractionFn type {t!r}")


def dimension_from_druid(d) -> DimensionSpec:
    if isinstance(d, str):
        return DimensionSpec(d)
    t = d.get("type", "default")
    if t == "default":
        return DimensionSpec(d["dimension"], d.get("outputName"))
    if t == "extraction":
        return DimensionSpec(
            d["dimension"],
            d.get("outputName"),
            extraction=_extraction_from_druid(d["extractionFn"]),
        )
    raise WireError(f"unsupported dimension type {t!r}")


def _iso_ms(s: str) -> int:
    return int(np.datetime64(s.rstrip("Z"), "ms").astype(np.int64))


# Any start at-or-before year 0000 / end at-or-past year 3000 is treated as
# unbounded — covers our own _ETERNITY spelling, variants without millis,
# and anything a client means as "everything".
_ETERNITY_LO = int(np.datetime64("0000-01-01", "ms").astype(np.int64))
_ETERNITY_HI = int(np.datetime64("3000-01-01", "ms").astype(np.int64))
# Druid's canonical eternity instants (Long.MIN/MAX_VALUE as millis) have
# six-digit years np.datetime64 cannot parse; match them by prefix.
_DRUID_MIN_PREFIX = "-146136543-"
_DRUID_MAX_PREFIX = "146140482-"


def _bound_ms(s: str) -> int:
    s = s.strip()
    # Druid's canonical instants parse to values far outside the sentinel
    # range; genuine far-future/far-past bounds pass through UNCLAMPED so a
    # real [3500, 3600) interval stays a real interval
    if s.startswith(_DRUID_MIN_PREFIX):
        return -(1 << 62)
    if s.startswith(_DRUID_MAX_PREFIX):
        return 1 << 62
    return _iso_ms(s)


def intervals_from_druid(ivs: List[str]) -> Tuple[Tuple[int, int], ...]:
    # an eternity interval is the wire form of "no constraint" (Druid
    # requires an intervals field; our specs use () — a round-trip must not
    # turn it into a real time filter, which would demand a time column).
    # Detected by parsed bounds, not string equality: Druid's canonical
    # spelling, ours, and milliless variants must all decode to ().
    out = []
    for iv in ivs or ():
        a, b = iv.split("/")
        am = _bound_ms(a)
        bm = _bound_ms(b)
        if am <= _ETERNITY_LO and bm >= _ETERNITY_HI:
            # intervals union: eternity subsumes everything
            return ()
        out.append((am, bm))
    return tuple(out)


def granularity_from_druid(g) -> str:
    if isinstance(g, str):
        return g
    if isinstance(g, dict):
        if g.get("type") == "period":
            return g["period"]
        if g.get("type") == "all":
            return "all"
    raise WireError(f"unsupported granularity {g!r}")


def _common(d):
    filt = filter_from_druid(d["filter"]) if d.get("filter") else None
    ivs = intervals_from_druid(d.get("intervals", []))
    vcols = tuple(
        Q.VirtualColumn(
            v["name"],
            _expr(v["expression"]),
            "double" if v.get("outputType", "DOUBLE") == "DOUBLE" else "long",
        )
        for v in d.get("virtualColumns", ())
    )
    aggs = tuple(agg_from_druid(a) for a in d.get("aggregations", ()))
    posts = tuple(post_agg_from_druid(p) for p in d.get("postAggregations", ()))
    return filt, ivs, vcols, aggs, posts


def having_from_druid(d: Dict[str, Any]) -> Q.Having:
    """Druid havingSpec -> model.  A having the engine can't honor must be
    a WireError, never a silent drop (it filters result rows)."""
    t = d.get("type")
    ops = {"greaterThan": ">", "lessThan": "<", "equalTo": "=="}
    if t in ops:
        return Q.HavingCompare(d["aggregation"], ops[t], d["value"])
    if t == "and":
        return Q.HavingAnd(
            tuple(having_from_druid(s) for s in d["havingSpecs"])
        )
    if t == "or":
        return Q.HavingOr(
            tuple(having_from_druid(s) for s in d["havingSpecs"])
        )
    if t == "not":
        return Q.HavingNot(having_from_druid(d["havingSpec"]))
    raise WireError(f"unsupported havingSpec type {t!r}")


def query_from_druid(d: Dict[str, Any]) -> Q.QuerySpec:
    qt = d.get("queryType")
    ds = d.get("dataSource")
    if isinstance(ds, dict):
        ds = ds.get("name")
    if qt == "groupBy":
        filt, ivs, vcols, aggs, posts = _common(d)
        dims = tuple(dimension_from_druid(x) for x in d.get("dimensions", ()))
        ls = None
        if d.get("limitSpec"):
            spec = d["limitSpec"]
            ls = Q.LimitSpec(
                spec.get("limit"),
                tuple(
                    Q.OrderByColumnSpec(
                        c["dimension"] if isinstance(c, dict) else c,
                        c.get("direction", "ascending") if isinstance(c, dict) else "ascending",
                    )
                    for c in spec.get("columns", ())
                ),
                spec.get("offset", 0),
            )
        subtotals = ()
        if d.get("subtotalsSpec"):
            # name lists -> dimension-index tuples (the model's form)
            by_name = {spec.name: i for i, spec in enumerate(dims)}
            try:
                subtotals = tuple(
                    tuple(by_name[n] for n in names)
                    for names in d["subtotalsSpec"]
                )
            except KeyError as err:
                raise WireError(
                    f"subtotalsSpec names unknown dimension {err}"
                )
        return Q.GroupByQuery(
            datasource=ds,
            dimensions=dims,
            aggregations=aggs,
            post_aggregations=posts,
            filter=filt,
            having=(
                having_from_druid(d["having"]) if d.get("having") else None
            ),
            limit_spec=ls,
            intervals=ivs,
            granularity=granularity_from_druid(d.get("granularity", "all")),
            virtual_columns=vcols,
            subtotals=subtotals,
        )
    if qt == "topN":
        filt, ivs, vcols, aggs, posts = _common(d)
        dim = dimension_from_druid(d["dimension"])
        metric = d["metric"]
        descending = True
        if isinstance(metric, dict):
            t = metric.get("type")
            if t == "inverted":
                descending = False
                metric = metric.get("metric")
                if isinstance(metric, dict):
                    # Druid encodes descending dimension order as inverted-
                    # wrapped lexicographic
                    if metric.get("type") not in ("dimension", "lexicographic"):
                        raise WireError(
                            "unsupported inverted topN metric "
                            f"{metric.get('type')!r}"
                        )
                    ordering = metric.get("ordering", "lexicographic")
                    if ordering != "lexicographic":
                        raise WireError(
                            f"unsupported topN dimension ordering {ordering!r}"
                        )
                    descending = True
                    metric = dim.name
            elif t in ("dimension", "lexicographic"):
                # dimension-ordered topN: rank ASCENDING by the dimension's
                # own value (Druid expresses descending as inverted-wrapped
                # lexicographic, handled above).  alphaNumeric/numeric
                # orderings rank c2 before c10; a lexicographic sort would
                # silently return the wrong top-K, so they are rejected,
                # not coerced
                ordering = metric.get("ordering", "lexicographic")
                if ordering != "lexicographic":
                    raise WireError(
                        f"unsupported topN dimension ordering {ordering!r}"
                    )
                descending = False
                metric = dim.name
            else:
                raise WireError(f"unsupported topN metric spec {t!r}")
        return Q.TopNQuery(
            datasource=ds,
            dimension=dim,
            metric=metric,
            threshold=d["threshold"],
            aggregations=aggs,
            post_aggregations=posts,
            filter=filt,
            intervals=ivs,
            granularity=granularity_from_druid(d.get("granularity", "all")),
            virtual_columns=vcols,
            descending=descending,
        )
    if qt == "timeseries":
        filt, ivs, vcols, aggs, posts = _common(d)
        return Q.TimeseriesQuery(
            datasource=ds,
            granularity=granularity_from_druid(d.get("granularity", "all")),
            aggregations=aggs,
            post_aggregations=posts,
            filter=filt,
            intervals=ivs,
            virtual_columns=vcols,
            descending=d.get("descending", False),
            skip_empty_buckets=bool(
                (d.get("context") or {}).get("skipEmptyBuckets", False)
            ),
            output_name=(d.get("context") or {}).get(
                "outputName", "timestamp"
            ),
        )
    if qt == "scan":
        filt, ivs, vcols, _, _ = _common(d)
        for o in d.get("orderBy") or ():
            if "columnName" not in o:
                raise WireError("scan orderBy entry missing columnName")
        order_by = tuple(
            Q.OrderByColumnSpec(
                o["columnName"], o.get("order", "ascending")
            )
            for o in (d.get("orderBy") or ())
        )
        # legacy scan `order` field: time ordering
        if not order_by and d.get("order") in ("ascending", "descending"):
            order_by = (Q.OrderByColumnSpec("__time", d["order"]),)
        return Q.ScanQuery(
            datasource=ds,
            columns=tuple(d.get("columns", ())),
            filter=filt,
            intervals=ivs,
            limit=d.get("limit"),
            virtual_columns=vcols,
            order_by=order_by,
            offset=d.get("offset", 0),
            result_format=d.get("resultFormat", "list"),
        )
    if qt == "search":
        filt, ivs, _, _, _ = _common(d)
        qspec = d.get("query", {})
        return Q.SearchQuery(
            datasource=ds,
            dimensions=tuple(d.get("searchDimensions", ())),
            query=qspec.get("value", ""),
            filter=filt,
            intervals=ivs,
            limit=d.get("limit", 1000),
        )
    if qt == "timeBoundary":
        return Q.TimeBoundaryQuery(datasource=ds, bound=d.get("bound"))
    if qt == "dataSourceMetadata":
        return Q.DataSourceMetadataQuery(datasource=ds)
    if qt == "segmentMetadata":
        return Q.SegmentMetadataQuery(
            datasource=ds,
            intervals=intervals_from_druid(d.get("intervals", [])),
        )
    raise WireError(f"unsupported queryType {qt!r}")
