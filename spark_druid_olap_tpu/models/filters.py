"""Filter spec family — mirror of Druid's filter JSON sub-language.

Reference parity: the reference's `FilterSpec` case-class family
(selector / bound / in / regex / logical and-or-not / javascript), SURVEY.md §2
query-model row, expected `org/sparklinedata/druid/DruidQuery.scala` `[U]`.
Here each spec additionally knows how to *evaluate itself on device* —
`exec/filters.py` compiles a spec tree into a jittable boolean-mask function
over segment columns (the TPU analog of Druid evaluating the filter inside its
historical engine).  Where the reference escapes to JavaScript filters
(JS codegen layer, SURVEY.md L0), we escape to `ExpressionFilter`, compiled to
XLA element-wise ops by `ops/expressions.py`.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Sequence, Tuple


class Filter:
    """Base class.  `to_druid()` produces wire-compatible Druid JSON."""

    def to_druid(self) -> Dict[str, Any]:
        raise NotImplementedError

    # sugar for building trees
    def __and__(self, other: "Filter") -> "Filter":
        return And(tuple(f for f in (self, other)))

    def __or__(self, other: "Filter") -> "Filter":
        return Or(tuple(f for f in (self, other)))

    def __invert__(self) -> "Filter":
        return Not(self)


@dataclasses.dataclass(frozen=True)
class Selector(Filter):
    """dimension == value (Druid `selector`)."""

    dimension: str
    value: Optional[str]

    def to_druid(self):
        return {"type": "selector", "dimension": self.dimension, "value": self.value}


@dataclasses.dataclass(frozen=True)
class InFilter(Filter):
    """dimension IN (values) (Druid `in`).

    `null_in_values` records that the ORIGINAL list contained a literal
    NULL (stripped from `values`): a positive match set is unchanged, but
    under Kleene evaluation every NON-member row is then UNKNOWN rather
    than FALSE — which is what makes `NOT (x IN (..., NULL))` match
    nothing at any negation depth (SQL three-valued semantics)."""

    dimension: str
    values: Tuple[str, ...]
    null_in_values: bool = False

    def to_druid(self):
        vals = list(self.values)
        if self.null_in_values:
            vals = vals + [None]
        return {"type": "in", "dimension": self.dimension, "values": vals}


@dataclasses.dataclass(frozen=True)
class Bound(Filter):
    """Range filter (Druid `bound`).  `ordering` is "lexicographic" for string
    dimensions (sound because our dictionaries are sorted — codes preserve
    order) or "numeric" for metric/time columns."""

    dimension: str
    lower: Optional[str] = None
    upper: Optional[str] = None
    lower_strict: bool = False
    upper_strict: bool = False
    ordering: str = "lexicographic"

    def to_druid(self):
        d: Dict[str, Any] = {"type": "bound", "dimension": self.dimension}
        if self.lower is not None:
            d["lower"] = self.lower
            d["lowerStrict"] = self.lower_strict
        if self.upper is not None:
            d["upper"] = self.upper
            d["upperStrict"] = self.upper_strict
        d["ordering"] = self.ordering
        return d


@dataclasses.dataclass(frozen=True)
class Regex(Filter):
    """Druid `regex` filter.  Evaluated host-side against the dictionary (the
    dictionary is small; match once per dict entry, then it's an `in` filter on
    codes — strictly better than Druid's per-row regex)."""

    dimension: str
    pattern: str

    def to_druid(self):
        return {"type": "regex", "dimension": self.dimension, "pattern": self.pattern}


@dataclasses.dataclass(frozen=True)
class LikeFilter(Filter):
    """SQL LIKE — compiled to regex on the dictionary like `Regex`."""

    dimension: str
    pattern: str  # SQL pattern with % and _

    def to_druid(self):
        return {"type": "like", "dimension": self.dimension, "pattern": self.pattern}


@dataclasses.dataclass(frozen=True)
class And(Filter):
    fields: Tuple[Filter, ...]

    def to_druid(self):
        return {"type": "and", "fields": [f.to_druid() for f in self.fields]}


@dataclasses.dataclass(frozen=True)
class Or(Filter):
    fields: Tuple[Filter, ...]

    def to_druid(self):
        return {"type": "or", "fields": [f.to_druid() for f in self.fields]}


@dataclasses.dataclass(frozen=True)
class Not(Filter):
    field: Filter

    def to_druid(self):
        return {"type": "not", "field": self.field.to_druid()}


@dataclasses.dataclass(frozen=True)
class ExpressionFilter(Filter):
    """Residual scalar predicate over columns, compiled to XLA element-wise ops
    by `ops/expressions.py` — the TPU-native analog of the reference's
    JavaScript filter escape hatch (SURVEY.md L0 jscodegen `[U]`): instead of
    emitting JS source for Druid's Rhino interpreter, we emit a jittable
    function."""

    expression: Any  # plan.expr.Expr

    def to_druid(self):
        return {"type": "expression", "expression": str(self.expression)}


@dataclasses.dataclass(frozen=True)
class IntervalFilter(Filter):
    """Half-open [start_ms, end_ms) intervals over the time column.  The
    reference turns time-column predicates into the *query interval* rather
    than a filter (ProjectFilterTransform, SURVEY.md §2 `[U]`); we keep both
    paths — interval narrowing prunes whole segments, and this filter handles
    row-level residue."""

    dimension: str  # usually "__time"
    intervals: Tuple[Tuple[int, int], ...]

    def to_druid(self):
        def fmt(iv):
            return f"{_ms_to_iso(iv[0])}/{_ms_to_iso(iv[1])}"

        return {
            "type": "interval",
            "dimension": self.dimension,
            "intervals": [fmt(iv) for iv in self.intervals],
        }


_MIN_ISO_MS = -62135596800000  # 0001-01-01
_MAX_ISO_MS = 253402300799999  # 9999-12-31


def _ms_to_iso(ms: int) -> str:
    """Integer-exact ISO-8601: float seconds lose the last millisecond near
    the range ends, and strftime %Y does not zero-pad years < 1000."""
    import datetime

    ms = max(_MIN_ISO_MS, min(int(ms), _MAX_ISO_MS))  # clamp open-bound sentinels
    sec, frac = divmod(ms, 1000)  # Python floor-div: exact for negatives too
    dt = datetime.datetime.fromtimestamp(sec, tz=datetime.timezone.utc)
    return (
        f"{dt.year:04d}-{dt.month:02d}-{dt.day:02d}"
        f"T{dt.hour:02d}:{dt.minute:02d}:{dt.second:02d}.{frac:03d}Z"
    )


def filter_from_druid(d: Dict[str, Any]) -> Filter:
    """Parse Druid filter JSON back into the spec tree (wire-compat round trip)."""
    t = d["type"]
    if t == "selector":
        return Selector(d["dimension"], d.get("value"))
    if t == "in":
        vals = d["values"]
        return InFilter(
            d["dimension"],
            tuple(v for v in vals if v is not None),
            null_in_values=any(v is None for v in vals),
        )
    if t == "bound":
        return Bound(
            d["dimension"],
            d.get("lower"),
            d.get("upper"),
            d.get("lowerStrict", False),
            d.get("upperStrict", False),
            d.get("ordering", "lexicographic"),
        )
    if t == "regex":
        return Regex(d["dimension"], d["pattern"])
    if t == "like":
        return LikeFilter(d["dimension"], d["pattern"])
    if t == "and":
        return And(tuple(filter_from_druid(f) for f in d["fields"]))
    if t == "or":
        return Or(tuple(filter_from_druid(f) for f in d["fields"]))
    if t == "not":
        return Not(filter_from_druid(d["field"]))
    if t == "search":
        # contains / insensitive_contains map onto the Regex filter (same
        # O(dictionary) evaluation; re.escape keeps %/_/metacharacters
        # literal, which the LIKE translator cannot express)
        import re as _re

        q = d.get("query", {})
        qt = q.get("type")
        value = q.get("value", "")
        cs = q.get("case_sensitive", q.get("caseSensitive", True))
        insensitive = qt in (
            "insensitiveContains", "insensitive_contains"
        ) or (qt == "contains" and not cs)
        if qt not in ("contains", "insensitiveContains",
                      "insensitive_contains"):
            raise ValueError(f"unsupported search query type {qt!r}")
        pat = ("(?i)" if insensitive else "") + _re.escape(value)
        return Regex(d["dimension"], pat)
    if t == "interval":
        from .wire import intervals_from_druid

        return IntervalFilter(
            d.get("dimension", "__time"),
            intervals_from_druid(d.get("intervals", [])),
        )
    if t == "expression":
        from .wire import _expr

        return ExpressionFilter(_expr(d["expression"]))
    if t == "columnComparison":
        from ..plan import expr as E

        dims = d.get("dimensions", [])
        if len(dims) != 2 or not all(isinstance(x, str) for x in dims):
            raise ValueError(
                "columnComparison requires exactly two plain dimensions"
            )
        return ExpressionFilter(
            E.Comparison("==", E.Col(dims[0]), E.Col(dims[1]))
        )
    raise ValueError(f"unsupported filter type {t!r}")
