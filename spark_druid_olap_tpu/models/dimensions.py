"""Dimension specs + extraction functions — Druid JSON mirror.

Reference parity: `DimensionSpec` (default, extraction) +
`ExtractionFunctionSpec` (timeFormat, javascript, regex, substring…) —
SURVEY.md §2 query-model row `[U]`.  Extraction functions are how the
reference pushes `GROUP BY f(dim)` down to Druid; on TPU an extraction is a
host-side *dictionary rewrite*: we apply the function to the (small) dictionary
once, producing a code→newcode remap table that the kernel applies per row with
one int32 gather — never per-row string work on device.

Time-granularity bucketing (`GROUP BY date_trunc(...)`) is the exception: it
is arithmetic on the int64 time column, done on device (ops/timeseries.py).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional


class ExtractionFn:
    def to_druid(self) -> Dict[str, Any]:
        raise NotImplementedError

    def apply_to_dict(self, values):
        """Map each dictionary value -> extracted string (host-side)."""
        raise NotImplementedError


@dataclasses.dataclass(frozen=True)
class RegexExtraction(ExtractionFn):
    pattern: str
    index: int = 1
    replace_missing: Optional[str] = None

    def to_druid(self):
        return {"type": "regex", "expr": self.pattern}

    def apply_to_dict(self, values):
        import re

        rx = re.compile(self.pattern)
        out = []
        for v in values:
            m = rx.search(v)
            out.append(m.group(self.index) if m else (self.replace_missing or v))
        return out


@dataclasses.dataclass(frozen=True)
class SubstringExtraction(ExtractionFn):
    index: int
    length: Optional[int] = None

    def to_druid(self):
        d = {"type": "substring", "index": self.index}
        if self.length is not None:
            d["length"] = self.length
        return d

    def apply_to_dict(self, values):
        if self.length is None:
            return [v[self.index:] for v in values]
        return [v[self.index : self.index + self.length] for v in values]


@dataclasses.dataclass(frozen=True)
class CaseExtraction(ExtractionFn):
    """UPPER/LOWER over a dimension — a pure dictionary rewrite."""

    upper: bool

    def to_druid(self):
        return {"type": "upper" if self.upper else "lower"}

    def apply_to_dict(self, values):
        return [v.upper() if self.upper else v.lower() for v in values]


@dataclasses.dataclass(frozen=True)
class CascadeExtraction(ExtractionFn):
    """Druid `cascade` — composed extractions applied left-to-right
    (innermost string function first)."""

    fns: tuple  # Tuple[ExtractionFn, ...]

    def to_druid(self):
        return {
            "type": "cascade",
            "extractionFns": [f.to_druid() for f in self.fns],
        }

    def apply_to_dict(self, values):
        for f in self.fns:
            values = f.apply_to_dict(values)
        return values


def _js_str(s: str) -> str:
    """Escape a Python string into a single-quoted JS string literal body:
    backslash FIRST, then quote and control characters — a lone backslash
    must not escape the closing quote of the generated function."""
    return (
        s.replace("\\", "\\\\")
        .replace("'", "\\'")
        .replace("\n", "\\n")
        .replace("\r", "\\r")
        .replace("\t", "\\t")
    )


@dataclasses.dataclass(frozen=True)
class StrFuncExtraction(ExtractionFn):
    """TRIM/LTRIM/RTRIM/REPLACE over a dimension — pure dictionary
    rewrites with no native Druid extraction type.  Serialized as Druid's
    `javascript` extraction (the reference shipped exactly such functions
    to Druid through its JSCodeGenerator — SURVEY.md §2 JS-codegen row)."""

    fn: str
    args: tuple = ()

    def to_druid(self):
        if self.fn == "replace":
            f, t = _js_str(str(self.args[0])), _js_str(str(self.args[1]))
            body = f"x.split('{f}').join('{t}')"
        else:
            # SQL TRIM strips spaces only; JS trim() strips all whitespace
            pat = {
                "trim": "/^ +| +$/g", "ltrim": "/^ +/", "rtrim": "/ +$/"
            }[self.fn]
            body = f"x.replace({pat},'')"
        return {
            "type": "javascript",
            "function": f"function(x){{return x==null?null:{body}}}",
        }

    def apply_to_dict(self, values):
        from ..plan.expr import apply_strfunc

        return [apply_strfunc(self.fn, self.args, v) for v in values]


@dataclasses.dataclass(frozen=True)
class TimeFormatExtraction(ExtractionFn):
    """Druid `timeFormat` — used when grouping the time column by a calendar
    granularity that isn't a fixed millisecond period (month/quarter/year)."""

    format: str  # strftime-style
    granularity: Optional[str] = None

    def to_druid(self):
        d = {"type": "timeFormat", "format": self.format}
        if self.granularity:
            d["granularity"] = self.granularity
        return d

    def apply_to_dict(self, values):  # applied to time bucket starts, host-side
        import datetime

        return [
            datetime.datetime.fromtimestamp(int(v) / 1000.0, tz=datetime.timezone.utc)
            .strftime(self.format)
            for v in values
        ]


@dataclasses.dataclass(frozen=True)
class TimeFieldExtraction(ExtractionFn):
    """SQL EXTRACT(field FROM ts) as a dimension (VERDICT r1 missing #7).

    Dictionary-backed: over a numeric-dict date dimension the field is
    computed per DICTIONARY VALUE (host-side, O(cardinality)); over the time
    column the engine buckets at the field's granularity and remaps bucket
    starts — either way the kernel sees one int32 gather.  Values decode as
    ints (SQL EXTRACT returns numbers), unlike the string-valued Druid
    timeFormat this wire-serializes to."""

    field: str  # year | month | day | hour | minute | second

    _FORMATS = {
        "year": "%Y", "month": "%m", "day": "%d",
        "hour": "%H", "minute": "%M", "second": "%S",
    }

    def to_druid(self):
        return {"type": "timeFormat", "format": self._FORMATS[self.field]}

    @property
    def granularity(self) -> str:
        """Bucket granularity that makes the field constant per bucket."""
        return self.field

    def apply_to_dict(self, values):
        import datetime

        out = []
        for v in values:
            ms = int(v)
            dt = datetime.datetime.fromtimestamp(
                ms / 1000.0, tz=datetime.timezone.utc
            )
            out.append(
                {
                    "year": dt.year,
                    "month": dt.month,
                    "day": dt.day,
                    "hour": dt.hour,
                    "minute": dt.minute,
                    "second": dt.second,
                }[self.field]
            )
        return out


@dataclasses.dataclass(frozen=True)
class DimensionSpec:
    """Output dimension of a GroupBy/TopN: a physical dimension (or __time),
    an optional extraction fn, and the output name."""

    dimension: str
    output_name: Optional[str] = None
    extraction: Optional[ExtractionFn] = None
    # time-dimension bucketing (when dimension == "__time")
    granularity: Optional[str] = None  # e.g. "hour", "day", "month", "P3M"

    @property
    def name(self) -> str:
        return self.output_name or self.dimension

    def to_druid(self):
        if self.extraction is None:
            return {
                "type": "default",
                "dimension": self.dimension,
                "outputName": self.name,
            }
        return {
            "type": "extraction",
            "dimension": self.dimension,
            "outputName": self.name,
            "extractionFn": self.extraction.to_druid(),
        }


@dataclasses.dataclass(frozen=True)
class LookupExtraction(ExtractionFn):
    """Druid `lookup` extraction: map dimension values through a registered
    key->value table at query time (`LOOKUP(dim, 'name')` in SQL).  The map
    travels as a tuple of pairs so the spec stays frozen/hashable; semantics
    follow Druid's map lookup: unmapped values become `replace_missing`
    (None -> null group, the Druid default) unless `retain_missing`, which
    passes them through unchanged."""

    name: str
    mapping: Tuple[Tuple[str, str], ...]
    retain_missing: bool = False
    replace_missing: Optional[str] = None

    @classmethod
    def from_mapping(
        cls,
        name: str,
        mapping,
        retain_missing: bool = False,
        replace_missing: Optional[str] = None,
    ) -> "LookupExtraction":
        """Canonical constructor from a dict-like mapping: the sorted-pairs
        normalization lives HERE so every construction path (SQL planning,
        wire decode) produces specs that hash/compare equal for the same
        logical lookup."""
        return cls(
            name,
            tuple(sorted((str(k), str(v)) for k, v in dict(mapping).items())),
            retain_missing=retain_missing,
            replace_missing=replace_missing,
        )

    def to_druid(self):
        d: Dict[str, Any] = {
            "type": "lookup",
            # `name` is not part of Druid's inline-map wire form, but losing
            # it on a round-trip would make the decoded spec hash differently
            # from the locally planned one (cache miss); our decoder reads it
            # back and Druid-side consumers ignore unknown fields
            "name": self.name,
            "lookup": {"type": "map", "map": dict(self.mapping)},
        }
        if self.retain_missing:
            d["retainMissingValue"] = True
        elif self.replace_missing is not None:
            d["replaceMissingValueWith"] = self.replace_missing
        return d

    def apply_to_dict(self, values):
        m = dict(self.mapping)
        if self.retain_missing:
            return [m.get(v, v) for v in values]
        # Druid: without retain/replace, unmapped values become null (None
        # here folds into the dimension's null group)
        return [m.get(v, self.replace_missing) for v in values]


@dataclasses.dataclass(frozen=True)
class FormatExtraction(ExtractionFn):
    """CONCAT with one dimension operand: literal prefix/suffix around the
    value — Druid's `stringFormat` extraction; a pure dictionary rewrite."""

    prefix: str = ""
    suffix: str = ""

    def to_druid(self):
        # literal '%' must be escaped for Java's String.format
        pre = self.prefix.replace("%", "%%")
        suf = self.suffix.replace("%", "%%")
        return {"type": "stringFormat", "format": f"{pre}%s{suf}"}

    def apply_to_dict(self, values):
        from ..plan.expr import apply_strfunc

        return [
            apply_strfunc("concat", (self.prefix, self.suffix), v)
            for v in values
        ]


@dataclasses.dataclass(frozen=True)
class StrlenExtraction(ExtractionFn):
    """LENGTH over a dimension (Druid `strlen`), as integer lengths."""

    def to_druid(self):
        return {"type": "strlen"}

    def apply_to_dict(self, values):
        from ..plan.expr import apply_strfunc

        return [apply_strfunc("length", (), v) for v in values]
