"""Cluster tier: broker + N historicals over a shared snapshot store
(ISSUE 16).

Topology: one BROKER (a normal `TPUOlapContext` with a `ClusterClient`
attached — it owns the write path and answers anything not covered by
the scatter surface locally) and N HISTORICALS (read-only
`HistoricalNode` processes mmap-booting the same `storage_dir`, each
serving partial-state RPCs for its assigned replica subset).

  * `assignment` — rendezvous-hashed segment -> replica-chain maps,
    epoch-bumped on membership change, manifest-persisted.
  * `wire` — the dense groupby partial-state codec (base64 + dtype +
    shape, strictly validated on decode).
  * `historical` — the serving replica (in-process for tests,
    `python -m spark_druid_olap_tpu.cluster.historical` for real
    processes).
  * `broker` — scatter/retry/hedge/breaker + merge-tree gather with
    coverage accounting.
"""

from .assignment import (
    Assignment,
    build_assignment,
    load_assignment,
    rebalance,
    replicas_for,
    save_assignment,
)
from .broker import ClusterClient, ReplicaSetLost
from .historical import HistoricalNode
from .wire import WireDecodeError, decode_state, encode_state

__all__ = [
    "Assignment",
    "ClusterClient",
    "HistoricalNode",
    "ReplicaSetLost",
    "WireDecodeError",
    "build_assignment",
    "decode_state",
    "encode_state",
    "load_assignment",
    "rebalance",
    "replicas_for",
    "save_assignment",
]
