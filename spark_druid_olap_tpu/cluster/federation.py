"""Federated observability scrape (cluster/, ISSUE 19).

A cluster hides every historical's registry and workload profile behind
its own port; this module gives the BROKER one merged surface:

* `GET /status/metrics?cluster=1` — the broker scrapes each
  historical's `/status/metrics`, injects a `node` label into every
  sample line (node ids ride the `bounded_label` cardinality guard, so
  membership churn cannot explode the merged exposition), merges the
  family headers, and appends its own registry under `node="broker"`.
* `GET /status/profile?cluster=1` — same shape over the JSON profile
  docs: `{broker, nodes: {id: doc}, stale: [...]}`.

Staleness model: an unreachable historical NEVER fails the scrape — it
is simply absent from the merged series and stamped on the
`sdol_cluster_scrape_stale` gauge (1 = last scrape failed), so a
dashboard distinguishes "node reports zero" from "node unreachable".
The federation fan-out passes `resilience.checkpoint("cluster.federate")`
per node (trace-propagation/GL2703): deadlines bound a scrape fanned
over a large membership, and the chaos matrix can arm the site.  The
broker hands `scrape_nodes` its scatter pool so the per-node fetches
run concurrently (one slowest-node round trip, not the serial sum);
node ids are sorted before submission and folded in that order, so the
merged exposition is byte-identical between the serial and parallel
paths.
"""

from __future__ import annotations

import json
import urllib.request
from collections import OrderedDict
from typing import Dict, List, Optional, Set, Tuple

from ..obs import bounded_label
from ..resilience import checkpoint
from ..utils.log import get_logger

log = get_logger("cluster.federation")

__all__ = [
    "STALE_METRIC",
    "scrape_nodes",
    "scrape_nodes_json",
    "merge_prometheus",
]

STALE_METRIC = "sdol_cluster_scrape_stale"

# one scraped body is bounded so a misbehaving node cannot balloon the
# merged exposition past what a scrape client will accept
_SCRAPE_MAX_BYTES = 4 << 20


def _fetch_node(url: str, path: str, timeout_s: float) -> Optional[str]:
    """One node's scrape body, or None (the staleness stamp) on any
    fetch failure.  The federation checkpoint (GL2703) fires OUTSIDE
    the fault-ok try: a deadline/chaos injection at the site must
    propagate to the caller (via `Future.result()` on the parallel
    path), never be mistaken for an unreachable node."""
    checkpoint("cluster.federate")
    try:
        with urllib.request.urlopen(
            url + path, timeout=timeout_s
        ) as resp:
            return resp.read(_SCRAPE_MAX_BYTES).decode(
                "utf-8", "replace"
            )
    except Exception as e:  # fault-ok: stale stamp, never a 500
        log.warning("scrape of %s%s failed: %s", url, path, e)
        return None


def scrape_nodes(
    nodes: Dict[str, str], path: str, timeout_s: float, pool=None,
) -> Dict[str, Optional[str]]:
    """GET `path` from every node; None marks an unreachable node (the
    staleness stamp), never an exception — the merged scrape must serve
    through any subset of the membership being down.

    With `pool` (the broker passes its scatter executor) the fetches
    fan out concurrently, so a scrape of N nodes costs one slowest-node
    round trip instead of the serial sum — the per-node `timeout_s`
    still bounds each fetch individually.  Node ids are sorted BEFORE
    submission and the result dict is built in that same order, so the
    downstream first-writer-wins merge fold sees an identical sequence
    on the serial and parallel paths (fold-determinism/GL24xx)."""
    items = sorted(nodes.items())
    if pool is None:
        return OrderedDict(
            (nid, _fetch_node(url, path, timeout_s))
            for nid, url in items
        )
    futs = [
        (nid, pool.submit(_fetch_node, url, path, timeout_s))
        for nid, url in items
    ]
    return OrderedDict((nid, fut.result()) for nid, fut in futs)


def scrape_nodes_json(
    nodes: Dict[str, str], path: str, timeout_s: float, pool=None,
) -> Dict[str, Optional[dict]]:
    """`scrape_nodes` + JSON decode; an unparseable body is stale too."""
    docs: Dict[str, Optional[dict]] = {}
    for nid, text in scrape_nodes(nodes, path, timeout_s, pool).items():
        if text is None:
            docs[nid] = None
            continue
        try:
            doc = json.loads(text)
            docs[nid] = doc if isinstance(doc, dict) else None
        except ValueError:
            docs[nid] = None
    return docs


def _inject_node_label(line: str, node: str) -> str:
    """Rewrite one exposition sample line to carry node="...": inserted
    first in an existing label set, or as the whole set when bare."""
    brace = line.find("{")
    space = line.find(" ")
    if brace != -1 and (space == -1 or brace < space):
        return f'{line[:brace + 1]}node="{node}",{line[brace + 1:]}'
    if space == -1:
        return line
    return f'{line[:space]}{{node="{node}"}}{line[space:]}'


def merge_prometheus(sections: Dict[str, Optional[str]]) -> str:
    """Merge per-node exposition texts into ONE text 0.0.4 document:
    family headers deduped (first writer wins the help text), every
    sample line node-labeled, exemplar/other comments dropped (they
    cannot be node-attributed), and the `sdol_cluster_scrape_stale`
    gauge appended over the full membership."""
    headers: "OrderedDict[str, List[str]]" = OrderedDict()
    samples: Dict[str, List[str]] = {}
    seen_headers: Set[Tuple[str, str]] = set()
    staleness: List[Tuple[str, int]] = []
    for node in sorted(sections):
        text = sections[node]
        nl = bounded_label("cluster_node", node or "unknown")
        staleness.append((nl, 0 if text is not None else 1))
        if text is None:
            continue
        fam = ""
        for line in text.splitlines():
            if line.startswith("# HELP") or line.startswith("# TYPE"):
                parts = line.split(None, 3)
                if len(parts) < 3:
                    continue
                kind, name = parts[1], parts[2]
                if kind == "TYPE":
                    fam = name
                if (name, kind) not in seen_headers:
                    seen_headers.add((name, kind))
                    headers.setdefault(name, []).append(line)
            elif not line or line.startswith("#"):
                continue
            else:
                key = fam or line.split("{", 1)[0].split(" ", 1)[0]
                headers.setdefault(key, [])
                samples.setdefault(key, []).append(
                    _inject_node_label(line, nl)
                )
    lines: List[str] = []
    for fam, hdr in headers.items():
        lines.extend(hdr)
        lines.extend(samples.get(fam, ()))
    lines.append(
        f"# HELP {STALE_METRIC} last federated scrape of this node "
        "failed (1 = metrics below exclude it)"
    )
    lines.append(f"# TYPE {STALE_METRIC} gauge")
    for nl, stale in staleness:
        lines.append(f'{STALE_METRIC}{{node="{nl}"}} {stale}')
    return "\n".join(lines) + "\n"
