"""Broker: scatter covered queries to historicals, gather through the
merge tree (cluster/, ISSUE 16 tentpole).

`ClusterClient` rides a normal `TPUOlapContext` (attach() sets
`ctx.cluster`, and the api/server query paths divert covered queries
here).  The execution contract:

* **Assignment** — rendezvous-hashed segment -> replica-chain map
  (assignment.py) with a replication factor, epoch-bumped and
  manifest-persisted on every membership change.  Broker-local delta
  segments (appended after the map was built) are RESIDUAL: executed
  in-process and ⊕'d into the gather, so fresh appends never wait for
  a rebalance.
* **Scatter** — one RPC per replica group over the existing wire
  surface (`POST /druid/v2/cluster/partial`), on a thread pool, with a
  per-replica timeout, failover across the chain, optional hedging
  past `cluster_hedge_ms`, and a per-historical `CircuitBreaker`
  (generalizing `ResilienceState.breakers`) — an open node is skipped,
  not waited on.
* **Gather** — replica states ⊕ through the SAME
  `merge_groupby_states` algebra the mesh slices use, guarded by the
  assignment-epoch version check (GL2301): a state computed against a
  different catalog version (or a reshaped dictionary domain) is a
  replica failure, never a wrong merge.
* **Degradation ladder** — a failed replica fails over to the next in
  its chain; a LOST replica group (every replica down) triggers the
  partial collector so the answer ships coverage-stamped through the
  existing partial machinery instead of erroring; metadata/health
  queries never route here at all, so they serve through any breaker
  state.

Tracing (ISSUE 19): the scatter span in the query thread hands its
(trace, span) pair EXPLICITLY to the pool workers — `span_in` records
into the handle under the trace's own lock, so every replica attempt
opens a `cluster_rpc` span (node/outcome/hedge attrs) even though the
contextvar trace is invisible on a fresh pool thread.  Each RPC
carries `X-Druid-Query-Id` + `X-Sdol-Parent-Span` headers; the
historical traces under the same identity and returns its rendered
subtree, which grafts under the attempt's span — `/druid/v2/trace/{id}`
serves ONE tree spanning the cluster, and obs/prof.py folds the
grafted device/transfer/host buckets into per-historical attribution.
A torn/oversized trace payload degrades to an `untraced` stub, never a
failed replica.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.request
from concurrent.futures import ThreadPoolExecutor, as_completed
from typing import Dict, List, Optional, Tuple

from ..catalog.segment import DeltaSegment
from ..exec.metrics import QueryMetrics
from ..models import query as Q
from ..obs import (
    SPAN_CLUSTER_MERGE,
    SPAN_CLUSTER_RPC,
    SPAN_GATHER,
    SPAN_SCATTER,
    current_query_id,
    current_trace,
    record_cluster_health,
    record_cluster_rpc,
    record_query_metrics,
    span,
    span_event,
    span_in,
)
from ..obs.otlp import rpc_span_id
from ..resilience import (
    CircuitBreaker,
    checkpoint,
    classify_error,
    current_partial,
    injector,
)
from ..utils.log import get_logger
from .assignment import (
    Assignment,
    build_assignment,
    load_assignment,
    save_assignment,
)
from .wire import WireDecodeError, decode_state, decode_trace, trace_headers

log = get_logger("cluster.broker")

__all__ = ["ClusterClient", "ReplicaSetLost"]


class ReplicaSetLost(RuntimeError):
    """Every replica of one scatter group failed — the group's segments
    are lost from this answer (coverage-stamped, never a 500)."""


class ClusterClient:
    """The broker half: membership, assignment, scatter/gather."""

    def __init__(self, ctx, nodes: Optional[Dict[str, str]] = None,
                 replication: Optional[int] = None):
        cfg = ctx.config
        self.ctx = ctx
        self.replication = int(replication or cfg.cluster_replication)
        self.rpc_timeout_s = float(cfg.cluster_rpc_timeout_ms) / 1e3
        self.retries = max(0, int(cfg.cluster_rpc_retries))
        self.hedge_s = float(cfg.cluster_hedge_ms) / 1e3
        self.scrape_timeout_s = float(cfg.cluster_scrape_timeout_ms) / 1e3
        self._lock = threading.Lock()
        # node_id -> base url ("http://host:port")
        self._nodes: Dict[str, str] = dict(nodes or {})
        self._breakers: Dict[str, CircuitBreaker] = {}
        self._last_ok: Dict[str, float] = {}
        self.assignment: Optional[Assignment] = None
        self.last_metrics: Optional[QueryMetrics] = None
        # resume the epoch sequence from a persisted manifest so a
        # broker restart continues, never rewinds, the epoch clock
        self._epoch_floor = 0
        if getattr(ctx, "storage", None) is not None:
            prev = load_assignment(ctx.storage.root)
            if prev is not None:
                self._epoch_floor = prev.epoch
        self._pool = ThreadPoolExecutor(
            max_workers=8, thread_name_prefix="sdol-scatter"
        )
        if self._nodes:
            self.rebalance()

    # -- membership / assignment --------------------------------------------

    def attach(self) -> "ClusterClient":
        self.ctx.cluster = self
        return self

    def detach(self) -> None:
        if self.ctx.cluster is self:
            self.ctx.cluster = None

    def close(self) -> None:
        self.detach()
        self._pool.shutdown(wait=False)

    def nodes(self) -> Dict[str, str]:
        with self._lock:
            return dict(self._nodes)

    def add_node(self, node_id: str, url: str) -> Assignment:
        with self._lock:
            self._nodes[node_id] = url.rstrip("/")
        return self.rebalance()

    def remove_node(self, node_id: str) -> Assignment:
        with self._lock:
            self._nodes.pop(node_id, None)
        return self.rebalance()

    def set_node_url(self, node_id: str, url: str) -> None:
        """Same member, new address (a restarted node on an ephemeral
        port): no epoch bump — the assignment keys on node ids, so the
        map is unchanged."""
        with self._lock:
            if node_id not in self._nodes:
                raise KeyError(f"unknown node {node_id!r}")
            self._nodes[node_id] = url.rstrip("/")

    def _assignable(self) -> Tuple[Dict[str, List[str]], Dict[str, int]]:
        """{datasource: [segment_id...]} of PERSISTED segments (the ones
        every historical's snapshot boot can serve) + the catalog
        versions the map is computed at.  Delta segments stay residual:
        only this process has them until a flush."""
        seg_ids: Dict[str, List[str]] = {}
        versions: Dict[str, int] = {}
        storage = getattr(self.ctx, "storage", None)
        for name in sorted(self.ctx.catalog.tables()):
            ds = self.ctx.catalog.get(name)
            if ds is None:
                continue
            # pin the SNAPSHOT version (stable across processes booting
            # the same store generation), not the process-local live
            # version — see DurableStorage.snapshot_version
            snap = (
                storage.snapshot_version(name)
                if storage is not None else None
            )
            versions[name] = int(ds.version) if snap is None else snap
            seg_ids[name] = [
                s.segment_id for s in ds.segments
                if not isinstance(s, DeltaSegment)
            ]
        return seg_ids, versions

    def rebalance(self) -> Assignment:
        """Recompute the map over the CURRENT membership and catalog at
        the next epoch; deterministic (rendezvous), minimal-movement,
        manifest-persisted.  Called on every membership change and on
        node rejoin after a restart."""
        with self._lock:
            seg_ids, versions = self._assignable()
            epoch = max(
                self._epoch_floor,
                self.assignment.epoch if self.assignment else 0,
            ) + 1
            asg = build_assignment(
                seg_ids, self._nodes, self.replication,
                epoch=epoch, versions=versions,
            )
            self.assignment = asg
            for nid in self._nodes:
                if nid not in self._breakers:
                    cfg = self.ctx.config
                    self._breakers[nid] = CircuitBreaker(
                        failure_threshold=cfg.cluster_breaker_failures,
                        cooldown_ms=cfg.cluster_breaker_cooldown_ms,
                        backend=f"historical:{nid}",
                    )
            for nid in list(self._breakers):
                if nid not in self._nodes:
                    del self._breakers[nid]
            if getattr(self.ctx, "storage", None) is not None:
                save_assignment(self.ctx.storage.root, asg)
        log.info(
            "assignment epoch %d: %d nodes, %d segments, replication %d",
            asg.epoch, len(asg.nodes), len(asg.segment_map),
            asg.replication,
        )
        self._publish_health()
        return asg

    def _breaker(self, node_id: str) -> CircuitBreaker:
        with self._lock:
            br = self._breakers.get(node_id)
            if br is None:
                cfg = self.ctx.config
                br = self._breakers[node_id] = CircuitBreaker(
                    failure_threshold=cfg.cluster_breaker_failures,
                    cooldown_ms=cfg.cluster_breaker_cooldown_ms,
                    backend=f"historical:{node_id}",
                )
            return br

    # -- health ---------------------------------------------------------------

    def _live_nodes(self) -> List[str]:
        with self._lock:
            ids = list(self._nodes)
        return [n for n in ids if self._breaker(n).state != "open"]

    def state(self) -> dict:
        """The /status/health cluster section: per-historical liveness
        (breaker state + last successful contact), the assignment
        epoch, and the replication deficit.  Reads breaker state only
        through the public accessors — never the guarded internals
        (GL2303)."""
        asg = self.assignment
        live = self._live_nodes()
        under, lost = asg.deficit(live) if asg else (0, 0)
        with self._lock:
            nodes = {
                nid: {
                    "url": url,
                    "live": nid in live,
                    "breaker": self._breakers[nid].to_dict()
                    if nid in self._breakers else None,
                    "last_ok_ms_ago": (
                        round((time.monotonic() - self._last_ok[nid]) * 1e3)
                        if nid in self._last_ok else None
                    ),
                    "assigned_segments": (
                        len(asg.segments_for(nid)) if asg else 0
                    ),
                }
                for nid, url in sorted(self._nodes.items())
            }
        doc = {
            "nodes": nodes,
            "live": len(live),
            "epoch": asg.epoch if asg else 0,
            "replication": self.replication,
            "replication_deficit": under,
            "segments_lost": lost,
        }
        self._publish_health(live=len(live), under=under, lost=lost)
        return doc

    def _publish_health(self, live=None, under=None, lost=None) -> None:
        asg = self.assignment
        if live is None or under is None or lost is None:
            lv = self._live_nodes()
            live = len(lv)
            under, lost = asg.deficit(lv) if asg else (0, 0)
        record_cluster_health(
            live=live, total=len(self.nodes()),
            epoch=asg.epoch if asg else 0, deficit=under, lost=lost,
        )

    # -- federated observability (ISSUE 19) -----------------------------------

    def federated_metrics(self) -> str:
        """The `/status/metrics?cluster=1` body: every historical's
        exposition node-labeled and merged with the broker's own
        (`node="broker"`); unreachable nodes are absent + stamped on
        `sdol_cluster_scrape_stale`, never a failed scrape."""
        from ..obs import get_registry
        from .federation import merge_prometheus, scrape_nodes

        sections: Dict[str, Optional[str]] = dict(
            scrape_nodes(
                self.nodes(), "/status/metrics", self.scrape_timeout_s,
                pool=self._pool,
            )
        )
        sections["broker"] = get_registry().render_prometheus()
        return merge_prometheus(sections)

    def federated_profile(self, local_doc: Optional[dict] = None) -> dict:
        """The `/status/profile?cluster=1` document: the broker's own
        profile plus every historical's under its node id; unreachable
        nodes carry {"stale": true} and are listed in `stale`."""
        from .federation import scrape_nodes_json

        docs = scrape_nodes_json(
            self.nodes(), "/status/profile", self.scrape_timeout_s,
            pool=self._pool,
        )
        return {
            "cluster": True,
            "broker": local_doc or {},
            "nodes": {
                nid: (doc if doc is not None else {"stale": True})
                for nid, doc in docs.items()
            },
            "stale": sorted(
                nid for nid, doc in docs.items() if doc is None
            ),
        }

    # -- coverage -------------------------------------------------------------

    def covers(self, q, ds) -> bool:
        """Does the broker serve this query?  GroupBy-family with
        mergeable dense state (the engine's own fusable gate), no wire
        subtotals, and at least one historical to scatter to.  Anything
        else — metadata queries, sparse/adaptive-tier shapes, grouping
        sets — executes locally exactly as before."""
        if not self._nodes or self.assignment is None:
            return False
        if not isinstance(
            q, (Q.GroupByQuery, Q.TimeseriesQuery, Q.TopNQuery)
        ):
            return False
        if isinstance(q, Q.GroupByQuery) and q.subtotals:
            return False
        try:
            return bool(self.ctx.engine.fusable(q, ds))
        except Exception:  # fault-ok: an ungateable query stays local
            return False

    # -- scatter --------------------------------------------------------------

    def _rpc(self, url: str, payload: bytes,
             headers: Optional[Dict[str, str]] = None) -> dict:
        # trace-propagation headers (GL2701): built by wire.trace_headers
        # in the caller, merged under the content type here
        hdrs = {"Content-Type": "application/json"}
        hdrs.update(headers or {})
        req = urllib.request.Request(
            url + "/druid/v2/cluster/partial",
            data=payload,
            headers=hdrs,
            method="POST",
        )
        with urllib.request.urlopen(
            req, timeout=self.rpc_timeout_s
        ) as resp:
            raw = resp.read()
        # torn-response chaos site: partial mode truncates the body the
        # broker sees, exactly a connection dying mid-transfer — the
        # strict decode below must fail over, never merge garbage
        frac = injector().partial_fraction("cluster.torn_response")
        if frac is not None:
            raw = raw[: int(len(raw) * frac)]
        try:
            return json.loads(raw)
        except ValueError as e:
            raise WireDecodeError(f"torn response body: {e}") from e

    def _attempt(self, node: str, payload: bytes, expect_version: int,
                 attempts: list, trace=None, parent=None, qid: str = "",
                 hedge: bool = False) -> dict:
        """One replica attempt: breaker-gated RPC + strict decode +
        version guard, under its own `cluster_rpc` span on the
        EXPLICITLY-threaded trace handle (the contextvar trace is
        invisible on a pool thread — `span_in` records through the
        handle instead).  A successful reply's rendered subtree grafts
        under the span; a failed attempt leaves an error span.  Appends
        (node, ms, outcome) to `attempts` and raises on any failure."""
        seq = len(attempts)
        span_otlp = rpc_span_id(qid, node, seq)
        with span_in(
            trace, parent, SPAN_CLUSTER_RPC, node=node, attempt=seq,
            hedge=hedge, otlp_span_id=span_otlp,
        ) as s:
            br = self._breaker(node)
            if not br.allow():
                attempts.append((node, 0.0, "breaker_open"))
                record_cluster_rpc(node, "breaker_open")
                if s is not None:
                    s.attrs.update(outcome="breaker_open", error=True)
                raise ReplicaSetLost(f"breaker open for {node}")
            url = self.nodes().get(node)
            if url is None:
                attempts.append((node, 0.0, "removed"))
                if s is not None:
                    s.attrs.update(outcome="removed", error=True)
                raise ReplicaSetLost(f"node {node} left the membership")
            t0 = time.perf_counter()
            try:
                # per-RPC chaos site: error mode IS a timed-out/refused
                # connection; delay mode is a slow network path
                checkpoint("cluster.rpc")
                doc = self._rpc(
                    url, payload, headers=trace_headers(qid, span_otlp)
                )
                ver = int(doc.get("version", -1))
                if expect_version and ver != expect_version:
                    raise WireDecodeError(
                        f"version skew: replica at {ver}, assignment "
                        f"epoch expects {expect_version}"
                    )
                state = decode_state(doc.get("state"))
            except Exception as e:
                ms = (time.perf_counter() - t0) * 1e3
                br.record_failure()
                outcome = type(e).__name__
                attempts.append((node, ms, outcome))
                record_cluster_rpc(
                    node, classify_error(e), ms,
                    query_id=current_query_id() or qid, failover=True,
                )
                if s is not None:
                    s.attrs.update(
                        outcome=outcome, ms=round(ms, 3), error=True
                    )
                raise
            ms = (time.perf_counter() - t0) * 1e3
            br.record_success()
            with self._lock:
                self._last_ok[node] = time.monotonic()
            record_cluster_rpc(
                node, "ok", ms, query_id=current_query_id() or qid
            )
            segments = list(doc.get("segments") or ())
            if s is not None and trace is not None:
                s.attrs.update(
                    outcome="ok", ms=round(ms, 3), segments=len(segments)
                )
                # graft the historical's subtree (or its degraded
                # `untraced` stub — trace trouble never fails a replica
                # that computed a good state) under THIS attempt's span
                graft = decode_trace(doc.get("trace"), node)
                if graft.get("attrs", {}).get("untraced") and isinstance(
                    doc.get("receipt"), dict
                ):
                    # the separately-shipped receipt often survives a
                    # torn trace payload: keep per-node attribution
                    graft["receipt"] = doc["receipt"]
                trace.graft(s, graft)
            return {
                "node": node, "ms": ms, "version": ver, "state": state,
                "rows": int(doc.get("rows", 0)),
                "segments": segments,
                "receipt": doc.get("receipt"),
            }

    def _fetch_group(self, chain: Tuple[str, ...], payload: bytes,
                     expect_version: int, trace=None, parent=None,
                     qid: str = "") -> dict:
        """Fetch one replica group's partial state: walk the chain with
        failover (plus `cluster_rpc_retries` re-walks), hedging the
        primary past `cluster_hedge_ms`.  Runs on a pool thread; the
        caller threads (trace, scatter-span) through so every attempt
        records its own `cluster_rpc` span — the contextvar trace is
        deliberately invisible here, the explicit handle is the
        sanctioned path (obs.trace.span_in)."""
        attempts: list = []
        if self.hedge_s > 0 and len(chain) > 1:
            r = self._fetch_hedged(chain, payload, expect_version,
                                   attempts, trace=trace, parent=parent,
                                   qid=qid)
            if r is not None:
                r["attempts"] = attempts
                return r
            walk = list(chain[2:]) + list(chain) * self.retries
        else:
            walk = list(chain) * (1 + self.retries)
        last: Optional[Exception] = None
        for node in walk:
            # scatter checkpoint (GL2302): the injection point the
            # chaos matrix arms, and the deadline check when the query
            # thread runs this inline
            checkpoint("cluster.scatter")
            try:
                r = self._attempt(node, payload, expect_version, attempts,
                                  trace=trace, parent=parent, qid=qid)
                r["attempts"] = attempts
                return r
            except Exception as e:
                last = e
        raise ReplicaSetLost(
            f"every replica of chain {chain} failed: "
            f"{[a[2] for a in attempts]}"
        ) from last

    def _fetch_hedged(self, chain, payload, expect_version, attempts,
                      trace=None, parent=None, qid: str = ""):
        """First-of-two hedge: issue to the primary, wait
        `cluster_hedge_ms`, then issue to the secondary and take
        whichever succeeds first.  Both racers record their own
        `cluster_rpc` spans through the explicit trace handle (the
        second with `hedge=True`).  Returns None when both hedged
        attempts fail (the caller falls back to the sequential walk)."""
        import queue as queue_mod

        results: "queue_mod.Queue" = queue_mod.Queue()

        def run(node, hedged):
            try:
                results.put(
                    ("ok", self._attempt(node, payload, expect_version,
                                         attempts, trace=trace,
                                         parent=parent, qid=qid,
                                         hedge=hedged))
                )
            except Exception as e:  # fault-ok: collected, not raised
                results.put(("err", e))

        threading.Thread(
            target=run, args=(chain[0], False), daemon=True
        ).start()
        launched = 1
        try:
            kind, val = results.get(timeout=self.hedge_s)
        except queue_mod.Empty:
            record_cluster_rpc(chain[0], "hedged", hedged=True)
            threading.Thread(
                target=run, args=(chain[1], True), daemon=True
            ).start()
            launched = 2
            kind, val = results.get(timeout=self.rpc_timeout_s * 2 + 1)
        got = 1
        while kind != "ok" and got < launched:
            kind, val = results.get(timeout=self.rpc_timeout_s * 2 + 1)
            got += 1
        return val if kind == "ok" else None

    # -- execute (scatter -> gather -> finalize) ------------------------------

    def execute(self, q, ds):
        """Answer one covered query through the cluster.  Assigned
        segments scatter to their replica chains; residual segments
        (deltas / anything the assignment epoch predates) execute
        in-process; everything ⊕'s through the merge tree and
        finalizes exactly like a local dense execution."""
        from ..exec.engine import segments_in_scope

        t0 = time.perf_counter()
        engine = self.ctx.engine
        asg = self.assignment
        segs = segments_in_scope(q, ds)
        groups: Dict[Tuple[str, ...], list] = {}
        residual: list = []
        for s in segs:
            chain = asg.replicas(s.segment_id) if asg is not None else ()
            if chain:
                groups.setdefault(chain, []).append(s)
            else:
                residual.append(s)
        expect_version = int(asg.versions.get(ds.name, 0)) if asg else 0

        # residual FIRST: the engine's partial accounting begins the
        # pass (begin_pass resets the collector), so the broker's own
        # scope additions must come after
        res_uids = frozenset(s.uid for s in residual)
        state, rows_local = engine.groupby_partials_host(
            q, ds, within_uids=res_uids
        )
        pc = current_partial()
        if pc is not None and groups:
            a_segs = sum(len(g) for g in groups.values())
            a_rows = sum(
                s.num_rows for g in groups.values() for s in g
            )
            a_delta = sum(
                s.num_rows for g in groups.values() for s in g
                if isinstance(s, DeltaSegment)
            )
            pc.add_scope(a_segs, a_rows, a_delta)

        qdoc = q.to_druid()
        qid = current_query_id() or ""

        def _payload(g):
            # per-group scope: the historical computes its partial over
            # EXACTLY these segment ids, so two replica groups never
            # overlap and the ⊕ never double-counts
            return json.dumps(
                {
                    "query": qdoc,
                    "segments": [s.segment_id for s in g],
                    "version": expect_version or None,
                    "context": {"queryId": qid},
                }
            ).encode()

        results: list = []
        lost: list = []
        # the scatter workers run on pool threads where the contextvar
        # trace is invisible — hand them the trace handle + scatter span
        # explicitly so each attempt records its own cluster_rpc span
        # (and grafts the historical's subtree under it)
        tr = current_trace()
        with span(
            SPAN_SCATTER, groups=len(groups), nodes=len(self.nodes())
        ) as scatter_span:
            futs = {
                self._pool.submit(
                    self._fetch_group, chain, _payload(g), expect_version,
                    tr, scatter_span, qid,
                ): (chain, g)
                for chain, g in sorted(groups.items())
            }
            for fut in as_completed(futs):
                chain, g = futs[fut]
                try:
                    r = fut.result()
                except Exception as e:
                    lost.append((chain, g, e))
                    span_event(
                        "rpc", node="|".join(chain), ms=0.0,
                        outcome="lost", segments=len(g),
                    )
                    continue
                results.append((chain, r, g))

        node_receipts: Dict[str, Optional[dict]] = {}
        gathered_rows = 0
        with span(SPAN_GATHER, groups=len(results), lost=len(lost)):
            # fold in assignment (chain) order, never arrival or
            # serving-node order: a failover then changes WHO computed a
            # group's state but not where it lands in the float fold, so
            # answers stay byte-identical through replica changes
            for chain, r, g in sorted(results, key=lambda t: t[0]):
                checkpoint("cluster.gather")
                # GL2301 merge guard: the fetch already pinned the
                # replica's catalog version to the assignment epoch's;
                # re-assert before the fold so a future refactor cannot
                # silently drop the check, and let the ⊕'s own shape
                # guard catch a reshaped dictionary domain
                if expect_version and int(r["version"]) != expect_version:
                    lost.append(
                        (chain, g,
                         ReplicaSetLost("version skew at gather"))
                    )
                    continue
                try:
                    with span(SPAN_CLUSTER_MERGE):
                        state = engine.merge_groupby_states(
                            q, ds, state, r["state"]
                        )
                except ValueError as e:
                    # dictionary-domain drift: the replica's state does
                    # not ⊕ with ours — a lost group, never a bad merge
                    lost.append((("merge",), g, e))
                    continue
                gathered_rows += int(r["rows"])
                node_receipts[r["node"]] = r.get("receipt")
                if pc is not None:
                    rows, drows = _group_rows(g)
                    pc.add_seen(len(g), rows, drows)

        if lost:
            for chain, g, e in lost:
                log.warning(
                    "replica group %s lost (%d segments): %s",
                    chain, len(g), e,
                )
            if pc is not None:
                # a lost replica SET degrades to a stamped partial
                # through the existing machinery — the trigger marks
                # the answer best-effort; coverage already reflects the
                # unseen rows
                pc.trigger("cluster.scatter")

        df = engine.finalize_groupby_state(q, ds, state)
        total_ms = (time.perf_counter() - t0) * 1e3
        m = QueryMetrics(
            query_type=type(q).__name__,
            strategy="cluster",
            datasource=ds.name,
            query_id=current_query_id() or "",
            executor="cluster",
            distributed=True,
            rows_scanned=rows_local + gathered_rows,
            segments=len(segs),
            total_ms=total_ms,
        )
        if pc is not None and pc.is_partial:
            m.partial = True
            m.coverage = pc.coverage()
        self.last_metrics = m
        record_query_metrics(m, outcome="partial" if m.partial else "ok")
        return df


def _group_rows(g) -> Tuple[int, int]:
    rows = sum(s.num_rows for s in g)
    drows = sum(s.num_rows for s in g if isinstance(s, DeltaSegment))
    return rows, drows
