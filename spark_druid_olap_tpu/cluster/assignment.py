"""Segment -> historical assignment: rendezvous hashing + epochs.

Druid's coordinator assigns segments to historicals with a replication
factor and rebalances on membership change; the local analog is
rendezvous (highest-random-weight) hashing over stable `segment_id`
strings — NOT process-local uids, which differ across processes booting
the same snapshot.  Properties the chaos matrix leans on:

* **Deterministic** — every broker computing an assignment for the same
  (segments, nodes, replication) gets the same map; no coordination.
* **Minimal movement** — removing a node moves only the segments it
  held (each promotes its next-ranked replica); adding a node steals
  only the segments that now rank it in their top-R.  A rolling restart
  therefore never reshuffles the whole cluster.
* **Epoched** — every rebalance bumps a monotonic epoch, persisted in
  the assignment manifest (catalog/persist.py) next to the snapshots it
  indexes, so health checks and receipts can name WHICH map served a
  query.

The map also pins the per-datasource catalog version it was computed
at: the broker's gather refuses to ⊕ a replica state computed against a
different version (dictionary domains may differ), which is the
GL2301 broker-discipline contract.
"""

from __future__ import annotations

import dataclasses
import hashlib
from typing import Dict, Iterable, List, Optional, Tuple

from ..catalog.persist import (
    load_assignment_manifest,
    save_assignment_manifest,
)

__all__ = [
    "Assignment",
    "build_assignment",
    "rebalance",
    "replicas_for",
    "save_assignment",
    "load_assignment",
]


def _score(segment_id: str, node_id: str) -> int:
    h = hashlib.sha256(
        f"{segment_id}|{node_id}".encode("utf-8")
    ).digest()
    return int.from_bytes(h[:8], "big")


def replicas_for(
    segment_id: str, nodes: Iterable[str], replication: int
) -> Tuple[str, ...]:
    """Top-R nodes for one segment by rendezvous weight (primary
    first); clamped to the membership size."""
    ranked = sorted(
        nodes, key=lambda n: (_score(segment_id, n), n), reverse=True
    )
    r = max(1, int(replication))
    return tuple(ranked[:r])


@dataclasses.dataclass(frozen=True)
class Assignment:
    """One epoch's immutable segment -> replica-chain map."""

    epoch: int
    replication: int
    nodes: Tuple[str, ...]  # sorted membership at this epoch
    # segment_id -> replica chain (primary first)
    segment_map: Dict[str, Tuple[str, ...]]
    # datasource -> catalog version the map was computed at; the
    # gather-side merge guard (GL2301) compares replica answers to this
    versions: Dict[str, int]

    def replicas(self, segment_id: str) -> Tuple[str, ...]:
        return self.segment_map.get(segment_id, ())

    def segments_for(self, node_id: str) -> List[str]:
        return sorted(
            sid for sid, chain in self.segment_map.items()
            if node_id in chain
        )

    def deficit(self, live_nodes: Iterable[str]) -> Tuple[int, int]:
        """(under-replicated segments, fully-lost segments) against the
        currently-live membership — the health gauges."""
        live = set(live_nodes)
        under = lost = 0
        for chain in self.segment_map.values():
            alive = sum(1 for n in chain if n in live)
            if alive < len(chain):
                under += 1
            if alive == 0:
                lost += 1
        return under, lost

    def to_dict(self) -> dict:
        return {
            "epoch": self.epoch,
            "replication": self.replication,
            "nodes": list(self.nodes),
            "segment_map": {
                sid: list(chain)
                for sid, chain in sorted(self.segment_map.items())
            },
            "versions": dict(self.versions),
        }

    @classmethod
    def from_dict(cls, doc: dict) -> "Assignment":
        return cls(
            epoch=int(doc["epoch"]),
            replication=int(doc["replication"]),
            nodes=tuple(doc["nodes"]),
            segment_map={
                str(sid): tuple(chain)
                for sid, chain in doc["segment_map"].items()
            },
            versions={
                str(k): int(v) for k, v in doc.get("versions", {}).items()
            },
        )


def build_assignment(
    segment_ids: Dict[str, List[str]],
    nodes: Iterable[str],
    replication: int,
    epoch: int = 1,
    versions: Optional[Dict[str, int]] = None,
) -> Assignment:
    """Fresh map at `epoch` for {datasource: [segment_id, ...]} over the
    given membership."""
    members = tuple(sorted(set(nodes)))
    seg_map: Dict[str, Tuple[str, ...]] = {}
    for _ds, sids in sorted(segment_ids.items()):
        for sid in sids:
            seg_map[sid] = (
                replicas_for(sid, members, replication) if members else ()
            )
    return Assignment(
        epoch=int(epoch),
        replication=int(replication),
        nodes=members,
        segment_map=seg_map,
        versions=dict(versions or {}),
    )


def rebalance(
    prev: Assignment,
    nodes: Iterable[str],
    segment_ids: Optional[Dict[str, List[str]]] = None,
    versions: Optional[Dict[str, int]] = None,
) -> Assignment:
    """Next-epoch map after a membership (or segment-set) change.
    Deterministic: identical inputs produce identical maps, and the HRW
    ranking guarantees only segments touching the changed nodes move."""
    if segment_ids is None:
        segment_ids = {"": sorted(prev.segment_map)}
    return build_assignment(
        segment_ids,
        nodes,
        prev.replication,
        epoch=prev.epoch + 1,
        versions=versions if versions is not None else prev.versions,
    )


def save_assignment(directory: str, asg: Assignment) -> str:
    return save_assignment_manifest(directory, asg.to_dict())


def load_assignment(directory: str) -> Optional[Assignment]:
    doc = load_assignment_manifest(directory)
    return Assignment.from_dict(doc) if doc else None
