"""Cluster wire codec: the host partial-state currency over HTTP.

The broker/historical RPC moves exactly the interchange currency the
unified executor core already defines (exec/engine.py):

    {"sums": f64[G, A], "mins": f64[G, M], "maxs": f64[G, M],
     "sketches": {name: i8/u8[G, W]}}

encoded as JSON — per-array dtype + shape + base64 payload — because
the historical surface is the existing stdlib HTTP server and JSON is
its wire format.  Decode is STRICT: a torn body (the
`cluster.torn_response` fault site truncates mid-payload), a missing
key, or a byte count that disagrees with dtype x shape raises
`WireDecodeError`, which the broker's scatter loop treats as a replica
failure and fails over — a corrupt replica answer must never ⊕ into
the merge.
"""

from __future__ import annotations

import base64
from typing import Dict

import numpy as np

__all__ = ["WireDecodeError", "encode_state", "decode_state"]

_STATE_KEYS = ("sums", "mins", "maxs")


class WireDecodeError(ValueError):
    """A replica response that cannot be decoded into a valid partial
    state (torn payload, missing key, shape/byte mismatch)."""


def _encode_array(a: np.ndarray) -> dict:
    a = np.ascontiguousarray(a)
    return {
        "dtype": str(a.dtype),
        "shape": list(a.shape),
        "data": base64.b64encode(a.tobytes()).decode("ascii"),
    }


def _decode_array(doc) -> np.ndarray:
    if not isinstance(doc, dict):
        raise WireDecodeError(f"array doc is {type(doc).__name__}, not dict")
    try:
        dtype = np.dtype(doc["dtype"])
        shape = tuple(int(x) for x in doc["shape"])
        raw = base64.b64decode(str(doc["data"]).encode("ascii"),
                               validate=True)
    except WireDecodeError:
        raise
    except Exception as e:
        raise WireDecodeError(f"malformed array doc: {e}") from e
    want = dtype.itemsize * int(np.prod(shape, dtype=np.int64))
    if len(raw) != want:
        raise WireDecodeError(
            f"torn array payload: {len(raw)} bytes for "
            f"{dtype}{list(shape)} (want {want})"
        )
    # copy: frombuffer views are read-only and the merge fold must own
    # writable arrays
    return np.frombuffer(raw, dtype=dtype).reshape(shape).copy()


def encode_state(state: dict) -> dict:
    """Host partial-state dict -> JSON-safe document."""
    doc = {k: _encode_array(state[k]) for k in _STATE_KEYS}
    doc["sketches"] = {
        str(name): _encode_array(arr)
        for name, arr in (state.get("sketches") or {}).items()
    }
    return doc


def decode_state(doc) -> Dict[str, object]:
    """JSON document -> host partial-state dict (strict; raises
    `WireDecodeError` on anything short of a complete valid state)."""
    if not isinstance(doc, dict):
        raise WireDecodeError(
            f"state doc is {type(doc).__name__}, not dict"
        )
    missing = [k for k in _STATE_KEYS if k not in doc]
    if missing:
        raise WireDecodeError(f"state doc missing keys {missing}")
    state = {k: _decode_array(doc[k]) for k in _STATE_KEYS}
    sk = doc.get("sketches")
    if sk is not None and not isinstance(sk, dict):
        raise WireDecodeError("sketches member is not a dict")
    state["sketches"] = {
        str(name): _decode_array(arr) for name, arr in (sk or {}).items()
    }
    return state
