"""Cluster wire codec: the host partial-state currency over HTTP.

The broker/historical RPC moves exactly the interchange currency the
unified executor core already defines (exec/engine.py):

    {"sums": f64[G, A], "mins": f64[G, M], "maxs": f64[G, M],
     "sketches": {name: i8/u8[G, W]}}

encoded as JSON — per-array dtype + shape + base64 payload — because
the historical surface is the existing stdlib HTTP server and JSON is
its wire format.  Decode is STRICT: a torn body (the
`cluster.torn_response` fault site truncates mid-payload), a missing
key, or a byte count that disagrees with dtype x shape raises
`WireDecodeError`, which the broker's scatter loop treats as a replica
failure and fails over — a corrupt replica answer must never ⊕ into
the merge.

The OBSERVABILITY side-channel (ISSUE 19) rides the same responses
with the OPPOSITE decode posture: `encode_trace`/`decode_trace` move a
historical's rendered span subtree next to its partial state, and any
problem with that payload — torn, oversized, wrong shape — degrades to
an `untraced` stub, NEVER a replica failure.  A query must not fail
over (or lose a good partial state) because its telemetry was ugly.
`trace_headers` builds the propagation headers the broker attaches to
every scatter RPC (`X-Druid-Query-Id` — Druid's own echo header — plus
`X-Sdol-Parent-Span`, the OTLP span id of the broker's `cluster_rpc`
span) so both processes trace under one identity.
"""

from __future__ import annotations

import base64
import json
from typing import Dict, Optional

import numpy as np

__all__ = [
    "WireDecodeError",
    "encode_state",
    "decode_state",
    "HEADER_QUERY_ID",
    "HEADER_PARENT_SPAN",
    "TRACE_MAX_BYTES",
    "trace_headers",
    "encode_trace",
    "decode_trace",
    "untraced_stub",
]

_STATE_KEYS = ("sums", "mins", "maxs")

# trace-propagation headers (graftlint GL2701: every cluster RPC sender
# must attach these — through `trace_headers`, so the names live here)
HEADER_QUERY_ID = "X-Druid-Query-Id"
HEADER_PARENT_SPAN = "X-Sdol-Parent-Span"

# upper bound for one rendered span subtree on the wire, each way: an
# instrumentation explosion (a scan that opened a span per row) must not
# bloat every scatter response — past the cap the subtree degrades to an
# `untraced` stub while the partial state ships untouched
TRACE_MAX_BYTES = 262_144


class WireDecodeError(ValueError):
    """A replica response that cannot be decoded into a valid partial
    state (torn payload, missing key, shape/byte mismatch)."""


def _encode_array(a: np.ndarray) -> dict:
    a = np.ascontiguousarray(a)
    return {
        "dtype": str(a.dtype),
        "shape": list(a.shape),
        "data": base64.b64encode(a.tobytes()).decode("ascii"),
    }


def _decode_array(doc) -> np.ndarray:
    if not isinstance(doc, dict):
        raise WireDecodeError(f"array doc is {type(doc).__name__}, not dict")
    try:
        dtype = np.dtype(doc["dtype"])
        shape = tuple(int(x) for x in doc["shape"])
        raw = base64.b64decode(str(doc["data"]).encode("ascii"),
                               validate=True)
    except WireDecodeError:
        raise
    except Exception as e:
        raise WireDecodeError(f"malformed array doc: {e}") from e
    want = dtype.itemsize * int(np.prod(shape, dtype=np.int64))
    if len(raw) != want:
        raise WireDecodeError(
            f"torn array payload: {len(raw)} bytes for "
            f"{dtype}{list(shape)} (want {want})"
        )
    # copy: frombuffer views are read-only and the merge fold must own
    # writable arrays
    return np.frombuffer(raw, dtype=dtype).reshape(shape).copy()


def encode_state(state: dict) -> dict:
    """Host partial-state dict -> JSON-safe document."""
    doc = {k: _encode_array(state[k]) for k in _STATE_KEYS}
    doc["sketches"] = {
        str(name): _encode_array(arr)
        for name, arr in (state.get("sketches") or {}).items()
    }
    return doc


def decode_state(doc) -> Dict[str, object]:
    """JSON document -> host partial-state dict (strict; raises
    `WireDecodeError` on anything short of a complete valid state)."""
    if not isinstance(doc, dict):
        raise WireDecodeError(
            f"state doc is {type(doc).__name__}, not dict"
        )
    missing = [k for k in _STATE_KEYS if k not in doc]
    if missing:
        raise WireDecodeError(f"state doc missing keys {missing}")
    state = {k: _decode_array(doc[k]) for k in _STATE_KEYS}
    sk = doc.get("sketches")
    if sk is not None and not isinstance(sk, dict):
        raise WireDecodeError("sketches member is not a dict")
    state["sketches"] = {
        str(name): _decode_array(arr) for name, arr in (sk or {}).items()
    }
    return state


# ---------------------------------------------------------------------------
# Trace side-channel (ISSUE 19): lenient by design — degrade, never fail
# ---------------------------------------------------------------------------


def trace_headers(query_id: str, parent_span_id: str = "") -> Dict[str, str]:
    """The propagation headers a cluster RPC sender attaches (GL2701):
    the query id both processes trace under, plus the broker-side span
    id the historical's trace records as its cross-process parent."""
    headers = {HEADER_QUERY_ID: str(query_id or "")}
    if parent_span_id:
        headers[HEADER_PARENT_SPAN] = str(parent_span_id)
    return headers


def untraced_stub(node: str, reason: str) -> dict:
    """The degraded graft: a zero-duration marker node standing where a
    historical's subtree would have been.  Shape-compatible with a
    rendered span node so the grafted tree stays well-formed; `attrs`
    name the node and why its telemetry is missing."""
    return {
        "name": "query",
        "start_ms": 0.0,
        "duration_ms": 0.0,
        "attrs": {
            "node": str(node or "?"),
            "remote": True,
            "untraced": True,
            "reason": str(reason or "unknown"),
        },
    }


def _valid_span_node(node, depth: int = 0) -> bool:
    """Structural check over a rendered span node: dict shape, string
    name, numeric timings, recursively valid children.  Bounded depth so
    a hostile/corrupt payload cannot recurse past sys limits."""
    if depth > 64 or not isinstance(node, dict):
        return False
    if not isinstance(node.get("name"), str):
        return False
    for key in ("start_ms", "duration_ms"):
        if not isinstance(node.get(key, 0.0), (int, float)):
            return False
    attrs = node.get("attrs")
    if attrs is not None and not isinstance(attrs, dict):
        return False
    children = node.get("children")
    if children is None:
        return True
    if not isinstance(children, list):
        return False
    return all(_valid_span_node(c, depth + 1) for c in children)


def encode_trace(
    trace_doc: Optional[dict], max_bytes: int = TRACE_MAX_BYTES
) -> Optional[dict]:
    """Historical side: the rendered subtree of `QueryTrace.to_dict()`
    ready to ride the partial response, or an `untraced` stub when it is
    malformed or oversized.  Never raises and never returns something
    that would fail the response encode."""
    if not isinstance(trace_doc, dict):
        return None
    node = trace_doc.get("spans")
    if not _valid_span_node(node):
        return untraced_stub("", "malformed local trace")
    subtree = dict(node)
    # the remote receipt rides INSIDE the graft root so receipt folding
    # and obs_dump see per-node attribution even from the subtree alone
    receipt = trace_doc.get("receipt")
    if isinstance(receipt, dict):
        subtree["receipt"] = receipt
    try:
        if len(json.dumps(subtree)) > max(1024, int(max_bytes)):
            return untraced_stub("", "trace payload over size cap")
    except (TypeError, ValueError):
        return untraced_stub("", "unserializable trace payload")
    return subtree


def decode_trace(
    doc, node: str, max_bytes: int = TRACE_MAX_BYTES
) -> dict:
    """Broker side: validate a replica's trace payload into a graftable
    subtree.  ANY defect — absent, torn, wrong shape, oversized —
    returns an `untraced` stub for `node`; this function never raises
    (trace trouble must not fail a replica that computed a good
    partial)."""
    if doc is None:
        return untraced_stub(node, "replica returned no trace")
    try:
        if not _valid_span_node(doc):
            return untraced_stub(node, "malformed trace payload")
        if len(json.dumps(doc)) > max(1024, int(max_bytes)):
            return untraced_stub(node, "trace payload over size cap")
    except Exception:
        return untraced_stub(node, "undecodable trace payload")
    out = dict(doc)
    attrs = dict(out.get("attrs") or {})
    attrs.setdefault("node", str(node or "?"))
    attrs["remote"] = True
    out["attrs"] = attrs
    return out
