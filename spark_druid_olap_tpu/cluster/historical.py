"""Historical process: a read-only serving replica over the shared
snapshot store (cluster/, ISSUE 16).

One historical = one `TPUOlapContext` mmap-booted from the SAME
`storage_dir` the broker writes (snapshot load reads .npy headers only
— boot is metadata-time, ~57 ms at SF10 — and the pages of a segment
fault in lazily as queries touch it, so a node effectively loads only
its ASSIGNED subset) + one `OlapServer` exposing the existing wire
surface, including `POST /druid/v2/cluster/partial`.

Historicals are deliberately read-only consumers of the store: fsync
off, no flush sweep, no compaction — the broker owns the write path,
so N processes can share one directory without write-write races.  A
restarting historical re-runs the normal storage recovery (snapshot
mmap + WAL replay past the watermark) and is 503-busy until replay
finishes; its replicas carry the traffic meanwhile.

In-process use (tests; kill = `shutdown()`, restart = a fresh node on
the same directory):

    node = HistoricalNode("h0", storage_dir).start()
    ... node.url ...
    node.shutdown()

Subprocess use (bench; real SIGKILL):

    python -m spark_druid_olap_tpu.cluster.historical \
        --storage-dir DIR --node-id h0 --port 0 --announce FILE

writes {"node_id", "port", "url", "pid"} to FILE once serving.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from ..utils.log import get_logger

log = get_logger("cluster.historical")


class HistoricalNode:
    """One in-process historical: context + HTTP server over a shared
    snapshot store."""

    def __init__(
        self,
        node_id: str,
        storage_dir: str,
        host: str = "127.0.0.1",
        port: int = 0,
        config=None,
    ):
        self.node_id = node_id
        self.storage_dir = storage_dir
        self.host = host
        self._want_port = port
        self.ctx = None
        self.server = None
        self._config = config

    def start(self) -> "HistoricalNode":
        from ..api import TPUOlapContext
        from ..config import SessionConfig
        from ..server import OlapServer

        cfg = self._config or SessionConfig.load_calibrated()
        # read-only consumer of the shared store: no fsync (this node
        # never journals), no background flush sweep, no compaction —
        # the broker owns the write path
        cfg = dataclasses.replace(
            cfg,
            storage_dir=self.storage_dir,
            storage_fsync=False,
            snapshot_flush_s=0.0,
            compaction_interval_s=0.0,
        )
        self.ctx = TPUOlapContext(cfg)
        # the id the scatter surface stamps on every partial response
        self.ctx.cluster_node_id = self.node_id
        self.server = OlapServer(
            self.ctx, host=self.host, port=self._want_port
        )
        self.server.start()
        log.info(
            "historical %s serving %s on %s", self.node_id,
            self.storage_dir, self.url,
        )
        return self

    @property
    def port(self) -> int:
        return self.server.port if self.server else 0

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def shutdown(self) -> None:
        if self.server is not None:
            self.server.shutdown()
            self.server = None


def main(argv: Optional[list] = None) -> int:
    import argparse
    import json
    import os
    import signal
    import threading

    ap = argparse.ArgumentParser(
        prog="spark_druid_olap_tpu.cluster.historical",
        description="serve one historical replica over a shared "
        "snapshot store",
    )
    ap.add_argument("--storage-dir", required=True)
    ap.add_argument("--node-id", required=True)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument(
        "--announce",
        help="write {node_id, port, url, pid} JSON here once serving "
        "(how the bench driver finds ephemeral ports)",
    )
    args = ap.parse_args(argv)
    node = HistoricalNode(
        args.node_id, args.storage_dir, host=args.host, port=args.port
    ).start()
    if args.announce:
        from ..catalog.persist import atomic_write_json

        atomic_write_json(
            args.announce,
            {
                "node_id": node.node_id,
                "port": node.port,
                "url": node.url,
                "pid": os.getpid(),
            },
        )
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    signal.signal(signal.SIGINT, lambda *_: stop.set())
    stop.wait()
    node.shutdown()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
