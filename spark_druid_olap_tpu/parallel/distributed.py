"""Distributed GroupBy execution: shard_map over a device mesh + ICI merge.

Reference parity: this is the Druid **broker scatter-gather** rebuilt on XLA
collectives (SURVEY.md §2 parallelism table, §3.3 `[U]`).  In the reference,
the broker fans a query to historicals, each computes per-segment partial
aggregates, and the broker merges partials (sum-merge, min/max-merge, HLL
register-max, sketch union).  Here:

* historicals  → mesh devices, each holding a row shard in HBM
* HTTP fan-out → `shard_map` over the ``data`` axis (one traced program, SPMD)
* broker merge → `lax.psum` (sums/counts), `lax.pmin`/`pmax` (extrema, HLL
  registers), `all_gather` + KMV-union fold (theta) — riding ICI, with DCN
  handled transparently by the same collectives on multi-host meshes
* Spark-side final merge → `exec.engine.finalize_groupby` on the replicated
  [G, M] state (tiny)

The ``groups`` mesh axis additionally shards the group-id domain (the
TP-analog): each device matches only its slice of [0, G), shrinking the
one-hot block and sketch states by the axis size; no collective is needed on
that axis — outputs stay group-sharded until the host gathers them.

**Kernel ladder (VERDICT r4 #1).**  The per-shard kernel is routed by the
same calibrated cost model as the single-device engine
(`plan.cost.choose_query_kernel`) — the round-4 engine hard-coded the dense
one-hot, which made every high-cardinality SSB query (9 of 13) inexecutable
on the mesh.  The full ladder now runs SPMD:

* dense / Pallas one-hot  — small G (psum/pmin/pmax merge over ``data``)
* segment scatter         — large G, dense [Gl, M] state, same collectives
* sparse sort-compaction  — huge domain, few present: per-device
  `sparse_partial_aggregate` (slots ladder included), then an
  `all_gather` + `merge_sparse_states` fold over ``data`` — the broker
  merge in sparse-state form.  The ``groups`` axis shards the *group-id
  domain* (each device keeps only gids in its slice), multiplying slot
  capacity by the axis size.
* adaptive domain compaction — a distributed phase A measures per-dim
  presence counts (tiny per-dim GroupBys, psum-merged like any aggregate);
  the host builds the kept-code LUTs; phase B is the normal SPMD program
  over the compacted lowering (LUTs broadcast as staged jit constants).

**Durable shard residency (VERDICT r4 #3).**  Row shards are keyed by
(datasource, column, data-axis size, FULL segment signature) — never by a
query's pruned segment scope — so assembly is paid once per datasource
version, like Druid historicals owning their segments across queries.
Correctness needs no segment exclusion: the row mask (intervals + the full
filter) already excludes every row interval/zone pruning would have dropped,
so pruning here only narrows the *metrics* scope.

Long-context analog (SURVEY.md §5): rows are the "sequence" axis.  Blockwise
partial aggregation over row chunks + ring/allreduce merge of aggregate state
is the same communication shape ring-attention uses for KV blocks — scaling
group-by past one chip's HBM without materializing anything global.
"""

from __future__ import annotations

import contextlib
import threading as _threading
import time as _time
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..catalog.segment import ROW_PAD, DataSource
from ..exec.engine import (
    GroupByLowering,
    finalize_groupby,
    finalize_timeseries,
    finalize_topn,
    groupby_with_time_granularity,
    timeseries_to_groupby,
    topn_to_groupby,
)
from ..models import aggregations as A
from ..models import query as Q
from ..ops import hll as hll_ops
from ..ops import quantiles as quantiles_ops
from ..ops import theta as theta_ops
from ..obs import (
    SPAN_ADAPTIVE_PROBE,
    SPAN_ARENA_BUILD,
    SPAN_COLLECTIVE_MERGE,
    SPAN_FINALIZE,
    SPAN_SEGMENT_DISPATCH,
    SPAN_SPARSE_DISPATCH,
    current_query_id,
    record_query_metrics,
    span,
    span_event,
)
from ..ops.groupby import (
    SCATTER_CUTOVER,
    choose_block_rows,
    dense_partial_aggregate,
    partial_aggregate,
    scatter_partial_aggregate,
)
from ..utils.log import get_logger
from . import spmd_arena
from .mesh import (
    DATA_AXIS,
    GROUPS_AXIS,
    SLICE_AXIS,
    make_mesh,
    row_axes,
    shard_map_compat,
)
from .multihost import initialize as multihost_initialize, put_sharded

log = get_logger("parallel.distributed")

_SPARSE_STATE_KEYS = ("gids", "sums", "mins", "maxs")
_SPARSE_FLAG_KEYS = ("overflow", "row_overflow", "n_rows", "n_real")


class DistributedEngine:
    """Executes GroupBy-family queries SPMD over a mesh.

    Row shards are built host-side by concatenating ALL segment columns and
    padding to a multiple of (mesh data size × ROW_PAD); `jax.device_put`
    with a NamedSharding places each shard in its device's HBM.  Residency
    is durable across queries (see module docstring)."""

    def __init__(
        self,
        mesh: Optional[Mesh] = None,
        shard_cache_bytes: int = 4 << 30,
        program_cache_entries: int = 128,
        strategy: str = "auto",
    ):
        from ..utils.lru import ByteBudgetCache, CountBudgetCache

        # multi-host runtime formation (parallel/multihost.py) rides the
        # unified core's construction: a no-op single-process, it resolves
        # the jax.distributed cluster from env markers on real pods so the
        # mesh below spans every host's devices (ISSUE 15 satellite)
        multihost_initialize()
        if mesh is not None and SLICE_AXIS in mesh.shape:
            # virtual multi-slice topology: the slice mesh drives ONLY the
            # unified arena path (placement + merge tree).  Legacy SPMD
            # programs keep their (data, groups) contract by flattening
            # the slice x data product onto the data axis — the mesh is a
            # placement strategy, not a fork of the executor.
            self.slice_mesh = mesh
            devs = list(mesh.devices.flat)
            self.mesh = make_mesh(n_data=len(devs), n_groups=1, devices=devs)
        else:
            self.slice_mesh = None
            self.mesh = mesh if mesh is not None else make_mesh()
        # "auto" routes by the calibrated cost model; an explicit kernel
        # class is honored as such, same contract as
        # exec.engine.Engine(strategy=...).  Validated here: an unknown
        # string would otherwise fall into the dense one-hot branch — at
        # high G that is a pathological compile, not an error message
        if strategy not in (
            "auto", "dense", "pallas", "segment", "scatter", "sparse",
            "adaptive",
        ):
            raise ValueError(f"unknown groupby strategy {strategy!r}")
        self.strategy = strategy
        self.last_metrics = None  # observability (exec/metrics.py)
        # row-shard cache: keyed by (ds, column, data-axis, full segment
        # signature) — durable across queries; LRU under a byte budget
        self._shard_cache = ByteBudgetCache(shard_cache_bytes)
        # compiled SPMD program cache (query shape x schema x local rows x
        # strategy); without it every execute() re-traces the shard_map
        self._spmd_cache = CountBudgetCache(program_cache_entries)
        # lowering cache: rebuilding a lowering stages device constants
        # (dictionary remaps, bucket tables) — one blocking H2D per constant
        # on every execution without it (same as exec/engine.py)
        self._lowering_cache = CountBudgetCache(program_cache_entries)
        # calibrated cost model for kernel routing (loaded once)
        self._calibrated_cfg = None
        # kernel-ladder memos, mirroring exec/engine.py Engine.__init__:
        # adaptive kept-code sets + decline memo, remembered sparse rungs,
        # and sparse declines (ladder exhausted -> route straight to
        # scatter on repeats)
        self._adaptive_kept: Dict = {}
        self._adaptive_declined: set = set()
        self._sparse_slots: Dict = {}
        self._sparse_row_capacity: Dict = {}
        self._sparse_declined: set = set()
        # resilience wiring (resilience.py): same contract as
        # exec.engine.Engine — transient failures/recoveries report to the
        # breaker (TPUOlapContext swaps in its shared one); the breaker
        # gates routing at the api layer, never execution here
        from ..resilience import CircuitBreaker

        self.breaker = CircuitBreaker()
        self._retry_attempts = 2
        self._retry_backoff_ms = 25.0
        # unified SPMD-arena core (ISSUE 15): the stacked [B, R] layout
        # shared with exec/arena.py, sharded over the row devices.
        # TPUOlapContext syncs this from SessionConfig.arena_execution,
        # same contract as the local engine's toggle.
        self.arena_execution = True
        # per-thread state-capture holder (delta-aware result cache):
        # mirrors exec.engine.Engine._m_local
        self._m_local = _threading.local()

    def _cfg(self):
        if self._calibrated_cfg is None:
            from ..config import SessionConfig

            self._calibrated_cfg = SessionConfig.load_calibrated()
        return self._calibrated_cfg

    def _lowering_for(self, q: Q.GroupByQuery, ds: DataSource):
        from ..exec.lowering import cached_lowering

        return cached_lowering(self._lowering_cache, q, ds)

    # -- host-side row-shard assembly ---------------------------------------

    def _global_columns(self, ds: DataSource, names, segs=None):
        """Assemble (or reuse) sharded columns over a segment scope.

        Durable residency: the key has no query component beyond the
        segment scope, so every query sharing a scope against this
        datasource version reuses the same placed shards —
        `shard_assembly_ms` is paid once per (scope, column), the
        analog of historicals owning segments across queries (SURVEY.md §2
        data-parallelism row; VERDICT r4 #3).  A fixed per-scope layout
        also keeps `local_rows` constant, so SPMD programs cache across
        queries with the same scope.

        `segs` is the interval/zone-map PRUNED scope (the r5->r6 mesh
        regression fix: the mesh used to shard the FULL set for every
        query and pay a full-scope scan where the single-device engine
        pruned — profiled at SF1, ~100% of the flat ~400 ms/query floor
        was device time over rows pruning would have skipped).  None
        means the full set (streaming / scope-free callers)."""
        nd = self.mesh.shape[DATA_AXIS]
        segs = list(ds.segments) if segs is None else list(segs)
        total = sum(s.num_rows_padded for s in segs)
        chunk = nd * ROW_PAD
        padded = -(-max(total, 1) // chunk) * chunk
        sharding = NamedSharding(self.mesh, P(DATA_AXIS))
        seg_sig = tuple(s.uid for s in segs)

        def build(name: str, fill) -> jax.Array:
            # "col"/"valid" tags: a user column literally named
            # "__valid" must not alias the validity-mask entry (GL1301)
            key = (ds.name, "col", name, nd, seg_sig)
            hit = self._shard_cache.get(key)
            if hit is not None:
                return hit
            parts = [np.asarray(s.column(name)) for s in segs]
            host = np.concatenate(parts) if parts else np.zeros(0)
            if len(host) < padded:
                host = np.concatenate(
                    [host, np.full(padded - len(host), fill, dtype=host.dtype)]
                )
            arr = put_sharded(host, sharding)
            self._shard_cache[key] = arr
            return arr

        cols: Dict[str, jax.Array] = {}
        for n in names:
            fill = -1 if n in ds.dicts else 0
            cols[n] = build(n, fill)
        vkey = (ds.name, "valid", nd, seg_sig)
        valid = self._shard_cache.get(vkey)
        if valid is None:
            parts = [s.valid for s in segs]
            host = (
                np.concatenate(parts) if parts else np.zeros(0, dtype=bool)
            )
            if len(host) < padded:
                host = np.concatenate(
                    [host, np.zeros(padded - len(host), dtype=bool)]
                )
            valid = put_sharded(host, sharding)
            self._shard_cache[vkey] = valid
        cols["__valid"] = valid
        if ds.time_column and ds.time_column in cols:
            cols["__time"] = cols[ds.time_column]
        return cols, padded

    def _scope_for_metrics(self, q, ds: DataSource):
        """Interval + zone-map pruned segment scope — shared with the
        local engine's exact pruning policy.  Both the metrics AND the
        shard layout read it: `_place_shards` assembles only the pruned
        scope (the row mask still excludes within surviving segments)."""
        from ..exec.engine import segments_in_scope

        return segments_in_scope(q, ds)

    def clear_cache(self):
        self._shard_cache.clear()
        self._lowering_cache.clear()
        self._spmd_cache.clear()

    # -- SPMD programs -------------------------------------------------------

    def _mesh_key(self) -> Tuple:
        return tuple(sorted(self.mesh.shape.items()))

    def _groups_split(self, G: int) -> Tuple[int, int]:
        """(ng, Gl): group-domain shard count and per-device slice size.
        The axis must divide G; otherwise groups are replicated."""
        ng = self.mesh.shape[GROUPS_AXIS]
        if G % ng:
            ng = 1
        return ng, G // max(ng, 1)

    def _spmd_fn(
        self,
        lowering: GroupByLowering,
        local_rows: int,
        ds: DataSource,
        col_keys: Tuple[str, ...],
        strategy: str = "dense",
        key_extra: Tuple = (),
    ):
        """Build (or fetch) the compiled dense-state SPMD program.

        `strategy` routes the per-shard kernel (dense one-hot / Pallas /
        segment scatter); all produce the same [Gl, M] state, so the
        psum/pmin/pmax broker merge is strategy-independent.  Cached on
        (query shape, schema signature, local rows, mesh shape, strategy,
        key_extra): jit's compilation cache is keyed on callable identity,
        so rebuilding the closure per query would recompile every time."""
        from ..exec.lowering import _query_key

        # "dense-state" pins this family apart from the "sparse" /
        # "adaptive-presence" tuples sharing _spmd_cache (GL1301)
        cache_key = _query_key(lowering.query, ds) + (
            local_rows,
            self._mesh_key(),
            "dense-state",
            strategy,
        ) + tuple(key_extra)
        from ..obs import prof

        if cache_key in self._spmd_cache:
            prof.note_program_cache("dense-state", hit=True)
            return self._spmd_cache[cache_key]
        prof.note_program_cache("dense-state", hit=False)
        G = lowering.num_groups
        la = lowering.la
        ng, Gl = self._groups_split(G)
        num_min, num_max = len(la.min_names), len(la.max_names)
        sketches = list(la.sketch_aggs)
        block = choose_block_rows(local_rows, Gl)
        while local_rows % block:
            block -= ROW_PAD
        block = max(block, ROW_PAD)

        def shard_fn(cols: Dict[str, jax.Array]):
            cols = lowering.add_virtual(dict(cols))  # sketches read virtuals
            gid, mask, sv, mmv, mmm = lowering.row_arrays(cols)
            if ng > 1:
                off = lax.axis_index(GROUPS_AXIS).astype(jnp.int32) * Gl
                gid_l = gid - off  # ids outside [0, Gl) never match the iota
                if strategy in ("segment", "scatter"):
                    # scatter indexes the state directly — out-of-slice ids
                    # must be masked, not merely non-matching
                    mask = mask & (gid_l >= 0) & (gid_l < Gl)
            else:
                gid_l = gid
            if strategy in ("segment", "scatter"):
                sums, mins, maxs = scatter_partial_aggregate(
                    gid_l, mask, sv, mmv, mmm,
                    num_groups=Gl, num_min=num_min, num_max=num_max,
                )
            elif strategy == "pallas":
                sums, mins, maxs = partial_aggregate(
                    gid_l, mask, sv, mmv, mmm,
                    num_groups=Gl, num_min=num_min, num_max=num_max,
                    strategy="pallas",
                )
            else:
                sums, mins, maxs = dense_partial_aggregate(
                    gid_l, mask, sv, mmv, mmm,
                    num_groups=Gl, block_rows=block,
                    num_min=num_min, num_max=num_max,
                )
            # broker-merge over the data axis (ICI collectives)
            sums = lax.psum(sums, DATA_AXIS)
            if num_min:
                mins = lax.pmin(mins, DATA_AXIS)
            if num_max:
                maxs = lax.pmax(maxs, DATA_AXIS)
            sk_out = {}
            for agg in sketches:
                # per-agg FILTER mask composes with the row mask (same
                # contract as the local engine's sketch partials)
                mfn = la.mask_fns.get(agg.name)
                amask = mask & mfn(cols) if mfn is not None else mask
                if isinstance(agg, (A.HyperUnique, A.CardinalityAgg)):
                    st = hll_ops.partial_hll(agg, cols, gid_l, amask, Gl)
                    sk_out[agg.name] = lax.pmax(st, DATA_AXIS)
                elif isinstance(agg, A.QuantilesSketch):
                    st = quantiles_ops.partial_quantiles(
                        agg, cols, gid_l, amask, Gl
                    )
                    gathered = lax.all_gather(st, DATA_AXIS)  # [nd,Gl,K+1,2]
                    acc = gathered[0]
                    for i in range(1, gathered.shape[0]):
                        acc = quantiles_ops.merge_states(
                            acc, gathered[i], agg.size
                        )
                    sk_out[agg.name] = acc
                else:
                    st = theta_ops.partial_theta(agg, cols, gid_l, amask, Gl)
                    gathered = lax.all_gather(st, DATA_AXIS)  # [nd, Gl, K]
                    acc = gathered[0]
                    for i in range(1, gathered.shape[0]):
                        acc = theta_ops.merge_states(acc, gathered[i], agg.size)
                    sk_out[agg.name] = acc
            return sums, mins, maxs, sk_out

        specs = {n: P(DATA_AXIS) for n in col_keys}
        gspec = P(GROUPS_AXIS) if ng > 1 else P()
        out_spec = (gspec, gspec, gspec, {a.name: gspec for a in sketches})
        run = jax.jit(
            shard_map_compat(
                shard_fn,
                mesh=self.mesh,
                in_specs=(specs,),
                out_specs=out_spec,
            )
        )
        self._spmd_cache[cache_key] = run
        return run

    def _sparse_inner(self) -> str:
        """Inner kernel over the compacted slots: Pallas one-hot on a TPU
        backend, scatter elsewhere (same convention as exec/sparse_exec.py;
        past SPARSE_SLOTS the segmented-reduce tier takes over inside
        sparse_partial_aggregate regardless)."""
        from ..ops.pallas_groupby import pallas_available

        return "pallas" if pallas_available() else "segment"

    def _spmd_sparse_fn(
        self,
        lowering: GroupByLowering,
        local_rows: int,
        ds: DataSource,
        col_keys: Tuple[str, ...],
        slots: int,
        row_capacity: Optional[int],
    ):
        """Sparse sort-compaction SPMD program.

        Per device: compact the local shard's surviving rows, aggregate
        into `slots` sparse slots (one-hot within SPARSE_SLOTS, the
        segmented-reduce tier above).  Merge over the data axis is an
        `all_gather` + `merge_sparse_states` fold — the broker merge in
        sparse-state form.  The groups axis shards the GROUP-ID DOMAIN:
        each device keeps only gids in its slice (global ids preserved in
        the state), so the concatenated output holds up to ng × slots
        distinct groups with disjoint gid sets — finalize_groupby's
        slot_gids layout handles it unchanged.

        Returns (state, flags): state arrays are [ng*(slots+1), ...]
        (gids/sums/mins/maxs), flags are [ng] per-slice scalars
        (overflow / row_overflow / n_rows / n_real)."""
        from ..exec.lowering import _query_key
        from ..ops.sparse_groupby import (
            merge_sparse_states,
            sparse_partial_aggregate,
        )

        inner = self._sparse_inner()
        # structured key, NOT an f-string (graftlint jit-cache/GL103)
        cache_key = _query_key(lowering.query, ds) + (
            local_rows,
            self._mesh_key(),
            "sparse", inner, row_capacity, slots,
        )
        if cache_key in self._spmd_cache:
            return self._spmd_cache[cache_key]
        G = lowering.num_groups
        la = lowering.la
        ng, Gl = self._groups_split(G)
        num_min, num_max = len(la.min_names), len(la.max_names)
        nd = self.mesh.shape[DATA_AXIS]

        def shard_fn(cols: Dict[str, jax.Array]):
            gid, mask, sv, mmv, mmm = lowering.row_arrays(dict(cols))
            if ng > 1:
                off = lax.axis_index(GROUPS_AXIS).astype(jnp.int32) * Gl
                mask = mask & (gid >= off) & (gid < off + Gl)
            st = sparse_partial_aggregate(
                gid, mask, sv, mmv, mmm,
                num_groups=G, num_min=num_min, num_max=num_max,
                slots=slots, inner_strategy=inner,
                row_capacity=row_capacity,
            )
            gathered = jax.tree.map(
                lambda x: lax.all_gather(x, DATA_AXIS), st
            )
            acc = jax.tree.map(lambda x: x[0], gathered)
            for i in range(1, nd):
                acc = merge_sparse_states(
                    acc,
                    jax.tree.map(lambda x, i=i: x[i], gathered),
                    num_groups=G,
                )
            state = {k: acc[k] for k in _SPARSE_STATE_KEYS}
            flags = {
                k: acc[k].reshape(1) for k in _SPARSE_FLAG_KEYS
            }
            return state, flags

        specs = {n: P(DATA_AXIS) for n in col_keys}
        gspec = P(GROUPS_AXIS) if ng > 1 else P()
        out_spec = (
            {k: gspec for k in _SPARSE_STATE_KEYS},
            {k: gspec for k in _SPARSE_FLAG_KEYS},
        )
        run = jax.jit(
            shard_map_compat(
                shard_fn,
                mesh=self.mesh,
                in_specs=(specs,),
                out_specs=out_spec,
            )
        )
        self._spmd_cache[cache_key] = run
        return run

    def _presence_fn(
        self,
        lowering: GroupByLowering,
        local_rows: int,
        ds: DataSource,
        col_keys: Tuple[str, ...],
    ):
        """Adaptive phase A as an SPMD program: per-dim presence counts
        under the query's row mask, psum-merged over the data axis like any
        aggregate (VERDICT r4 #1's prescription).  Output is replicated
        (cardinality-sized vectors, tiny)."""
        from ..exec.lowering import _query_key
        from ..ops.pallas_groupby import pallas_available

        pallas_ok = pallas_available()
        cache_key = _query_key(lowering.query, ds) + (
            local_rows,
            self._mesh_key(),
            "adaptive-presence",
            pallas_ok,
        )
        if cache_key in self._spmd_cache:
            return self._spmd_cache[cache_key]
        # same platform convention as exec/adaptive_exec.py: one-hot
        # kernels only on a TPU backend, scatter everywhere else (a
        # cardinality-sized scatter state is cache-resident on CPU)
        strategies = [
            "pallas"
            if pallas_ok and d.cardinality <= SCATTER_CUTOVER
            else "segment"
            for d in lowering.dims
        ]

        def shard_fn(cols: Dict[str, jax.Array]):
            cols = lowering.add_virtual(dict(cols))
            mask = lowering.row_mask(cols)
            ones = mask.astype(jnp.float32)[:, None]
            zero_mm = jnp.zeros((ones.shape[0], 0), jnp.float32)
            zero_mmm = jnp.zeros((ones.shape[0], 0), jnp.bool_)
            per = []
            for d, strat in zip(lowering.dims, strategies):
                s, _, _ = partial_aggregate(
                    d.codes_fn(cols), mask, ones, zero_mm, zero_mmm,
                    num_groups=d.cardinality, num_min=0, num_max=0,
                    strategy=strat,
                )
                per.append(lax.psum(s[:, 0], DATA_AXIS))
            return per

        specs = {n: P(DATA_AXIS) for n in col_keys}
        run = jax.jit(
            shard_map_compat(
                shard_fn,
                mesh=self.mesh,
                in_specs=(specs,),
                out_specs=[P() for _ in lowering.dims],
            )
        )
        self._spmd_cache[cache_key] = run
        return run

    # -- entry points --------------------------------------------------------

    def execute(self, q: Q.QuerySpec, ds: DataSource):
        # Timeseries/TopN rewrites + finalization are shared with the local
        # engine (exec/engine.py) so distributed semantics cannot drift.
        if isinstance(q, Q.TimeseriesQuery):
            df = self.execute(timeseries_to_groupby(q), ds)
            return finalize_timeseries(df, q, ds)
        if isinstance(q, Q.TopNQuery):
            df = self.execute(topn_to_groupby(q), ds)
            return finalize_topn(df, q)
        assert isinstance(q, Q.GroupByQuery), type(q)
        # idempotent re-dispatch on transient device failure, mirroring
        # exec/engine.py: the SAME shared retry/backoff/breaker policy
        # (resilience.run_device_attempts), differing only in what a
        # failed dispatch has to evict (shards + SPMD programs here)
        from ..resilience import run_device_attempts

        q = groupby_with_time_granularity(q)

        def evict():
            from ..exec.lowering import _query_key

            qkey = _query_key(q, ds)
            self._lowering_cache.pop(qkey)
            # spmd keys are _query_key + (local_rows, mesh, ...): evict
            # only this query's programs, not every cached compile
            for k in [k for k in self._spmd_cache if k[:2] == qkey]:
                self._spmd_cache.pop(k)
            for k in [k for k in self._shard_cache if k[0] == ds.name]:
                self._shard_cache.pop(k)

        return run_device_attempts(
            self, lambda: self._execute_groupby_once(q, ds), evict,
            what="mesh device",
        )

    def _route_strategy(self, q, ds, lowering, qkey) -> str:
        """Kernel-class choice for this query on the mesh — the identical
        calibrated model the single-device engine routes by (plan/cost.py),
        with this engine's decline memos applied."""
        from ..plan.cost import choose_query_kernel

        exclude: List[str] = []
        if qkey in self._adaptive_declined:
            exclude.append("adaptive")
        if qkey in self._sparse_declined:
            exclude.append("sparse")
        if self.strategy != "auto" and self.strategy not in exclude:
            return self.strategy
        strat = choose_query_kernel(
            q, ds, lowering.num_groups, self._cfg(), exclude=tuple(exclude)
        )
        if strat == "dense":
            # the cost model's "dense" is a kernel CLASS; the Pallas kernel
            # is its hand-scheduled TPU implementation (same upgrade rule as
            # Engine._resolve_strategy, but per-device: the groups axis
            # shrinks the one-hot domain to Gl)
            from ..ops.pallas_groupby import pallas_available

            _, Gl = self._groups_split(lowering.num_groups)
            if Gl <= SCATTER_CUTOVER and pallas_available():
                return "pallas"
        return strat

    def _execute_groupby_once(self, q: Q.GroupByQuery, ds: DataSource):
        from ..exec.lowering import memo_key
        from ..exec.metrics import QueryMetrics

        from ..resilience import (
            checkpoint, checkpoint_partial, current_partial, fire,
        )

        # deadline checkpoint + device-dispatch fault site: the SPMD path
        # honors the same lifecycle contract as the single-device engine.
        # With a partial collector armed, an expiry here must degrade to a
        # coverage-stamped best-effort answer (the arena path's chunk loop
        # stops before its first dispatch), not an error — the engine's
        # anytime-answer contract, now on the mesh.
        if current_partial() is None:
            checkpoint("mesh.dispatch")
        else:
            checkpoint_partial("mesh.dispatch")
        fire("device_dispatch")
        t_total = _time.perf_counter()
        lowering = self._lowering_for(q, ds)
        # learned-memo identity: segment-set independent (lowering.memo_key,
        # same contract as the local engine) so continuous streamed ingest
        # neither forgets learned rungs nor leaks one memo entry per append
        qkey = memo_key(q, ds)
        strategy = self._route_strategy(q, ds, lowering, qkey)
        m = QueryMetrics(
            query_type="groupBy",
            strategy=strategy,
            datasource=ds.name,
            query_id=current_query_id(),
            distributed=True,
            mesh_shape=tuple(self.mesh.shape.values()),
            rows_scanned=ds.num_rows,
            segments=len(ds.segments),
            num_groups=lowering.num_groups,
        )
        # metrics scope: what pruning WOULD scan (parity with the local
        # engine's numbers); shards themselves always span the full set
        from ..exec.engine import _bytes_scanned

        scope = self._scope_for_metrics(q, ds)
        m.rows_scanned = sum(sg.num_rows for sg in scope)
        m.bytes_scanned = _bytes_scanned(scope, lowering.columns)
        m.segments = len(scope)

        out = None
        try:
            if strategy == "adaptive":
                out = self._execute_adaptive(q, ds, lowering, qkey, m)
                if out is None:  # declined: re-route without adaptive
                    strategy = self._route_strategy(q, ds, lowering, qkey)
                    m.strategy = strategy
            if out is None and strategy == "sparse":
                out = self._execute_sparse(q, ds, lowering, qkey, m)
                if out is None:  # ladder exhausted: dense-state scatter
                    strategy = "segment"
                    m.strategy = strategy
            if out is None and strategy in (
                "dense", "pallas", "segment", "scatter",
            ):
                # unified SPMD-arena core (ISSUE 15): the stacked-layout
                # program with scope as data.  None => ineligible (layout
                # declined / sketch aggs / groups axis) — fall through to
                # the legacy dense-state path unchanged.
                out = self._execute_arena_spmd(q, ds, lowering, m, strategy)
            if out is None:
                out = self._execute_dense_state(q, ds, lowering, m, strategy)
        except BaseException as err:
            # failed executions must reach the process registry too: a
            # dashboard's outcome="error" rate would otherwise show zero
            # for the distributed path while counting single-device ones
            from ..resilience import DeadlineExceeded

            m.total_ms = (_time.perf_counter() - t_total) * 1e3
            m.bytes_resident = self._shard_cache.bytes_used
            if isinstance(err, DeadlineExceeded):
                m.deadline_exceeded = True
            self.last_metrics = m
            record_query_metrics(
                m,
                "deadline" if isinstance(err, DeadlineExceeded) else "error",
            )
            raise
        m.total_ms = (_time.perf_counter() - t_total) * 1e3
        m.bytes_resident = self._shard_cache.bytes_used
        self.last_metrics = m
        record_query_metrics(m, "ok")
        log.info("%s", m.describe())
        return out

    def _place_shards(self, ds, columns, m, q=None):
        """Place (or reuse) the sharded column set for `q`'s pruned scope
        — `q=None` spans the full set (scope-free callers only)."""
        from ..resilience import fire

        fire("h2d")  # fault-injection site: shard placement
        t0 = _time.perf_counter()
        known = len(self._shard_cache)
        before_bytes = self._shard_cache.bytes_used
        segs = self._scope_for_metrics(q, ds) if q is not None else None
        cols, padded = self._global_columns(ds, columns, segs=segs)
        if len(self._shard_cache) > known:  # new shards were placed
            from ..obs import prof

            dt = _time.perf_counter() - t0
            new_bytes = max(0, self._shard_cache.bytes_used - before_bytes)
            m.h2d_ms += dt * 1e3
            m.h2d_bytes += new_bytes
            # receipts parity with the single-device engine: the transfer
            # reaches the profiling scope's h2d accumulators, and the
            # per-shard split is recorded as a span event so mesh bench
            # artifacts are attribution-honest (ISSUE 15 satellite)
            prof.record_h2d(new_bytes, dt)
            nd = self.mesh.shape[DATA_AXIS]
            span_event(
                "shard_h2d", datasource=ds.name, bytes=new_bytes,
                per_shard_bytes=new_bytes // max(1, nd), shards=nd,
            )
        return cols, padded

    def _execute_dense_state(
        self, q, ds, lowering, m, strategy, key_extra=()
    ):
        """The dense-[Gl, M]-state path (dense / Pallas / scatter kernels
        share it — only the per-shard kernel differs)."""
        from ..plan.cost import groupby_state_bytes

        cols, padded = self._place_shards(ds, lowering.columns, m, q=q)
        local_rows = padded // self.mesh.shape[DATA_AXIS]
        compiled = self._spmd_cache
        key_count = len(compiled)
        run = self._spmd_fn(
            lowering, local_rows, ds, tuple(cols.keys()), strategy,
            key_extra=key_extra,
        )
        m.program_cache_hit = len(compiled) == key_count
        nd = self.mesh.shape[DATA_AXIS]
        m.est_collective_ms = (
            2.0 * (nd - 1) / nd
            * groupby_state_bytes(q, lowering.num_groups, None)
            / self._cfg().collective_bytes_per_us
            / 1e3
        )
        t0 = _time.perf_counter()
        # single host fetch (one round trip — see engine._execute_groupby)
        # under the collective-merge span: the fetch blocks on the SPMD
        # program, so this is where the ICI merge's wall time is paid
        with span(SPAN_COLLECTIVE_MERGE):
            from ..obs import prof

            t_call = _time.perf_counter()
            out_state = run(cols)
            # sampled query: split the collective span into enqueue vs
            # device-complete time before the blocking fetch (obs/prof.py)
            out_state = prof.dispatch_sync(out_state, t_call)
            sums, mins, maxs, sk = jax.device_get(out_state)
        dt = (_time.perf_counter() - t0) * 1e3
        if m.program_cache_hit:
            m.device_ms = dt
        else:  # first call: trace+compile dominates (metrics.py semantics)
            m.compile_ms = dt
            prof.note_compile(dt, family="dense-state")
        t0 = _time.perf_counter()
        with span(SPAN_FINALIZE):
            out = finalize_groupby(
                q,
                lowering.dims,
                lowering.la,
                np.asarray(sums),
                np.asarray(mins),
                np.asarray(maxs),
                {k: np.asarray(v) for k, v in sk.items()},
            )
        m.finalize_ms += (_time.perf_counter() - t0) * 1e3
        return out

    # -- sparse tier ---------------------------------------------------------

    def _initial_row_capacity(
        self, q, ds, lowering, qkey, local_rows
    ) -> Optional[int]:
        """Initial compaction rung from the planner's selectivity estimate
        with 2x headroom, per DEVICE (the distributed analog of
        exec/sparse_exec.py's per-segment rung); a remembered rung from a
        previous overflow wins.  None = full local sort."""
        from ..ops import sparse_groupby as _sg

        selective = q.filter is not None or bool(q.intervals)
        if not selective:
            return None
        if qkey in self._sparse_row_capacity:
            return self._sparse_row_capacity[qkey]
        from ..plan.cost import estimate_selectivity

        sel = (
            estimate_selectivity(q.filter, ds)
            if q.filter is not None
            else 1.0
        )
        if sel >= 1.0:
            return _sg.ROW_CAPACITY
        need = 2.0 * sel * local_rows
        return next(
            (c for c in _sg.ROW_CAPACITY_LADDER if c >= need), None
        )

    def _execute_sparse(self, q, ds, lowering, qkey, m):
        """Sparse sort-compaction over the mesh with the full rung ladder
        (row capacity + slots).  Returns None when the slots ladder is
        exhausted by an exact count — the caller falls back to the
        dense-state scatter path, and the decline is remembered."""
        from ..ops import sparse_groupby as _sg

        if lowering.la.sketch_aggs or not lowering.dims:
            # sparse states carry no sketch registers and need real dims
            # (same eligibility as exec/sparse_exec.py); an explicit
            # strategy="sparse" on such a query falls through to scatter
            self._sparse_declined.add(qkey)
            return None
        cols, padded = self._place_shards(ds, lowering.columns, m, q=q)
        local_rows = padded // self.mesh.shape[DATA_AXIS]
        cap = self._initial_row_capacity(q, ds, lowering, qkey, local_rows)
        slots = self._sparse_slots.get(qkey, _sg.SPARSE_SLOTS)
        compiled = self._spmd_cache
        key_count = len(compiled)
        t0 = _time.perf_counter()
        while True:
            run = self._spmd_sparse_fn(
                lowering, local_rows, ds, tuple(cols.keys()), slots, cap
            )
            # dispatch span: the mesh receipt's dispatch_count must count
            # sparse rungs like the single-device ladder does
            with span(SPAN_SPARSE_DISPATCH, slots=slots):
                state, flags = jax.device_get(run(cols))
            if cap is not None and bool(flags["row_overflow"].any()):
                n = int(flags["n_rows"].max())
                new_cap = next(
                    (
                        c
                        for c in _sg.ROW_CAPACITY_LADDER
                        if c >= n and c > cap
                    ),
                    None,
                )
                self._sparse_row_capacity[qkey] = new_cap
                log.info(
                    "mesh sparse row compaction overflowed %d of %d; "
                    "rerunning at %s",
                    n, cap,
                    "full-shard sort" if new_cap is None else new_cap,
                )
                cap = new_cap
                continue
            if bool(flags["overflow"].any()):
                n_est = int(flags["n_real"].max())
                new_slots = next(
                    (
                        s
                        for s in _sg.SLOTS_LADDER
                        if s >= n_est and s > slots
                    ),
                    None,
                )
                if new_slots is None:
                    # n_real can be a lower bound after a truncated merge
                    # (ADVICE r4): one rung at a time before giving up
                    new_slots = next(
                        (s for s in _sg.SLOTS_LADDER if s > slots), None
                    )
                if new_slots is None:
                    log.info(
                        "mesh sparse slots ladder exhausted at %d (~%d "
                        "distinct); falling back to scatter (remembered)",
                        slots, n_est,
                    )
                    self._sparse_declined.add(qkey)
                    return None
                self._sparse_slots[qkey] = new_slots
                log.info(
                    "mesh sparse slots overflowed (~%d distinct > %d); "
                    "rerunning at %d slots",
                    n_est, slots, new_slots,
                )
                slots = new_slots
                cap = self._sparse_row_capacity.get(qkey, cap)
                continue
            break
        m.program_cache_hit = len(compiled) == key_count
        if m.program_cache_hit:
            m.device_ms = (_time.perf_counter() - t0) * 1e3
        else:
            m.compile_ms = (_time.perf_counter() - t0) * 1e3
        t0 = _time.perf_counter()
        out = finalize_groupby(
            q,
            lowering.dims,
            lowering.la,
            np.asarray(state["sums"]),
            np.asarray(state["mins"]),
            np.asarray(state["maxs"]),
            {},
            slot_gids=np.asarray(state["gids"]),
        )
        m.finalize_ms += (_time.perf_counter() - t0) * 1e3
        return out

    # -- adaptive tier -------------------------------------------------------

    def _execute_adaptive(self, q, ds, lowering, qkey, m):
        """Adaptive dictionary-domain compaction as a distributed phase A
        (presence counts psum-merged over the data axis) + the normal SPMD
        program over the compacted lowering (phase B).  Returns None when
        declining — the caller re-routes among the remaining classes."""
        from ..exec.adaptive_exec import (
            ADAPTIVE_MAX_COMPACT_GROUPS,
            ADAPTIVE_MIN_SHRINK,
            compacted_lowering,
        )
        from ..exec.lowering import empty_partials
        from ..plan.cost import choose_kernel_strategy

        # measured kept sets are only valid for the segment set they
        # scanned (a fresh delta may hold codes the scan never saw —
        # reusing a stale set would silently drop those rows); derived
        # sets are supersets by construction and survive appends.  Same
        # entry shapes as the local AdaptiveDomainMixin.
        seg_sig = tuple(s.uid for s in ds.segments)
        entry = self._adaptive_kept.get(qkey)
        kept = None
        if entry is not None:
            if entry[0] == "derived":
                kept = entry[1]
            elif entry[1] == seg_sig:
                kept = entry[2]
        if kept is None:
            # dictionary-derived shortcut (shared with the local engine):
            # a filter that pins every grouping dim replaces the SPMD
            # presence pass with O(cardinality) host work
            from ..exec.adaptive_exec import filter_derived_kept

            kept = filter_derived_kept(q, lowering, ds)
            if kept is not None:
                self._adaptive_kept[qkey] = ("derived", kept)
        if kept is None:
            # phase A reads only mask + dim-code columns (the shared
            # helper keeps the physical time column when intervals need it)
            from ..exec.adaptive_exec import presence_columns

            need = presence_columns(q, lowering, ds)
            try:
                cols, padded = self._place_shards(ds, need, m, q=q)
                local_rows = padded // self.mesh.shape[DATA_AXIS]
                run = self._presence_fn(
                    lowering, local_rows, ds, tuple(cols.keys())
                )
                with span(SPAN_ADAPTIVE_PROBE):
                    counts = jax.device_get(run(cols))
            except RuntimeError:
                # transient device failures belong to execute()'s
                # evict-and-retry path, NOT a permanent decline (review r5)
                raise
            except Exception:
                log.warning(
                    "mesh adaptive presence pass failed; declining",
                    exc_info=True,
                )
                self._adaptive_declined.add(qkey)
                return None
            kept = [
                np.nonzero(np.asarray(c) > 0)[0].astype(np.int32)
                for c in counts
            ]
            self._adaptive_kept[qkey] = ("measured", seg_sig, kept)
        Gc = 1
        for kd in kept:
            Gc *= len(kd)
        if Gc > ADAPTIVE_MAX_COMPACT_GROUPS or (
            Gc > ADAPTIVE_MIN_SHRINK * lowering.num_groups
        ):
            log.info(
                "mesh adaptive compaction declined: G'=%d of G=%d",
                Gc, lowering.num_groups,
            )
            self._adaptive_declined.add(qkey)
            self._adaptive_kept.pop(qkey, None)
            return None
        if any(len(kd) == 0 for kd in kept):
            # some grouping dim has NO present code under the filter: the
            # exact result is the empty grouped frame
            la = lowering.la
            sums, mins, maxs, sketch_states = empty_partials(la, 0)
            return finalize_groupby(
                q, lowering.dims, la,
                np.asarray(sums), np.asarray(mins), np.asarray(maxs),
                {k: np.asarray(v) for k, v in sketch_states.items()},
            )
        clow = compacted_lowering(lowering, kept)
        cards = tuple(d.cardinality for d in clow.dims)
        # phase B kernel from the calibrated model at the COMPACTED
        # cardinality (the r4 engine bug class: a static resolver's dense
        # pick is a ~200x inversion on CPU backends)
        strat = choose_kernel_strategy(ds.num_rows, clow.num_groups, self._cfg())
        if strat == "dense":
            from ..ops.pallas_groupby import pallas_available

            _, Gl = self._groups_split(clow.num_groups)
            if Gl <= SCATTER_CUTOVER and pallas_available():
                strat = "pallas"
        m.num_groups = clow.num_groups
        return self._execute_dense_state(
            q, ds, clow, m, strat, key_extra=("adaptive",) + cards
        )

    # -- unified SPMD-arena core (ISSUE 15) ----------------------------------
    #
    # The stacked [B, R] arena layout (exec/arena.py) sharded over the row
    # devices is the ONE program both paths lower; the mesh contributes a
    # placement strategy (device-major permuted stacking) and a boundary
    # collective merge.  The scope rides as DATA (membership + window
    # start), so one compiled program serves every same-window-size scope.

    def _arena_mesh(self) -> Mesh:
        """The mesh the arena path shards rows over: the virtual
        multi-slice mesh when one was given, the flat data mesh
        otherwise."""
        return self.slice_mesh if self.slice_mesh is not None else self.mesh

    def _arena_mesh_key(self) -> Tuple:
        return tuple(sorted(self._arena_mesh().shape.items()))

    def _row_device_count(self) -> int:
        mesh = self._arena_mesh()
        return int(np.prod([mesh.shape[a] for a in row_axes(mesh)]))

    def _arena_layout(self, ds: DataSource):
        """The scope-independent stacked layout for `ds`, or None when
        the arena path must decline (toggle off, per-query disable,
        group-domain sharding, <2 segments, or non-uniform padded row
        counts)."""
        if not self.arena_execution:
            return None
        from ..exec import arena as _arena_mod

        if _arena_mod.query_disabled():
            return None
        if self.mesh.shape[GROUPS_AXIS] > 1:
            # the groups axis shards the gid domain — the arena program
            # folds full-domain states, so the legacy paths own that mesh
            return None
        return spmd_arena.plan_spmd_layout(ds, self._row_device_count())

    def _merge_tree_for(self, q, lowering) -> Tuple[str, float, float]:
        """(tree, flat_us, hier_us): the calibrated cost model's merge
        tree for this query's state size on this topology.  On the flat
        data mesh both trees coincide at ICI pricing and "flat" wins the
        tie — the single-program default."""
        from ..plan.cost import choose_merge_tree, groupby_state_bytes

        sbytes = groupby_state_bytes(q, lowering.num_groups, None)
        if self.slice_mesh is not None:
            ns = self.slice_mesh.shape[SLICE_AXIS]
            nd = self.slice_mesh.shape[DATA_AXIS]
        else:
            ns, nd = 1, self.mesh.shape[DATA_AXIS]
        return choose_merge_tree(sbytes, ns, nd, self._cfg())

    def _place_arena(self, ds: DataSource, layout, names, m):
        """Place (or reuse) the permuted [B_pad, R] column stacks.

        Keys carry the FULL segment signature and the row-device count —
        never a query's scope — so residency is durable across every
        query of the datasource version (the r4 #3 contract, now with
        program-cache generality on top).  Placement order is the PR 10
        prefetch plan ported per-device: resident stacks first (free
        cache hits), then cold stacks largest-first so the longest
        transfer issues earliest."""
        from ..exec.pipeline import placement_order
        from ..obs import prof
        from ..resilience import fire

        fire("h2d")  # fault-injection site: shard placement
        mesh = self._arena_mesh()
        row_el = spmd_arena._row_spec_axes(mesh)
        sharding = NamedSharding(mesh, P(row_el, None))
        base = (ds.name, "spmd_arena", layout.ndt, layout.uids)

        def ckey(name: str) -> Tuple:
            # "col"/"valid" tags: a user column literally named
            # "__valid" must not alias the validity stack (GL1301)
            if name == "__valid":
                return base + ("valid",)
            return base + ("col", name)

        def est_bytes(name: str) -> int:
            if name == "__valid":
                return layout.B_pad * layout.R  # bool stack
            proto = np.asarray(layout.segs[0].column(name))
            return layout.B_pad * layout.R * proto.dtype.itemsize

        want = list(dict.fromkeys(list(names) + ["__valid"]))
        order = placement_order(
            want, lambda n: self._shard_cache.get(ckey(n)) is not None,
            est_bytes,
        )
        t0 = _time.perf_counter()
        before = self._shard_cache.bytes_used
        cols: Dict[str, jax.Array] = {}
        placed = 0
        with span(
            SPAN_ARENA_BUILD, datasource=ds.name, blocks=layout.B,
            shards=layout.ndt,
        ):
            for name in order:
                key = ckey(name)
                hit = self._shard_cache.get(key)
                if hit is None:
                    host = spmd_arena.stack_column(layout, name)
                    hit = put_sharded(host, sharding)
                    self._shard_cache[key] = hit
                    placed += 1
                cols[name] = hit
        prof.note_residency(hit=placed == 0)
        if ds.time_column and ds.time_column in cols:
            cols["__time"] = cols[ds.time_column]
        if placed:
            dt = _time.perf_counter() - t0
            new_bytes = max(0, self._shard_cache.bytes_used - before)
            m.h2d_ms += dt * 1e3
            m.h2d_bytes += new_bytes
            prof.record_h2d(new_bytes, dt)
            span_event(
                "shard_h2d", datasource=ds.name, bytes=new_bytes,
                per_shard_bytes=new_bytes // max(1, layout.ndt),
                shards=layout.ndt, columns=placed,
            )
        return cols

    def prefetch(self, q: Q.QuerySpec, ds: DataSource) -> bool:
        """Warm the arena placement for `q` ahead of execution (the PR 10
        prefetch plan surfaced on the mesh): places the stacked column
        set in residency-aware order so a following execute() pays zero
        h2d.  Returns False when the query/datasource is not
        arena-eligible (nothing to warm)."""
        inner, _ = self._groupby_family(q, ds)
        if inner is None:
            return False
        inner = groupby_with_time_granularity(inner)
        lowering = self._lowering_for(inner, ds)
        layout = self._arena_layout(ds)
        if layout is None:
            return False
        from ..exec.metrics import QueryMetrics

        scratch = QueryMetrics(query_type="prefetch")
        self._place_arena(ds, layout, lowering.columns, scratch)
        return True

    def _arena_spmd_fn(self, lowering, ds, layout, Lk, strategy, tree):
        """The cached single-dispatch unified program.  The key carries
        the window LENGTH `Lk` but never the scope itself — two disjoint
        scopes of equal rounded size share one compiled program."""
        from ..exec.lowering import _query_key
        from ..obs import prof

        # literal tag at the same tuple position as the legacy families
        # ("dense-state"/"sparse"/...) so no key can alias across
        # families sharing _spmd_cache (GL1301)
        cache_key = _query_key(lowering.query, ds) + (
            layout.L,
            self._arena_mesh_key(),
            "arena-spmd", layout.R, Lk, strategy, tree,
        )
        if cache_key in self._spmd_cache:
            prof.note_program_cache("arena-spmd", hit=True)
            return self._spmd_cache[cache_key]
        prof.note_program_cache("arena-spmd", hit=False)
        run = spmd_arena.build_spmd_arena_program(
            self._arena_mesh(), [lowering], [strategy], Lk, tree=tree
        )
        self._spmd_cache[cache_key] = run
        return run

    def _arena_chunk_fn(self, lowering, ds, layout, strategy):
        from ..exec.lowering import _query_key
        from ..obs import prof

        cache_key = _query_key(lowering.query, ds) + (
            layout.L,
            self._arena_mesh_key(),
            "arena-spmd-chunk", layout.R, strategy,
        )
        if cache_key in self._spmd_cache:
            prof.note_program_cache("arena-spmd-chunk", hit=True)
            return self._spmd_cache[cache_key]
        prof.note_program_cache("arena-spmd-chunk", hit=False)
        run = spmd_arena.build_spmd_chunk_program(
            self._arena_mesh(), [lowering], [strategy]
        )
        self._spmd_cache[cache_key] = run
        return run

    def _arena_merge_fn(self, lowering, ds, tree):
        from ..exec.lowering import _query_key
        from ..obs import prof

        cache_key = _query_key(lowering.query, ds) + (
            0,
            self._arena_mesh_key(),
            "arena-spmd-merge", tree,
        )
        if cache_key in self._spmd_cache:
            prof.note_program_cache("arena-spmd-merge", hit=True)
            return self._spmd_cache[cache_key]
        prof.note_program_cache("arena-spmd-merge", hit=False)
        run = spmd_arena.build_spmd_merge_program(
            self._arena_mesh(), [lowering], tree=tree
        )
        self._spmd_cache[cache_key] = run
        return run

    def _slice_count(self) -> int:
        return (
            self.slice_mesh.shape[SLICE_AXIS]
            if self.slice_mesh is not None
            else 1
        )

    def _execute_arena_spmd(self, q, ds, lowering, m, strategy):
        """The unified executor core on the mesh: ONE dispatch folds the
        scope inside the trace and merges at the boundary.  Returns None
        to decline (caller falls through to the legacy dense-state
        path)."""
        layout = self._arena_layout(ds)
        if layout is None or lowering.la.sketch_aggs:
            return None
        from ..exec.engine import _row_counts
        from ..exec.lowering import empty_partials
        from ..obs import prof
        from ..resilience import current_deadline, current_partial

        la, G = lowering.la, lowering.num_groups
        pc = current_partial()
        scope = self._scope_for_metrics(q, ds)
        if not scope:
            if pc is not None:
                pc.begin_pass()
                pc.add_scope(0, 0)
            sums, mins, maxs, _sk = jax.device_get(empty_partials(la, G))
        else:
            canonical = sorted(layout.index[s.uid] for s in scope)
            j_lo, Lk = spmd_arena.scope_window(layout, canonical)
            memb = spmd_arena.membership_matrix(layout, [canonical])
            tree, flat_us, hier_us = self._merge_tree_for(q, lowering)
            m.est_collective_ms = min(flat_us, hier_us) / 1e3
            cols = self._place_arena(ds, layout, lowering.columns, m)
            rows, delta = _row_counts(scope)
            if pc is not None:
                pc.begin_pass()
                pc.add_scope(len(scope), rows, delta)
            if current_deadline() is None:
                compiled = self._spmd_cache
                key_count = len(compiled)
                run = self._arena_spmd_fn(
                    lowering, ds, layout, Lk, strategy, tree
                )
                m.program_cache_hit = len(compiled) == key_count
                t0 = _time.perf_counter()
                # single dispatch + single fetch under the collective-
                # merge span: the receipt's dispatch_count is 1 per query
                with span(
                    SPAN_COLLECTIVE_MERGE, merge_tree=tree,
                    shards=layout.ndt, window=Lk,
                ):
                    span_event(
                        "merge_tree", tree=tree,
                        flat_us=round(flat_us, 3),
                        hier_us=round(hier_us, 3),
                        shards=layout.ndt, slices=self._slice_count(),
                    )
                    t_call = _time.perf_counter()
                    out_state = run(cols, np.int32(j_lo), memb)
                    out_state = prof.dispatch_sync(out_state, t_call)
                    sums, mins, maxs, _live = jax.device_get(out_state[0])
                dt = (_time.perf_counter() - t0) * 1e3
                if m.program_cache_hit:
                    m.device_ms = dt
                else:
                    m.compile_ms = dt
                    prof.note_compile(dt, family="arena-spmd")
                if pc is not None:
                    pc.add_seen(len(scope), rows, delta)
            else:
                sums, mins, maxs = self._arena_spmd_deadline(
                    ds, lowering, m, strategy, layout, cols, memb,
                    canonical, j_lo, Lk, tree, pc,
                )
        # result-cache state capture: the merged host partial state from
        # the collective — never a deadline-truncated one
        holder = getattr(self._m_local, "capture", None)
        if holder is not None and (pc is None or not pc.triggered):
            holder["state"] = self._pack_state(sums, mins, maxs)
        t0 = _time.perf_counter()
        with span(SPAN_FINALIZE):
            out = finalize_groupby(
                q, lowering.dims, la,
                np.asarray(sums), np.asarray(mins), np.asarray(maxs), {},
            )
        m.finalize_ms += (_time.perf_counter() - t0) * 1e3
        return out

    def _arena_spmd_deadline(
        self, ds, lowering, m, strategy, layout, cols, memb, canonical,
        j_lo, Lk, tree, pc,
    ):
        """Deadline partials on the unified core: per-shard stop-and-merge.
        The chunk loop folds one local step per dispatch into a
        row-sharded carry; a truncation lands on a step boundary, the
        merge program runs the boundary collectives over whatever was
        folded, and coverage is accounted host-side — local step `j`
        covers exactly the canonical blocks {j*ndt + d}, summed across
        shards."""
        from ..exec.engine import _row_counts
        from ..obs import prof
        from ..resilience import checkpoint_partial, fire

        ndt = layout.ndt
        compiled = self._spmd_cache
        key_count = len(compiled)
        step_fn = self._arena_chunk_fn(lowering, ds, layout, strategy)
        merge_fn = self._arena_merge_fn(lowering, ds, tree)
        m.program_cache_hit = len(compiled) == key_count
        carry = spmd_arena.init_carry_stacked(self._arena_mesh(), [lowering])
        by_step: Dict[int, List] = {}
        for b in canonical:
            by_step.setdefault(b // ndt, []).append(layout.segs[b])
        t0 = _time.perf_counter()
        for j in range(j_lo, j_lo + Lk):
            if checkpoint_partial("mesh.segment_loop"):
                break
            fire("device_dispatch")
            with span(
                SPAN_SEGMENT_DISPATCH, arena=1, chunk=j - j_lo,
                shards=ndt,
            ):
                t_call = _time.perf_counter()
                carry = step_fn(carry, cols, np.int32(j), memb)
                carry = prof.dispatch_sync(carry, t_call)
            if pc is not None:
                segs_j = by_step.get(j, [])
                rows_j, delta_j = _row_counts(segs_j)
                pc.add_seen(len(segs_j), rows_j, delta_j)
        with span(SPAN_COLLECTIVE_MERGE, merge_tree=tree, shards=ndt):
            sums, mins, maxs, _live = jax.device_get(merge_fn(carry)[0])
        dt = (_time.perf_counter() - t0) * 1e3
        if m.program_cache_hit:
            m.device_ms = dt
        else:
            m.compile_ms = dt
            prof.note_compile(dt, family="arena-spmd-chunk")
        return sums, mins, maxs

    @staticmethod
    def _pack_state(sums, mins, maxs, sketches=None) -> Dict:
        """Host partial-state dict in the result cache's schema — the
        engine's canonical packing, so mesh- and single-device-produced
        states are interchangeable under merge/finalize."""
        from ..exec.engine import _pack_host_state

        return _pack_host_state(sums, mins, maxs, sketches)

    # -- host partial-state surface (delta-aware result cache) ---------------

    def _groupby_family(self, q: Q.QuerySpec, ds: DataSource):
        """GroupBy-family normalization, shared shape with the local
        engine (exec.engine.Engine._groupby_family)."""
        if isinstance(q, Q.TimeseriesQuery):
            return (
                timeseries_to_groupby(q),
                lambda df: finalize_timeseries(df, q, ds),
            )
        if isinstance(q, Q.TopNQuery):
            return topn_to_groupby(q), lambda df: finalize_topn(df, q)
        if isinstance(q, Q.GroupByQuery):
            return q, lambda df: df
        return None, None

    @contextlib.contextmanager
    def state_capture(self):
        """Capture the merged HOST partial state of the next execution on
        this thread (the arena path stashes it just before finalize).
        Yields a dict whose "state" key holds the capture — None when the
        execution declined to the legacy paths or was deadline-truncated
        (a partial state must never seed the delta-aware result
        cache)."""
        holder = {"state": None}
        self._m_local.capture = holder
        try:
            yield holder
        finally:
            self._m_local.capture = None

    def groupby_partials_host(
        self, q: Q.QuerySpec, ds: DataSource, within_uids=None
    ):
        """Merged HOST partial state over the in-scope segments whose uid
        is in `within_uids` (None = the full scope) — the delta-reuse
        entry point, same contract as the local engine's.  Membership is
        data, so the delta scan is the SAME compiled program folding only
        the fresh blocks.  Raises ValueError when the query/datasource
        cannot produce mesh partial state (callers treat it as a cache
        decline)."""
        from ..exec.lowering import empty_partials, memo_key

        inner, _ = self._groupby_family(q, ds)
        if inner is None:
            raise ValueError(f"{type(q).__name__} has no partial state")
        inner = groupby_with_time_granularity(inner)
        lowering = self._lowering_for(inner, ds)
        layout = self._arena_layout(ds)
        if layout is None or lowering.la.sketch_aggs:
            raise ValueError(
                "query/datasource is not SPMD-arena eligible on the mesh"
            )
        strategy = self._route_strategy(
            inner, ds, lowering, memo_key(inner, ds)
        )
        if strategy in ("sparse", "adaptive"):
            raise ValueError(
                f"{strategy} tier has no mergeable mesh partial state"
            )
        segs = self._scope_for_metrics(inner, ds)
        if within_uids is not None:
            w = frozenset(within_uids)
            segs = [s for s in segs if s.uid in w]
        la, G = lowering.la, lowering.num_groups
        if not segs:
            sums, mins, maxs, _sk = jax.device_get(empty_partials(la, G))
        else:
            from ..exec.metrics import QueryMetrics

            scratch = QueryMetrics(query_type="partials")
            canonical = sorted(layout.index[s.uid] for s in segs)
            j_lo, Lk = spmd_arena.scope_window(layout, canonical)
            memb = spmd_arena.membership_matrix(layout, [canonical])
            tree, _f, _h = self._merge_tree_for(inner, lowering)
            cols = self._place_arena(ds, layout, lowering.columns, scratch)
            run = self._arena_spmd_fn(lowering, ds, layout, Lk, strategy, tree)
            with span(SPAN_COLLECTIVE_MERGE, merge_tree=tree, partials=1):
                sums, mins, maxs, _live = jax.device_get(
                    run(cols, np.int32(j_lo), memb)[0]
                )
        state = self._pack_state(sums, mins, maxs)
        return state, sum(s.num_rows for s in segs)

    def merge_groupby_states(self, q: Q.QuerySpec, ds: DataSource, a, b):
        """⊕ of two host partial states of the SAME query (the
        partial-aggregate-state algebra, identical to the local
        engine's).  Raises ValueError on a shape mismatch (dictionary
        domain changed — callers treat it as a cache miss)."""
        from ..exec.engine import _merge_sketch_states

        if a["sums"].shape != b["sums"].shape:
            raise ValueError(
                f"partial-state shape mismatch {a['sums'].shape} vs "
                f"{b['sums'].shape} (dictionary domain changed)"
            )
        inner, _ = self._groupby_family(q, ds)
        lowering = self._lowering_for(
            groupby_with_time_granularity(inner), ds
        )
        merged = {
            "sums": a["sums"] + b["sums"],
            "mins": np.minimum(a["mins"], b["mins"]),
            "maxs": np.maximum(a["maxs"], b["maxs"]),
            "sketches": dict(a["sketches"]),
        }
        _merge_sketch_states(lowering.la, merged["sketches"], b["sketches"])
        merged["sketches"] = {
            k: np.asarray(v) for k, v in merged["sketches"].items()
        }
        return merged

    def finalize_groupby_state(self, q: Q.QuerySpec, ds: DataSource, state):
        """Host partial state -> the query's final result frame (the same
        finalize the live mesh execution runs)."""
        inner, shape = self._groupby_family(q, ds)
        inner = groupby_with_time_granularity(inner)
        lowering = self._lowering_for(inner, ds)
        with span(SPAN_FINALIZE):
            df = finalize_groupby(
                inner, lowering.dims, lowering.la,
                np.asarray(state["sums"]),
                np.asarray(state["mins"]),
                np.asarray(state["maxs"]),
                {k: np.asarray(v) for k, v in state["sketches"].items()},
            )
        return shape(df)

    # -- micro-batch fusion on the shared arena ------------------------------

    def fusable(self, q: Q.QuerySpec, ds: DataSource) -> bool:
        """May this query join a fused micro-batch on the mesh?  Same
        surface as the local engine's: GroupBy-family, no wire subtotals,
        and the unified arena program can host it (no sketches, no
        sparse/adaptive tier, layout eligible)."""
        inner, _ = self._groupby_family(q, ds)
        if inner is None or inner.subtotals:
            return False
        try:
            inner = groupby_with_time_granularity(inner)
            lowering = self._lowering_for(inner, ds)
        except Exception:  # fault-ok: an unlowerable query declines fusion
            return False
        if lowering.la.sketch_aggs:
            return False
        from ..exec.lowering import memo_key

        strategy = self._route_strategy(
            inner, ds, lowering, memo_key(inner, ds)
        )
        if strategy in ("sparse", "adaptive"):
            return False
        return self._arena_layout(ds) is not None

    def _arena_spmd_fused_fn(self, members, ds, layout, Lk, strategies, tree):
        """The fused unified program: every member's fold inside ONE
        sharded scan, membership as data (one compiled program serves
        any member->scope mapping of the same window size)."""
        import json as _json

        from ..exec.lowering import _query_key
        from ..obs import prof

        cache_key = _query_key(members[0][1], ds) + (
            layout.L,
            self._arena_mesh_key(),
            "arena-spmd-fused",
            tuple(
                _json.dumps(mm[1].to_druid(), sort_keys=True, default=str)
                for mm in members[1:]
            ),
            strategies, layout.R, Lk, tree,
        )
        if cache_key in self._spmd_cache:
            prof.note_program_cache("arena-spmd-fused", hit=True)
            return self._spmd_cache[cache_key]
        prof.note_program_cache("arena-spmd-fused", hit=False)
        from ..serve.fusion import shared_row_plan

        share = shared_row_plan([mm[1] for mm in members])
        run = spmd_arena.build_spmd_arena_program(
            self._arena_mesh(), [mm[3] for mm in members], list(strategies),
            Lk, tree=tree, share=share,
        )
        self._spmd_cache[cache_key] = run
        return run

    def execute_fused(self, queries, ds: DataSource, query_ids=None):
        """Execute N compatible GroupBy-family queries as ONE unified
        arena dispatch: members share the sharded arena via the
        membership scan input, every member's fold runs inside the same
        program, and ONE host fetch returns all merged states.  Same
        (df, state, metrics) contract as the local engine's
        execute_fused; an ineligible batch falls back to serial
        per-member execution (state still captured)."""
        from ..exec.lowering import empty_partials, memo_key
        from ..exec.metrics import QueryMetrics
        from ..obs import prof
        from ..resilience import checkpoint, fire

        t0_all = _time.perf_counter()
        n = len(queries)
        query_ids = list(query_ids or [""] * n)
        members = []
        for q in queries:
            inner, shape = self._groupby_family(q, ds)
            if inner is None:
                raise ValueError(
                    f"{type(q).__name__} is not fusable (GroupBy-family "
                    "queries only)"
                )
            inner = groupby_with_time_granularity(inner)
            lowering = self._lowering_for(inner, ds)
            segs = self._scope_for_metrics(inner, ds)
            members.append((q, inner, shape, lowering, segs))
        layout = self._arena_layout(ds)
        strategies = tuple(
            self._route_strategy(mm[1], ds, mm[3], memo_key(mm[1], ds))
            for mm in members
        )
        if (
            layout is None
            or any(mm[3].la.sketch_aggs for mm in members)
            or any(s in ("sparse", "adaptive") for s in strategies)
        ):
            return self._execute_fused_serial(queries, ds, query_ids)
        prof.note_fusion(n)
        checkpoint("engine.fused_loop")  # fused deadline contract
        fire("device_dispatch")
        member_scopes = [
            sorted(layout.index[s.uid] for s in mm[4]) for mm in members
        ]
        all_blocks = sorted({b for sc in member_scopes for b in sc})
        batch_m = QueryMetrics(query_type="fused")
        states = None
        tree = "flat"
        if all_blocks:
            j_lo, Lk = spmd_arena.scope_window(layout, all_blocks)
            memb = spmd_arena.membership_matrix(layout, member_scopes)
            tree, flat_us, hier_us = self._merge_tree_for(
                members[0][1], members[0][3]
            )
            names = list(
                dict.fromkeys(c for mm in members for c in mm[3].columns)
            )
            cols = self._place_arena(ds, layout, names, batch_m)
            compiled = self._spmd_cache
            key_count = len(compiled)
            fn = self._arena_spmd_fused_fn(
                members, ds, layout, Lk, strategies, tree
            )
            batch_m.program_cache_hit = len(compiled) == key_count
            t0 = _time.perf_counter()
            with span(
                SPAN_COLLECTIVE_MERGE, merge_tree=tree, fused=n,
                shards=layout.ndt, window=Lk,
            ):
                span_event(
                    "merge_tree", tree=tree, flat_us=round(flat_us, 3),
                    hier_us=round(hier_us, 3), shards=layout.ndt,
                    slices=self._slice_count(), fused=n,
                )
                t_call = _time.perf_counter()
                outs = fn(cols, np.int32(j_lo), memb)
                outs = prof.dispatch_sync(outs, t_call)
                # ONE fetch for the whole batch — the round trip the
                # fused dispatch exists to amortize
                states = jax.device_get(outs)
            dt = (_time.perf_counter() - t0) * 1e3
            if batch_m.program_cache_hit:
                batch_m.device_ms = dt
            else:
                batch_m.compile_ms = dt
                prof.note_compile(dt, family="arena-spmd-fused")
        # empty-scope members in ONE host fetch before the demux loop
        # (GL204: no per-member device round trips while demuxing)
        empties = jax.device_get({
            i: empty_partials(mm[3].la, mm[3].num_groups)
            for i, mm in enumerate(members)
            if states is None or not member_scopes[i]
        })
        out = []
        elapsed_ms = (_time.perf_counter() - t0_all) * 1e3
        from ..exec.engine import _bytes_scanned, _row_counts

        for i, (q, inner, shape, lowering, segs) in enumerate(members):
            la, G = lowering.la, lowering.num_groups
            if i in empties:
                # empty scope: dead-shard identities ARE empty_partials,
                # but skip the device state entirely when nothing ran
                sums, mins, maxs, _sk = empties[i]
            else:
                sums, mins, maxs, _live = states[i]
            state = self._pack_state(sums, mins, maxs)
            with span(SPAN_FINALIZE, member=i):
                df = shape(finalize_groupby(
                    inner, lowering.dims, la,
                    state["sums"], state["mins"], state["maxs"],
                    state["sketches"],
                ))
            try:
                qt = q.to_druid().get("queryType", type(q).__name__)
            except Exception:  # fault-ok: metrics labeling only
                qt = type(q).__name__
            rows, _delta = _row_counts(segs)
            mm = QueryMetrics(
                query_type=qt,
                strategy=strategies[i],
                datasource=ds.name,
                query_id=query_ids[i],
                distributed=True,
                mesh_shape=tuple(self.mesh.shape.values()),
                rows_scanned=rows,
                bytes_scanned=_bytes_scanned(segs, lowering.columns),
                segments=len(segs),
                num_groups=G,
                # the batch's shared h2d/compile split evenly: ONE
                # stacked column set moved for all members
                h2d_bytes=batch_m.h2d_bytes // n,
                h2d_ms=batch_m.h2d_ms / n,
                compile_ms=batch_m.compile_ms,
                total_ms=elapsed_ms,
                fused_batch=n,
                program_cache_hit=batch_m.program_cache_hit,
            )
            record_query_metrics(mm, "ok")
            out.append((df, state, mm))
        self.last_metrics = out[-1][2] if out else None
        return out

    def _execute_fused_serial(self, queries, ds, query_ids):
        """Fallback for an arena-ineligible batch: serial per-member
        execution under state capture — the same (df, state, metrics)
        tuple contract, minus the shared dispatch."""
        out = []
        for q, qid in zip(queries, query_ids):
            with self.state_capture() as cap:
                df = self.execute(q, ds)
            mm = self.last_metrics
            if mm is not None and qid:
                mm.query_id = qid
            out.append((df, cap["state"], mm))
        return out
