"""Distributed GroupBy execution: shard_map over a device mesh + ICI merge.

Reference parity: this is the Druid **broker scatter-gather** rebuilt on XLA
collectives (SURVEY.md §2 parallelism table, §3.3 `[U]`).  In the reference,
the broker fans a query to historicals, each computes per-segment partial
aggregates, and the broker merges partials (sum-merge, min/max-merge, HLL
register-max, sketch union).  Here:

* historicals  → mesh devices, each holding a row shard in HBM
* HTTP fan-out → `shard_map` over the ``data`` axis (one traced program, SPMD)
* broker merge → `lax.psum` (sums/counts), `lax.pmin`/`pmax` (extrema, HLL
  registers), `all_gather` + KMV-union fold (theta) — riding ICI, with DCN
  handled transparently by the same collectives on multi-host meshes
* Spark-side final merge → `exec.engine.finalize_groupby` on the replicated
  [G, M] state (tiny)

The ``groups`` mesh axis additionally shards the group-id domain (the
TP-analog): each device matches only its slice of [0, G), shrinking the
one-hot block and sketch states by the axis size; no collective is needed on
that axis — outputs stay group-sharded until the host gathers them.

Long-context analog (SURVEY.md §5): rows are the "sequence" axis.  Blockwise
partial aggregation over row chunks + ring/allreduce merge of aggregate state
is the same communication shape ring-attention uses for KV blocks — scaling
group-by past one chip's HBM without materializing anything global.
"""

from __future__ import annotations

import functools
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..catalog.segment import ROW_PAD, DataSource
from ..models.dimensions import DimensionSpec
from ..exec.engine import (
    GroupByLowering,
    _prune_by_stats,
    finalize_groupby,
    finalize_timeseries,
    finalize_topn,
    groupby_with_time_granularity,
    lower_groupby,
    schema_signature,
    timeseries_to_groupby,
    topn_to_groupby,
)
from ..models import aggregations as A
from ..models import query as Q
from ..ops import hll as hll_ops
from ..ops import quantiles as quantiles_ops
from ..ops import theta as theta_ops
from ..ops.groupby import choose_block_rows, dense_partial_aggregate
from .mesh import DATA_AXIS, GROUPS_AXIS, make_mesh
from .multihost import put_sharded


class DistributedEngine:
    """Executes GroupBy-family queries SPMD over a mesh.

    Row shards are built host-side by concatenating segment columns and
    padding to a multiple of (mesh data size × ROW_PAD); `jax.device_put`
    with a NamedSharding places each shard in its device's HBM (streaming /
    residency caching mirrors the local engine and will move to the async
    ingest path of catalog/ingest.py)."""

    def __init__(
        self,
        mesh: Optional[Mesh] = None,
        shard_cache_bytes: int = 4 << 30,
        program_cache_entries: int = 128,
    ):
        from ..utils.lru import ByteBudgetCache, CountBudgetCache

        self.mesh = mesh if mesh is not None else make_mesh()
        self.last_metrics = None  # observability (exec/metrics.py)
        # row-shard cache: keyed by the exact segment set the shard was built
        # from (interval pruning changes the set => different global layout);
        # LRU under a byte budget (VERDICT r1 weak #7)
        self._shard_cache = ByteBudgetCache(shard_cache_bytes)
        # compiled SPMD program cache (query shape x schema x local rows);
        # without it every execute() re-traces and re-compiles the shard_map
        self._spmd_cache = CountBudgetCache(program_cache_entries)
        # lowering cache: rebuilding a lowering stages device constants
        # (dictionary remaps, bucket tables) — one blocking H2D per constant
        # on every execution without it (same as exec/engine.py)
        self._lowering_cache = CountBudgetCache(program_cache_entries)

    def _lowering_for(self, q: Q.GroupByQuery, ds: DataSource):
        from ..exec.lowering import cached_lowering

        return cached_lowering(self._lowering_cache, q, ds)

    # -- host-side row-shard assembly ---------------------------------------

    def _global_columns(
        self, ds: DataSource, names, intervals, filt=None,
        vcol_names=frozenset(),
    ):
        nd = self.mesh.shape[DATA_AXIS]
        segs = list(ds.segments)
        if intervals:
            segs = [
                s
                for s in segs
                if s.interval is None
                or any(a <= s.interval[1] and s.interval[0] < b
                       for a, b in intervals)
            ]
        if filt is not None and segs:
            # zone-map pruning, same conservative rules as the local
            # engine.  NOTE: each distinct pruned set keys its own shard
            # layout and SPMD compile (the precedent interval pruning set);
            # the byte-budget LRU bounds residency if filters churn
            segs = _prune_by_stats(segs, filt, ds, vcol_names)
        total = sum(s.num_rows_padded for s in segs)
        chunk = nd * ROW_PAD
        padded = -(-max(total, 1) // chunk) * chunk
        sharding = NamedSharding(self.mesh, P(DATA_AXIS))
        seg_sig = tuple(s.uid for s in segs)

        def build(name: str, fill) -> jax.Array:
            key = (ds.name, name, nd, seg_sig)
            hit = self._shard_cache.get(key)
            if hit is not None:
                return hit
            parts = [np.asarray(s.column(name)) for s in segs]
            host = np.concatenate(parts) if parts else np.zeros(0)
            if len(host) < padded:
                host = np.concatenate(
                    [host, np.full(padded - len(host), fill, dtype=host.dtype)]
                )
            arr = put_sharded(host, sharding)
            self._shard_cache[key] = arr
            return arr

        cols: Dict[str, jax.Array] = {}
        for n in names:
            fill = -1 if n in ds.dicts else 0
            cols[n] = build(n, fill)
        vkey = (ds.name, "__valid", nd, seg_sig)
        valid = self._shard_cache.get(vkey)
        if valid is None:
            parts = [s.valid for s in segs]
            host = (
                np.concatenate(parts) if parts else np.zeros(0, dtype=bool)
            )
            if len(host) < padded:
                host = np.concatenate(
                    [host, np.zeros(padded - len(host), dtype=bool)]
                )
            valid = put_sharded(host, sharding)
            self._shard_cache[vkey] = valid
        cols["__valid"] = valid
        if ds.time_column and ds.time_column in cols:
            cols["__time"] = cols[ds.time_column]
        return cols, padded, segs

    def clear_cache(self):
        self._shard_cache.clear()
        self._lowering_cache.clear()
        self._spmd_cache.clear()

    # -- SPMD program --------------------------------------------------------

    def _spmd_fn(self, lowering: GroupByLowering, local_rows: int,
                 ds: DataSource, col_keys: Tuple[str, ...]):
        """Build (or fetch) the compiled SPMD program for this lowering.

        Cached on (query shape, schema signature, local rows, mesh shape):
        jit's compilation cache is keyed on callable identity, so rebuilding
        the closure per query would recompile every time."""
        from ..exec.lowering import _query_key

        cache_key = _query_key(lowering.query, ds) + (
            local_rows,
            tuple(sorted(self.mesh.shape.items())),
        )
        if cache_key in self._spmd_cache:
            return self._spmd_cache[cache_key]
        G = lowering.num_groups
        la = lowering.la
        ng = self.mesh.shape[GROUPS_AXIS]
        if G % ng:
            ng = 1  # group axis must divide G; fall back to replicated groups
        Gl = G // max(ng, 1)
        num_min, num_max = len(la.min_names), len(la.max_names)
        sketches = list(la.sketch_aggs)
        block = choose_block_rows(local_rows, Gl)
        while local_rows % block:
            block -= ROW_PAD
        block = max(block, ROW_PAD)

        def shard_fn(cols: Dict[str, jax.Array]):
            gid, mask, sv, mmv, mmm = lowering.row_arrays(cols)
            if ng > 1:
                off = lax.axis_index(GROUPS_AXIS).astype(jnp.int32) * Gl
                gid_l = gid - off  # ids outside [0, Gl) never match the iota
            else:
                gid_l = gid
            sums, mins, maxs = dense_partial_aggregate(
                gid_l, mask, sv, mmv, mmm,
                num_groups=Gl, block_rows=block,
                num_min=num_min, num_max=num_max,
            )
            # broker-merge over the data axis (ICI collectives)
            sums = lax.psum(sums, DATA_AXIS)
            if num_min:
                mins = lax.pmin(mins, DATA_AXIS)
            if num_max:
                maxs = lax.pmax(maxs, DATA_AXIS)
            sk_out = {}
            for agg in sketches:
                # per-agg FILTER mask composes with the row mask (same
                # contract as the local engine's sketch partials)
                mfn = la.mask_fns.get(agg.name)
                amask = mask & mfn(cols) if mfn is not None else mask
                if isinstance(agg, (A.HyperUnique, A.CardinalityAgg)):
                    st = hll_ops.partial_hll(agg, cols, gid_l, amask, Gl)
                    sk_out[agg.name] = lax.pmax(st, DATA_AXIS)
                elif isinstance(agg, A.QuantilesSketch):
                    st = quantiles_ops.partial_quantiles(
                        agg, cols, gid_l, amask, Gl
                    )
                    gathered = lax.all_gather(st, DATA_AXIS)  # [nd,Gl,K+1,2]
                    acc = gathered[0]
                    for i in range(1, gathered.shape[0]):
                        acc = quantiles_ops.merge_states(
                            acc, gathered[i], agg.size
                        )
                    sk_out[agg.name] = acc
                else:
                    st = theta_ops.partial_theta(agg, cols, gid_l, amask, Gl)
                    gathered = lax.all_gather(st, DATA_AXIS)  # [nd, Gl, K]
                    acc = gathered[0]
                    for i in range(1, gathered.shape[0]):
                        acc = theta_ops.merge_states(acc, gathered[i], agg.size)
                    sk_out[agg.name] = acc
            return sums, mins, maxs, sk_out

        specs = {n: P(DATA_AXIS) for n in col_keys}
        gspec = P(GROUPS_AXIS) if ng > 1 else P()
        out_spec = (gspec, gspec, gspec, {a.name: gspec for a in sketches})
        run = jax.jit(
            jax.shard_map(
                shard_fn,
                mesh=self.mesh,
                in_specs=(specs,),
                out_specs=out_spec,
                check_vma=False,
            )
        )
        self._spmd_cache[cache_key] = run
        return run

    # -- entry points --------------------------------------------------------

    def execute(self, q: Q.QuerySpec, ds: DataSource):
        # Timeseries/TopN rewrites + finalization are shared with the local
        # engine (exec/engine.py) so distributed semantics cannot drift.
        if isinstance(q, Q.TimeseriesQuery):
            df = self.execute(timeseries_to_groupby(q), ds)
            return finalize_timeseries(df, q, ds)
        if isinstance(q, Q.TopNQuery):
            df = self.execute(topn_to_groupby(q), ds)
            return finalize_topn(df, q)
        assert isinstance(q, Q.GroupByQuery), type(q)
        # idempotent re-dispatch on transient device failure, mirroring
        # exec/engine.py (queries are read-only; SURVEY.md §5 failure row)
        q = groupby_with_time_granularity(q)
        try:
            return self._execute_groupby_once(q, ds)
        except NotImplementedError:
            raise
        except RuntimeError as err:
            from ..utils.log import get_logger

            get_logger("parallel.distributed").warning(
                "transient device failure (%s: %s); evicting shards and "
                "re-dispatching once",
                type(err).__name__,
                err,
            )
            from ..exec.lowering import _query_key

            qkey = _query_key(q, ds)
            self._lowering_cache.pop(qkey)
            # spmd keys are _query_key + (local_rows, mesh): evict only this
            # query's programs, not every cached query's compile
            for k in [k for k in self._spmd_cache if k[:2] == qkey]:
                self._spmd_cache.pop(k)
            for k in [k for k in self._shard_cache if k[0] == ds.name]:
                self._shard_cache.pop(k)
            return self._execute_groupby_once(q, ds)

    def _execute_groupby_once(self, q: Q.GroupByQuery, ds: DataSource):
        import time as _time

        from ..config import SessionConfig
        from ..exec.metrics import QueryMetrics
        from ..plan.cost import groupby_state_bytes

        t_total = _time.perf_counter()

        lowering = self._lowering_for(q, ds)
        m = QueryMetrics(
            query_type="groupBy",
            strategy="dense",
            distributed=True,
            mesh_shape=tuple(self.mesh.shape.values()),
            rows_scanned=ds.num_rows,
            segments=len(ds.segments),
            num_groups=lowering.num_groups,
        )
        t0 = _time.perf_counter()
        known = len(self._shard_cache)
        before_bytes = self._shard_cache.bytes_used
        cols, padded, scope = self._global_columns(
            ds, lowering.columns, q.intervals, q.filter,
            frozenset(
                v.name for v in getattr(q, "virtual_columns", ()) or ()
            ),
        )
        # post-prune counts, matching the local engine's metrics semantics
        from ..exec.engine import _bytes_scanned

        m.rows_scanned = sum(sg.num_rows for sg in scope)
        m.bytes_scanned = _bytes_scanned(scope, lowering.columns)
        m.segments = len(scope)
        if len(self._shard_cache) > known:  # new shards were placed
            m.h2d_ms = (_time.perf_counter() - t0) * 1e3
            m.h2d_bytes = max(
                0, self._shard_cache.bytes_used - before_bytes
            )
        local_rows = padded // self.mesh.shape[DATA_AXIS]
        compiled = self._spmd_cache
        key_count = len(compiled)
        run = self._spmd_fn(lowering, local_rows, ds, tuple(cols.keys()))
        m.program_cache_hit = len(compiled) == key_count
        nd = self.mesh.shape[DATA_AXIS]
        m.est_collective_ms = (
            2.0 * (nd - 1) / nd
            * groupby_state_bytes(q, lowering.num_groups, None)
            / SessionConfig().collective_bytes_per_us
            / 1e3
        )
        t0 = _time.perf_counter()
        # single host fetch (one round trip — see engine._execute_groupby)
        sums, mins, maxs, sk = jax.device_get(run(cols))
        dt = (_time.perf_counter() - t0) * 1e3
        if m.program_cache_hit:
            m.device_ms = dt
        else:  # first call: trace+compile dominates (metrics.py semantics)
            m.compile_ms = dt
        t0 = _time.perf_counter()
        out = finalize_groupby(
            q,
            lowering.dims,
            lowering.la,
            np.asarray(sums),
            np.asarray(mins),
            np.asarray(maxs),
            {k: np.asarray(v) for k, v in sk.items()},
        )
        m.finalize_ms = (_time.perf_counter() - t0) * 1e3
        m.total_ms = (_time.perf_counter() - t_total) * 1e3
        m.bytes_resident = self._shard_cache.bytes_used
        self.last_metrics = m
        return out
