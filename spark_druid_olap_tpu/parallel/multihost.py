"""Multi-host (multi-slice / DCN) support for the distributed engine.

Reference parity: the reference's communication backend is Apache HttpClient
to Druid nodes plus ZooKeeper/Curator discovery (SURVEY.md §2 communication
row, §5 distributed-backend row `[U]`).  The TPU-native replacement has two
halves:

* **discovery / rendezvous** — `jax.distributed.initialize`: on Cloud TPU
  pods the coordinator and process ids come from the environment, on other
  fleets they are passed explicitly.  This replaces CuratorConnection: after
  it returns, `jax.devices()` spans every host's chips and the runtime owns
  membership (no ZK znodes to watch).
* **data placement** — inside one process `jax.device_put(host, sharding)`
  is enough; across processes each host only holds ITS rows (its
  "historical" segments), so global arrays are assembled with
  `jax.make_array_from_process_local_data` — each process contributes its
  addressable shards and XLA's collectives (ICI within a slice, DCN between
  slices) do the rest at execution time.

The collectives in `parallel/distributed.py` (`psum`/`pmin`/`pmax`/
`all_gather`) are mesh-topology-agnostic: on a multi-slice mesh built by
`hybrid_mesh()` the data axis maps to DCN (cheap per-device partials, one
small merged state crosses slices) and the groups axis to ICI, matching the
bandwidth hierarchy the way SURVEY.md §5 prescribes.
"""

from __future__ import annotations

from typing import Dict, Optional

import jax
import numpy as np

from ..utils.log import get_logger

log = get_logger("parallel.multihost")

_initialized = False


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> bool:
    """Join (or form) the multi-host JAX runtime.  The CuratorConnection
    analog: after this, discovery is done — `jax.devices()` is global.

    Safe to call unconditionally: single-process sessions (everything in
    this repo's tests, and any laptop use) return False without touching
    the runtime; repeated calls are no-ops.  Returns True when a
    multi-process runtime is (already) up.

    MUST run before any other JAX call — `jax.distributed.initialize`
    refuses once the XLA backend exists, so this function deliberately
    avoids `jax.process_count()`/`jax.devices()` until after the
    rendezvous."""
    global _initialized
    if _initialized:
        return True
    # a launcher may have formed the runtime before us; is_initialized()
    # inspects the distributed client without initializing the XLA backend
    if getattr(jax.distributed, "is_initialized", lambda: False)():
        _initialized = True
        return True
    if coordinator_address is None and num_processes is None:
        # no explicit rendezvous and no cluster metadata in the
        # environment: stay single-process rather than hanging on a
        # coordinator that will never answer.  The markers cover Cloud TPU
        # pods plus the cluster launchers jax auto-detects (SLURM / OMPI).
        import os

        if not any(
            k in os.environ
            for k in (
                "COORDINATOR_ADDRESS",
                "JAX_COORDINATOR_ADDRESS",
                "CLOUD_TPU_TASK_ID",
                "TPU_WORKER_ID",
                "SLURM_JOB_ID",
                "OMPI_COMM_WORLD_SIZE",
            )
        ):
            return False
    try:
        # CPU backend: cross-process collectives need the Gloo transport
        # ("Multiprocess computations aren't implemented on the CPU
        # backend" otherwise) — must be set BEFORE the runtime forms.
        # Real TPU/GPU pods ignore it; a jax build without the flag (or
        # without Gloo) keeps the old failure mode at dispatch time.
        import os as _os

        if _os.environ.get("JAX_PLATFORMS", "") in ("", "cpu"):
            try:
                jax.config.update(
                    "jax_cpu_collectives_implementation", "gloo"
                )
            except Exception:  # fault-ok: older/newer flagless builds
                pass
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
        _initialized = True
        log.info(
            "joined distributed runtime: process %d/%d, %d global devices",
            jax.process_index(), jax.process_count(), jax.device_count(),
        )
        return True
    except ValueError as err:
        # auto-detection found a cluster marker but not enough of the env
        # to form a rendezvous (e.g. SLURM_JOB_ID inside an interactive
        # salloc shell with no srun task vars): stay single-process — the
        # contract is "safe to call unconditionally"
        if coordinator_address is None and num_processes is None:
            log.info("cluster env not resolvable (%s); staying single-process", err)
            return False
        raise
    except RuntimeError as err:
        # tolerate a launcher that already initialized the distributed
        # runtime; surface "backend already initialized" (caller ran JAX
        # ops before rendezvous) — that one is a real ordering bug
        if "already initialized" in str(err).lower() and "backend" not in str(
            err
        ).lower():
            _initialized = True
            return True
        raise


def hybrid_mesh(n_groups: int = 1):
    """A (data, groups) mesh laid out for the DCN x ICI hierarchy.

    Multi-slice: the data axis spans slices over DCN (each slice aggregates
    its own rows; only the [G, M] partial state crosses DCN once per query
    — the broker-merge shape), the groups axis stays inside a slice on ICI.
    Single-slice / single-host: identical to `mesh.make_mesh`."""
    from jax.sharding import Mesh

    from .mesh import AXIS_NAMES, make_mesh

    if jax.process_count() <= 1:
        return make_mesh(n_groups=n_groups)
    from jax.experimental import mesh_utils

    n_dev = jax.device_count()
    devs = mesh_utils.create_hybrid_device_mesh(
        mesh_shape=(n_dev // jax.process_count() // n_groups, n_groups),
        dcn_mesh_shape=(jax.process_count(), 1),
        process_is_granule=True,
    )
    return Mesh(devs, AXIS_NAMES)


def put_sharded(host: np.ndarray, sharding) -> jax.Array:
    """Place a host array laid out GLOBALLY under `sharding`, multi-host
    aware.

    Single-process: plain `jax.device_put` (the fast path every test and
    single-chip session takes).  Multi-process: every process knows the
    global row layout (the catalog is deterministic), but only materializes
    and transfers the shards its own devices address —
    `make_array_from_callback` slices `host` per-device, so no host pays
    H2D for another slice's rows (the DruidRDD
    one-partition-per-historical analog)."""
    if jax.process_count() <= 1:
        return jax.device_put(host, sharding)
    return jax.make_array_from_callback(
        host.shape, sharding, lambda idx: host[idx]
    )


def local_segments(segments) -> list:
    """This process's slice of a datasource's segments (round-robin by
    process index) — which rows each "historical" owns.  Deterministic so
    every process agrees on the global layout without coordination."""
    pc, pi = jax.process_count(), jax.process_index()
    if pc <= 1:
        return list(segments)
    return [s for i, s in enumerate(segments) if i % pc == pi]


def process_info() -> Dict[str, int]:
    return {
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "local_devices": len(jax.local_devices()),
        "global_devices": jax.device_count(),
    }
