"""Device mesh construction for distributed OLAP aggregation.

Reference parity: the reference's "cluster" is Druid's broker + historicals
discovered via ZooKeeper (SURVEY.md §2 ZK-discovery row `[U]`); its
parallelism is one Spark partition per (historical, segment-group).  The
TPU-native equivalent is a `jax.sharding.Mesh` whose axes carry the two ways
an aggregation can be decomposed:

* ``data``   — row/segment shards (the historicals-analog; DP/SP axis).  Each
  device aggregates its rows; partial states merge with `psum`/`pmin`/`pmax`
  over ICI.
* ``groups`` — group-domain shards (the TP-analog).  Each device owns a slice
  of the group-id domain [0, G); useful when G is large enough that the
  one-hot block or the sketch state per group dominates memory.

Discovery is the JAX runtime (`jax.distributed` across hosts) — no ZooKeeper.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# The ONLY axis names any mesh in this package declares.  Every
# collective / PartitionSpec in the tree is checked against these by
# graftlint's collective-axis pass (GL8xx) — add an axis here (or as a
# `*_AXIS` constant) before using it in an SPMD body.
DATA_AXIS = "data"
GROUPS_AXIS = "groups"
# Virtual multi-slice topology: the slice axis models the DCN-connected
# dimension of a multi-slice pod (each slice's devices talk over ICI;
# slices talk over DCN).  On a single-slice host it is a *virtual*
# partition of the device set used to exercise the hierarchical merge
# tree (`psum` over SLICE_AXIS is the DCN hop the cost model prices).
SLICE_AXIS = "slice"
AXIS_NAMES = (DATA_AXIS, GROUPS_AXIS)
SLICE_AXIS_NAMES = (SLICE_AXIS, DATA_AXIS)


def make_mesh(
    n_data: Optional[int] = None,
    n_groups: int = 1,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Create a (data, groups) mesh.  Defaults to all devices on the data
    axis.  With multi-host meshes the data axis should map to the
    DCN-connected dimension and groups to ICI (group-state merges are the
    bandwidth-heavy collective)."""
    devs = list(devices) if devices is not None else jax.devices()
    if n_data is None:
        n_data = len(devs) // n_groups
    if n_data * n_groups != len(devs):
        devs = devs[: n_data * n_groups]
    arr = np.array(devs).reshape(n_data, n_groups)
    return Mesh(arr, AXIS_NAMES)


def make_slice_mesh(
    n_slices: int,
    n_data: Optional[int] = None,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Create a (slice, data) mesh — the virtual multi-slice topology.

    The slice axis is outermost so contiguous device ranges form a slice
    (matching how `create_hybrid_device_mesh` granules a real pod: a
    slice's devices are ICI-adjacent, the slice axis is the DCN hop).
    Row shards are placed over BOTH axes — the arena treats the flattened
    (slice*data) product as its row-device count — and the merge tree
    decides whether the partial-state `psum` runs flat over both axes or
    hierarchically (data first, then slice)."""
    devs = list(devices) if devices is not None else jax.devices()
    if n_slices < 1:
        raise ValueError("n_slices must be >= 1")
    if n_data is None:
        n_data = len(devs) // n_slices
    if n_data < 1 or n_slices * n_data > len(devs):
        raise ValueError(
            "slice mesh %dx%d needs %d devices, have %d"
            % (n_slices, n_data, n_slices * n_data, len(devs))
        )
    arr = np.array(devs[: n_slices * n_data]).reshape(n_slices, n_data)
    return Mesh(arr, SLICE_AXIS_NAMES)


def row_axes(mesh: Mesh) -> Tuple[str, ...]:
    """The mesh axes rows are sharded over: (slice, data) on a slice mesh,
    (data,) on the standard mesh.  Collectives that merge per-device row
    partials reduce over exactly these axes."""
    if SLICE_AXIS in mesh.shape:
        return (SLICE_AXIS, DATA_AXIS)
    return (DATA_AXIS,)


def shard_map_compat(fn, *, mesh, in_specs, out_specs):
    """`jax.shard_map` across JAX versions: the new top-level API takes
    `check_vma`; older releases (<=0.4.x, this container's 0.4.37) only
    ship `jax.experimental.shard_map.shard_map` with the `check_rep`
    spelling.  Every SPMD program builds through here so a JAX upgrade or
    downgrade degrades to the available API instead of AttributeError-ing
    the whole distributed path."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=False,
        )
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(
        fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=False,
    )


def row_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P(DATA_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
