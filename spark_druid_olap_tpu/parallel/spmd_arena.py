"""The unified executor core's SPMD side: shard_map the arena (ISSUE 15).

PR 12's segment-stacked arena made the single-device path O(1) dispatches;
this module makes the SAME stacked `[B, R]` layout the one program the mesh
lowers too, so the mesh is a *placement strategy* over the arena rather
than a fork of the executor:

* **Device-major permuted stacking** — the datasource's segment blocks
  stack into ONE `[B_pad, R]` array per column, laid out so row-device
  ``d`` owns the cyclic canonical blocks ``{d, ndt+d, 2*ndt+d, ...}`` in
  its contiguous shard.  `B_pad = ndt * L` (zero blocks pad the tail), so
  a `NamedSharding` over the row axes gives every device an equal `[L, R]`
  block-stack with NO per-scope relayout: the layout is keyed on the FULL
  segment signature, never a query's pruned scope.
* **Scope as data, not shape** — a query's pruned uid set arrives as a
  per-block membership vector (a data input) plus a dynamic window start
  `j_lo` (also data).  Only the window LENGTH `Lk` — the scope size
  rounded up to device multiples — is a static program-key component, so
  two disjoint scopes of equal rounded size share one compiled program:
  the SPMD program-cache generality that per-scope shard layouts
  (`local_rows` keyed on the scope) traded away.  Compute still scales
  with the scope (the dynamic slice bounds the scan), keeping the r5->r6
  pruning win.
* **Fold inside the trace, merge at the boundary** — each device runs the
  exec/arena.py fold (`_member_init` / `_fold_block` / `finish_member`,
  imported — ONE fold implementation for both paths) over its local
  in-window blocks in canonical order, then the partial states merge with
  `psum`/`pmin`/`pmax` at the trace boundary.  A member whose blocks all
  live on other devices contributes exact identities (zeros for sums,
  ±inf-forced extrema), so the collective is exact for counts and
  min/max, and bit-exact for integer-valued f32 sums.
* **Merge trees** — on a virtual multi-slice mesh (`mesh.make_slice_mesh`)
  the boundary merge runs either FLAT (one psum over slice x data) or
  HIERARCHICAL (slice-local psum over ICI, then the merged state over the
  DCN slice axis), chosen per query by `plan.cost.choose_merge_tree` from
  the calibrated `collective_bytes_per_us` / `dcn_bytes_per_us` constants.
* **Deadline chunking** — with a wall-clock deadline armed, the scan
  splits into per-local-step chunk programs with the fold carry threaded
  through as a `[ndt, ...]` row-sharded array (per-shard stop-and-merge);
  a final merge program runs the boundary collectives.  Coverage is
  accounted host-side per step (the canonical blocks a step touches are
  known), summed across shards.

Builders here are pure (mesh + lowerings in, jitted program out); the
`DistributedEngine` caches them under structured query keys.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..utils.log import get_logger
from .mesh import DATA_AXIS, SLICE_AXIS, row_axes, shard_map_compat

log = get_logger("parallel.spmd_arena")


class SpmdArenaLayout:
    """The device-major permuted stacking of one datasource's segments
    over `ndt` row devices.  Scope-independent: keyed on the FULL segment
    signature, it survives any query's pruning unchanged."""

    __slots__ = ("segs", "uids", "B", "R", "L", "B_pad", "ndt", "index")

    def __init__(self, segs, ndt: int):
        self.segs = list(segs)
        self.uids = tuple(s.uid for s in self.segs)
        self.B = len(self.segs)
        # a runt tail block (ingest's append-tail) stacks zero-padded to
        # the full row shape; its validity stack is False past its own
        # rows, so the masked fold is exact over the pad
        self.R = max(
            (s.num_rows_padded for s in self.segs), default=0
        )
        self.ndt = ndt
        self.L = -(-max(self.B, 1) // ndt)
        self.B_pad = ndt * self.L
        # canonical segment index by uid (scope -> membership translation)
        self.index = {s.uid: i for i, s in enumerate(self.segs)}

    def pos(self, b: int) -> int:
        """Stacked position of canonical block `b`: device-major, so
        device `b % ndt` holds it at local step `b // ndt`."""
        return (b % self.ndt) * self.L + b // self.ndt


def plan_spmd_layout(ds, ndt: int) -> Optional[SpmdArenaLayout]:
    """Layout decision for one datasource on `ndt` row devices, or None
    when the stacked layout cannot apply: fewer than two segments, or
    padded row counts that aren't the ingest append-tail pattern (equal
    blocks plus at most one shorter LAST block).  The tail block stacks
    zero-padded with False validity — exact under the masked fold — but
    arbitrary shape mixes would let one giant segment inflate every
    block's pad, so those keep the legacy per-shard path (the same
    shape discipline as exec/arena.plan_for, tail-tolerant)."""
    segs = list(ds.segments)
    if len(segs) < 2:
        return None
    shape0 = segs[0].num_rows_padded
    if any(s.num_rows_padded != shape0 for s in segs[:-1]):
        return None
    if segs[-1].num_rows_padded > shape0:
        return None
    return SpmdArenaLayout(segs, ndt)


def scope_window(
    layout: SpmdArenaLayout, canonical: Sequence[int]
) -> Tuple[int, int]:
    """(j_lo, Lk): the local-step window covering the scope's canonical
    block range.  `j_lo` rides as DATA; only `Lk` keys the program."""
    k0, k1 = min(canonical), max(canonical) + 1
    j_lo = k0 // layout.ndt
    j_hi = -(-k1 // layout.ndt)
    return j_lo, j_hi - j_lo


def membership_matrix(
    layout: SpmdArenaLayout, member_scopes: Sequence[Sequence[int]]
) -> np.ndarray:
    """Permuted `[B_pad, n_members]` block-membership flags from each
    member's canonical in-scope indices.  Pad blocks stay False."""
    memb = np.zeros((layout.B_pad, len(member_scopes)), dtype=bool)
    for i, scope in enumerate(member_scopes):
        for b in scope:
            memb[layout.pos(b), i] = True
    return memb


def stack_column(layout: SpmdArenaLayout, name: str) -> np.ndarray:
    """Host-side permuted `[B_pad, R]` stack of one column (zero blocks
    for the pad tail; their validity is False so they can never fold)."""
    seg0 = layout.segs[0]
    proto = np.asarray(
        seg0.valid if name == "__valid" else seg0.column(name)
    )
    out = np.zeros((layout.B_pad, layout.R), dtype=proto.dtype)
    for b, s in enumerate(layout.segs):
        arr = np.asarray(s.valid if name == "__valid" else s.column(name))
        # runt tail block: rows past the segment stay zero / False-valid
        out[layout.pos(b), : arr.shape[0]] = arr
    return out


def _row_spec_axes(mesh) -> Any:
    """The PartitionSpec element sharding a leading row-device axis."""
    axes = row_axes(mesh)
    return axes if len(axes) > 1 else axes[0]


def _merge_groups(mesh, tree: str) -> List[Tuple[str, ...]]:
    if tree == "hierarchical" and SLICE_AXIS in mesh.shape:
        return [(DATA_AXIS,), (SLICE_AXIS,)]
    return [tuple(row_axes(mesh))]


def _boundary_merge(mesh, tree: str, member_carry):
    """finish_member + the collective merge of one member's carry.
    Returns (sums, mins, maxs, live_count) — live_count is the number of
    shards that folded at least one block (0 => empty scope on every
    shard; the host substitutes `empty_partials`)."""
    import jax.numpy as jnp
    from jax import lax

    from ..exec.arena import finish_member

    s, mn, mx, live = finish_member(member_carry)
    # dead-shard identities: zeros are already exact for sums (the carry
    # is zero-seeded), but the extrema carries hold zeros too — force
    # them to the fold identities so pmin/pmax cannot pull a dead 0.0
    # into a live lane
    if mn.shape[1]:
        mn = jnp.where(live, mn, jnp.inf)
    if mx.shape[1]:
        mx = jnp.where(live, mx, -jnp.inf)
    groups = _merge_groups(mesh, tree)
    for axes in groups:
        s = lax.psum(s, axes)
        if mn.shape[1]:
            mn = lax.pmin(mn, axes)
        if mx.shape[1]:
            mx = lax.pmax(mx, axes)
    live_n = lax.psum(live.astype(jnp.int32), tuple(row_axes(mesh)))
    return s, mn, mx, live_n


def build_spmd_arena_program(
    mesh,
    lowerings,
    strategies,
    Lk: int,
    tree: str = "flat",
    share=None,
):
    """The single-dispatch unified program: per-shard scanned fold over
    the `[Lk]` local-step window + boundary collective merge, ONE
    compiled XLA program.  Signature::

        fn(cols, j_lo, memb) -> ((sums, mins, maxs, live_n), ...) per member

    `cols` maps name -> `[B_pad, R]` row-sharded stack; `j_lo` is the
    replicated window start (data); `memb` is the `[B_pad, n]`
    row-sharded membership.  Nothing scope-shaped is baked into the
    trace, so one program serves every same-`Lk` scope."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from ..exec.arena import _fold_block, _member_init
    from ..exec.engine import _segment_partials

    n = len(lowerings)
    row_el = _row_spec_axes(mesh)
    no_start = np.False_  # plain left fold: no batch boundaries on a shard

    def shard_fn(cols, j_lo, memb):
        win = {
            k: lax.dynamic_slice_in_dim(v, j_lo, Lk, axis=0)
            for k, v in cols.items()
        }
        memb_w = lax.dynamic_slice_in_dim(memb, j_lo, Lk, axis=0)
        carry = tuple(_member_init(lw) for lw in lowerings)

        def body(c, xs):
            cols_b, memb_b = xs
            memo: Dict[Any, Any] = {}
            out = []
            for i in range(n):
                s, mn, mx, _sk = _segment_partials(
                    lowerings[i],
                    strategies[i],
                    dict(cols_b),
                    memo=memo if share is not None else None,
                    share=share[i] + (0,) if share is not None else None,
                )
                out.append(
                    _fold_block(c[i], (s, mn, mx), no_start, memb_b[i])
                )
            return tuple(out), None

        carry, _ = lax.scan(body, carry, (win, memb_w))
        return tuple(_boundary_merge(mesh, tree, c) for c in carry)

    in_specs = (P(row_el, None), P(), P(row_el, None))
    out_specs = tuple((P(), P(), P(), P()) for _ in range(n))
    # graftlint: disable=jit-cache -- caller caches under a query key
    return jax.jit(
        shard_map_compat(
            shard_fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs
        )
    )


def init_carry_stacked(mesh, lowerings):
    """Zero-seeded `[ndt, ...]`-stacked fold carries for the chunked
    (deadline) mode, placed row-sharded so each device owns its slice."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    ndt = int(np.prod([mesh.shape[a] for a in row_axes(mesh)]))
    row_el = _row_spec_axes(mesh)

    def leaf(x):
        host = np.zeros((ndt,) + np.shape(x), np.asarray(x).dtype)
        return jax.device_put(host, NamedSharding(mesh, P(row_el)))

    out = []
    for lw in lowerings:
        la, G = lw.la, lw.num_groups
        z2 = np.zeros((G, len(la.sum_names)), np.float32)
        zn = np.zeros((G, len(la.min_names)), np.float32)
        zx = np.zeros((G, len(la.max_names)), np.float32)
        zb = np.zeros((), bool)
        member = (z2, zn, zx, zb) + (z2, zn, zx, zb)
        out.append(tuple(leaf(x) for x in member))
    return tuple(out)


def build_spmd_chunk_program(mesh, lowerings, strategies, share=None):
    """One deadline-mode chunk: fold ONE local step into the stacked
    carry.  `fn(carry, cols, j, memb) -> carry` — the carry is a
    `[ndt, ...]` row-sharded pytree threaded across dispatches, so a
    stop-and-merge truncation lands on a per-shard step boundary."""
    import jax
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from ..exec.arena import _donate_carry, _fold_block
    from ..exec.engine import _segment_partials

    n = len(lowerings)
    row_el = _row_spec_axes(mesh)
    no_start = np.False_

    def shard_fn(carry, cols, j, memb):
        local = jax.tree.map(lambda x: x[0], carry)
        cols_b = {
            k: lax.dynamic_slice_in_dim(v, j, 1, axis=0)[0]
            for k, v in cols.items()
        }
        memb_b = lax.dynamic_slice_in_dim(memb, j, 1, axis=0)[0]
        memo: Dict[Any, Any] = {}
        out = []
        for i in range(n):
            s, mn, mx, _sk = _segment_partials(
                lowerings[i],
                strategies[i],
                dict(cols_b),
                memo=memo if share is not None else None,
                share=share[i] + (0,) if share is not None else None,
            )
            out.append(
                _fold_block(local[i], (s, mn, mx), no_start, memb_b[i])
            )
        return jax.tree.map(lambda x: x[None], tuple(out))

    in_specs = (P(row_el), P(row_el, None), P(), P(row_el, None))
    donate = {"donate_argnums": (0,)} if _donate_carry() else {}
    # graftlint: disable=jit-cache -- caller caches under a query key
    return jax.jit(
        shard_map_compat(
            shard_fn, mesh=mesh, in_specs=in_specs, out_specs=P(row_el)
        ),
        **donate,
    )


def build_spmd_merge_program(mesh, lowerings, tree: str = "flat"):
    """Deadline-mode boundary merge: `fn(carry) -> per-member (sums,
    mins, maxs, live_n)` — the same collective merge the single-dispatch
    program fuses after its scan, run once after the chunk loop stops."""
    import jax
    from jax.sharding import PartitionSpec as P

    n = len(lowerings)
    row_el = _row_spec_axes(mesh)

    def shard_fn(carry):
        local = jax.tree.map(lambda x: x[0], carry)
        return tuple(_boundary_merge(mesh, tree, c) for c in local)

    out_specs = tuple((P(), P(), P(), P()) for _ in range(n))
    # graftlint: disable=jit-cache -- caller caches under a query key
    return jax.jit(
        shard_map_compat(
            shard_fn, mesh=mesh, in_specs=(P(row_el),), out_specs=out_specs
        )
    )
