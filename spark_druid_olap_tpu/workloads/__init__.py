"""Benchmark / parity workloads: schema declarations, data generators, query
suites and oracles for the BASELINE.md configurations (SSB star schema,
TPC-H Q1, rollup and sketch workloads)."""
