"""TPC-H star workload: normalized tables, snowflake star declaration, and
the reference test suite's query classes in joined-SQL form.

Reference parity: the reference's integration corpus is the TPC-H
*flattened star* — `orderLineItemPartSupplier` over Druid datasource `tpch`
(SURVEY.md §4 `[U]`: `TPCHTest` runs Q1/Q3/Q5/Q7/Q8-class star queries with
the star-schema JSON + functional dependencies declared in the DDL).  Here:

* `gen_tables(scale)` builds a normalized TPC-H subset: `lineitem` fact +
  `orders` / `customer` / `supplier` / `part` dims.  Nation/region attributes
  are folded into customer and supplier as strings (the reference's flat
  table does the same; a dual-role `nation` dim would need join aliasing the
  star layer deliberately doesn't model).
* customer hangs off orders (`lineitem -> orders -> customer`) — the
  snowflake edge `StarRelationInfo(parent=...)` exists for exactly this.
* `QUERIES`: Q1 (single-table agg incl. AVG rewrite), Q3 (high-cardinality
  group by l_orderkey + ORDER BY revenue LIMIT 10 — the sparse-groupby
  shape), Q5-class (regional supplier volume), Q6 (interval + expression
  aggregate), Q12-class (shipmode CASE counts).  Q4/Q21-style EXISTS
  semijoins are out of scope: the planner has no semijoin rewrite (neither
  does the reference's — those queries fell back to Spark there too).
* `oracle(tables, name)` computes each result in float64 pandas.

Constants are adapted to this generator's value domains; query *shapes*
(join pattern, predicates, grouping, ordering) follow the TPC-H spec.
"""

from __future__ import annotations

from typing import Dict

import numpy as np

from ..catalog.segment import DimensionDict
from ..catalog.star import FunctionalDependency, StarRelationInfo, StarSchemaInfo

_MS_DAY = 86_400_000

REGIONS = ["AFRICA", "AMERICA", "ASIA", "EUROPE", "MIDDLE EAST"]
NATIONS = {
    "AFRICA": ["ALGERIA", "ETHIOPIA", "KENYA", "MOROCCO", "MOZAMBIQUE"],
    "AMERICA": ["ARGENTINA", "BRAZIL", "CANADA", "PERU", "UNITED STATES"],
    "ASIA": ["CHINA", "INDIA", "INDONESIA", "JAPAN", "VIETNAM"],
    "EUROPE": ["FRANCE", "GERMANY", "ROMANIA", "RUSSIA", "UNITED KINGDOM"],
    "MIDDLE EAST": ["EGYPT", "IRAN", "IRAQ", "JORDAN", "SAUDI ARABIA"],
}
SEGMENTS = ["AUTOMOBILE", "BUILDING", "FURNITURE", "HOUSEHOLD", "MACHINERY"]
SHIPMODES = ["AIR", "FOB", "MAIL", "RAIL", "REG AIR", "SHIP", "TRUCK"]
PRIORITIES = ["1-URGENT", "2-HIGH", "3-MEDIUM", "4-NOT SPECIFIED", "5-LOW"]

# attribute -> (owning table, fact-side index resolver)
DIM_ATTRS = {
    "o_orderpriority": "orders",
    "o_orderdate": "orders",  # numeric-dict dimension: ~2.4k distinct days
    "o_orderdate_year": "orders",
    "c_custkey": "orders",
    "c_name": "orders",
    "c_mktsegment": "orders",  # customer attrs ride the orders row (snowflake)
    "c_nation": "orders",
    "c_region": "orders",
    "s_nation": "supplier",
    "s_region": "supplier",
    "p_brand": "part",
    "p_type": "part",
    "l_returnflag": "lineitem",
    "l_linestatus": "lineitem",
    "l_shipmode": "lineitem",
    "l_orderkey": "lineitem",
}

FLAT_METRICS = [
    "l_quantity", "l_extendedprice", "l_discount", "l_tax",
]

STAR_SCHEMA = StarSchemaInfo(
    fact_table="lineitem",
    relations=(
        StarRelationInfo("orders", (("l_orderkey", "o_orderkey"),)),
        StarRelationInfo(
            "customer", (("o_custkey", "c_custkey"),), parent="orders"
        ),
        StarRelationInfo("supplier", (("l_suppkey", "s_suppkey"),)),
        StarRelationInfo("part", (("l_partkey", "p_partkey"),)),
    ),
    functional_dependencies=(
        FunctionalDependency("customer", "c_custkey", "c_name"),
        FunctionalDependency("customer", "c_custkey", "c_nation"),
        FunctionalDependency("customer", "c_custkey", "c_mktsegment"),
        FunctionalDependency("customer", "c_nation", "c_region"),
        FunctionalDependency("supplier", "s_nation", "s_region"),
        FunctionalDependency("orders", "o_orderkey", "o_orderpriority"),
    ),
)


def _geo(n: int, rng):
    reg = rng.choice(np.array(REGIONS, dtype=object), size=n)
    nation = np.empty(n, dtype=object)
    for r in REGIONS:
        m = reg == r
        nation[m] = rng.choice(np.array(NATIONS[r], dtype=object), int(m.sum()))
    return reg, nation


def gen_tables(scale: float = 0.01, seed: int = 13) -> Dict[str, Dict[str, np.ndarray]]:
    """Normalized TPC-H subset at ~SF `scale` (SF1: 6M lineitem rows).
    Keys are dense 0..n-1 so the pre-join is a direct gather."""
    rng = np.random.default_rng(seed)

    n_c = max(100, int(150_000 * scale))
    c_region, c_nation = _geo(n_c, rng)
    customer = {
        "c_custkey": np.arange(n_c, dtype=np.int64),
        "c_name": np.array(
            [f"Customer#{k:09d}" for k in range(n_c)], dtype=object
        ),
        "c_mktsegment": rng.choice(np.array(SEGMENTS, dtype=object), n_c),
        "c_nation": c_nation,
        "c_region": c_region,
    }

    n_s = max(50, int(10_000 * scale))
    s_region, s_nation = _geo(n_s, rng)
    supplier = {
        "s_suppkey": np.arange(n_s, dtype=np.int64),
        "s_nation": s_nation,
        "s_region": s_region,
    }

    n_p = max(200, int(200_000 * scale))
    part = {
        "p_partkey": np.arange(n_p, dtype=np.int64),
        "p_brand": np.array(
            [f"Brand#{a}{b}" for a, b in zip(
                rng.integers(1, 6, n_p), rng.integers(1, 6, n_p)
            )], dtype=object,
        ),
        "p_type": rng.choice(
            np.array(
                ["ECONOMY ANODIZED STEEL", "LARGE BRUSHED BRASS",
                 "MEDIUM POLISHED COPPER", "SMALL PLATED TIN",
                 "STANDARD BURNISHED NICKEL"], dtype=object,
            ),
            n_p,
        ),
    }

    n_o = max(500, int(1_500_000 * scale))
    d0 = int(np.datetime64("1992-01-01", "ms").astype(np.int64))
    d1 = int(np.datetime64("1998-08-02", "ms").astype(np.int64))
    o_orderdate = (
        rng.integers(d0 // _MS_DAY, d1 // _MS_DAY, size=n_o) * _MS_DAY
    )
    orders = {
        "o_orderkey": np.arange(n_o, dtype=np.int64),
        "o_custkey": rng.integers(0, n_c, size=n_o).astype(np.int64),
        "o_orderdate": o_orderdate,
        "o_orderpriority": rng.choice(np.array(PRIORITIES, dtype=object), n_o),
    }

    n = int(6_001_215 * scale)
    okey = rng.integers(0, n_o, size=n).astype(np.int64)
    shipdate = orders["o_orderdate"][okey] + rng.integers(
        1, 122, size=n
    ) * _MS_DAY
    lineitem = {
        "l_orderkey": okey,
        "l_suppkey": rng.integers(0, n_s, size=n).astype(np.int64),
        "l_partkey": rng.integers(0, n_p, size=n).astype(np.int64),
        "l_shipdate": shipdate,
        "l_quantity": rng.integers(1, 51, size=n).astype(np.float32),
        "l_extendedprice": (rng.random(n).astype(np.float32) * 55_450 + 90),
        "l_discount": (rng.integers(0, 11, size=n) / 100).astype(np.float32),
        "l_tax": (rng.integers(0, 9, size=n) / 100).astype(np.float32),
        "l_returnflag": rng.choice(
            np.array(["A", "N", "R"], dtype=object), n, p=[0.25, 0.5, 0.25]
        ),
        "l_linestatus": np.where(
            shipdate < int(np.datetime64("1995-06-17", "ms").astype(np.int64)),
            "F", "O",
        ).astype(object),
        "l_shipmode": rng.choice(np.array(SHIPMODES, dtype=object), n),
    }
    return {
        "lineitem": lineitem, "orders": orders, "customer": customer,
        "supplier": supplier, "part": part,
    }


def flat_columns(tables):
    """Pre-join the snowflake into the dictionary-encoded flat datasource
    (dictionaries built on the SMALL tables, codes gathered through FKs)."""
    li = tables["lineitem"]
    o = tables["orders"]
    c = tables["customer"]
    okey = li["l_orderkey"]
    ckey = o["o_custkey"][okey]  # snowflake hop resolved at flatten time

    cols: Dict[str, np.ndarray] = {
        "l_shipdate": li["l_shipdate"],
        "o_orderdate": o["o_orderdate"][okey],
        **{m: li[m] for m in FLAT_METRICS},
    }
    dicts: Dict[str, DimensionDict] = {}

    def add(attr, values, fact_idx):
        if values.dtype.kind in ("U", "S", "O"):
            d = DimensionDict.build(list(values))
            codes = d.encode(list(values))
        else:
            uniq = np.unique(values.astype(np.int64))
            d = DimensionDict(values=tuple(int(v) for v in uniq))
            codes = d.encode_numeric(values)
        dicts[attr] = d
        cols[attr] = codes[fact_idx] if fact_idx is not None else codes

    add("o_orderpriority", o["o_orderpriority"], okey)
    year = (
        o["o_orderdate"].astype("datetime64[ms]").astype("datetime64[Y]")
        .astype(int) + 1970
    )
    add("o_orderdate_year", year.astype(np.int64), okey)
    add("c_custkey", c["c_custkey"], ckey)
    add("c_name", c["c_name"], ckey)
    add("c_mktsegment", c["c_mktsegment"], ckey)
    add("c_nation", c["c_nation"], ckey)
    add("c_region", c["c_region"], ckey)
    add("s_nation", tables["supplier"]["s_nation"], li["l_suppkey"])
    add("s_region", tables["supplier"]["s_region"], li["l_suppkey"])
    add("p_brand", tables["part"]["p_brand"], li["l_partkey"])
    add("p_type", tables["part"]["p_type"], li["l_partkey"])
    for a in ("l_returnflag", "l_linestatus", "l_shipmode"):
        add(a, li[a], None)
    add("l_orderkey", li["l_orderkey"], None)
    return cols, dicts


FLAT_DIMS = list(DIM_ATTRS)


def register(ctx, scale: float = 0.01, seed: int = 13,
             rows_per_segment: int = 1 << 22, tables=None):
    """Register the flat fact (with snowflake star schema) + normalized
    dims — the reference's orderLineItemPartSupplier DDL analog."""
    tables = tables if tables is not None else gen_tables(scale, seed)
    cols, dicts = flat_columns(tables)
    ctx.register_table(
        "lineitem", cols,
        dimensions=FLAT_DIMS, metrics=FLAT_METRICS,
        time_column="l_shipdate", star_schema=STAR_SCHEMA,
        rows_per_segment=rows_per_segment, dicts=dicts,
    )
    ctx.register_table("orders", tables["orders"], time_column="o_orderdate")
    for t in ("customer", "supplier", "part"):
        ctx.register_table(t, tables[t])
    return tables


_J_ORD = "JOIN orders ON l_orderkey = o_orderkey"
_J_CUST = "JOIN customer ON o_custkey = c_custkey"
_J_SUPP = "JOIN supplier ON l_suppkey = s_suppkey"
_J_PART = "JOIN part ON l_partkey = p_partkey"

QUERIES: Dict[str, str] = {
    # Q1: pricing summary report — AVG rewrite + expression aggregates
    "q1": """
        SELECT l_returnflag, l_linestatus,
               sum(l_quantity) AS sum_qty,
               sum(l_extendedprice) AS sum_base_price,
               sum(l_extendedprice * (1 - l_discount)) AS sum_disc_price,
               sum(l_extendedprice * (1 - l_discount) * (1 + l_tax)) AS sum_charge,
               avg(l_quantity) AS avg_qty,
               avg(l_extendedprice) AS avg_price,
               avg(l_discount) AS avg_disc,
               count(*) AS count_order
        FROM lineitem
        WHERE l_shipdate <= '1998-09-02'
        GROUP BY l_returnflag, l_linestatus
        ORDER BY l_returnflag, l_linestatus
    """,
    # Q3-class: shipping priority — snowflake join + huge group domain
    # (l_orderkey: the sparse-groupby shape) + ORDER BY revenue LIMIT 10
    "q3": f"""
        SELECT l_orderkey,
               sum(l_extendedprice * (1 - l_discount)) AS revenue
        FROM lineitem {_J_ORD} {_J_CUST}
        WHERE c_mktsegment = 'BUILDING'
          AND o_orderdate < '1995-03-15'
          AND l_shipdate > '1995-03-15'
        GROUP BY l_orderkey
        ORDER BY revenue DESC
        LIMIT 10
    """,
    # Q10-class: returned-item reporting — GROUP BY customer attributes;
    # exercises FD grouping pruning (c_custkey determines c_name/c_nation:
    # the kernel groups by c_custkey alone, pruned columns ride hidden
    # code aggregations)
    "q10": f"""
        SELECT c_custkey, c_name, c_nation,
               sum(l_extendedprice * (1 - l_discount)) AS revenue
        FROM lineitem {_J_ORD} {_J_CUST}
        WHERE o_orderdate >= '1993-10-01' AND o_orderdate < '1994-01-01'
          AND l_returnflag = 'R'
        GROUP BY c_custkey, c_name, c_nation
        ORDER BY revenue DESC
        LIMIT 20
    """,
    # Q5-class: local supplier volume — both dim branches constrained to one
    # region, grouped by supplier nation
    "q5": f"""
        SELECT s_nation, sum(l_extendedprice * (1 - l_discount)) AS revenue
        FROM lineitem {_J_ORD} {_J_CUST} {_J_SUPP}
        WHERE c_region = 'ASIA' AND s_region = 'ASIA'
          AND o_orderdate >= '1994-01-01' AND o_orderdate < '1995-01-01'
        GROUP BY s_nation
        ORDER BY revenue DESC
    """,
    # Q6: forecasting revenue change — pure interval + bound filters into an
    # expression aggregate, no grouping
    "q6": """
        SELECT sum(l_extendedprice * l_discount) AS revenue
        FROM lineitem
        WHERE l_shipdate >= '1994-01-01' AND l_shipdate < '1995-01-01'
          AND l_discount >= 0.05 AND l_discount <= 0.07
          AND l_quantity < 24
    """,
    # Q12-class: shipmode line-priority counts — CASE inside SUM
    "q12": f"""
        SELECT l_shipmode,
               sum(CASE WHEN o_orderpriority = '1-URGENT'
                         OR o_orderpriority = '2-HIGH'
                        THEN 1 ELSE 0 END) AS high_line_count,
               sum(CASE WHEN o_orderpriority <> '1-URGENT'
                        AND o_orderpriority <> '2-HIGH'
                        THEN 1 ELSE 0 END) AS low_line_count
        FROM lineitem {_J_ORD}
        WHERE l_shipmode IN ('MAIL', 'SHIP')
          AND l_shipdate >= '1994-01-01' AND l_shipdate < '1995-01-01'
        GROUP BY l_shipmode
        ORDER BY l_shipmode
    """,
    # Q7-class: volume shipping between two nations — OR-of-ANDs across two
    # dimension branches + EXTRACT over the time column as a grouping dim
    "q7": f"""
        SELECT s_nation, c_nation,
               EXTRACT(YEAR FROM l_shipdate) AS l_year,
               sum(l_extendedprice * (1 - l_discount)) AS revenue
        FROM lineitem {_J_ORD} {_J_CUST} {_J_SUPP}
        WHERE ((s_nation = 'FRANCE' AND c_nation = 'GERMANY')
            OR (s_nation = 'GERMANY' AND c_nation = 'FRANCE'))
          AND l_shipdate >= '1995-01-01' AND l_shipdate <= '1996-12-31'
        GROUP BY s_nation, c_nation, EXTRACT(YEAR FROM l_shipdate)
        ORDER BY s_nation, c_nation, l_year
    """,
    # Q14-class: promo revenue — LIKE inside CASE, ratio of two aggregates
    # as a post-aggregation (constants adapted to this generator's p_type
    # domain: 'MEDIUM%' plays the role of 'PROMO%')
    "q14": f"""
        SELECT 100 * sum(CASE WHEN p_type LIKE 'MEDIUM%'
                              THEN l_extendedprice * (1 - l_discount)
                              ELSE 0 END)
                 / sum(l_extendedprice * (1 - l_discount)) AS promo_revenue
        FROM lineitem {_J_PART}
        WHERE l_shipdate >= '1995-09-01' AND l_shipdate < '1995-10-01'
    """,
    # Q19-class: discounted revenue — disjunction of conjunct blocks mixing
    # string dims and numeric metric bounds
    "q19": f"""
        SELECT sum(l_extendedprice * (1 - l_discount)) AS revenue
        FROM lineitem {_J_PART}
        WHERE (p_brand = 'Brand#12' AND l_quantity >= 1 AND l_quantity <= 11
               AND l_shipmode IN ('AIR', 'REG AIR'))
           OR (p_brand = 'Brand#23' AND l_quantity >= 10 AND l_quantity <= 20
               AND l_shipmode IN ('AIR', 'REG AIR'))
           OR (p_brand = 'Brand#34' AND l_quantity >= 20 AND l_quantity <= 30
               AND l_shipmode IN ('AIR', 'REG AIR'))
    """,
    # Q8 via EXTRACT(YEAR FROM o_orderdate) — no pre-materialized year
    # column needed (dictionary-backed EXTRACT dimension)
    "q8_extract": f"""
        SELECT EXTRACT(YEAR FROM o_orderdate) AS o_orderdate_year,
               sum(CASE WHEN s_nation = 'BRAZIL'
                        THEN l_extendedprice * (1 - l_discount)
                        ELSE 0 END) AS brazil_volume,
               sum(l_extendedprice * (1 - l_discount)) AS total_volume
        FROM lineitem {_J_ORD} {_J_CUST} {_J_SUPP} {_J_PART}
        WHERE c_region = 'AMERICA' AND p_type = 'ECONOMY ANODIZED STEEL'
          AND o_orderdate >= '1995-01-01' AND o_orderdate <= '1996-12-31'
        GROUP BY EXTRACT(YEAR FROM o_orderdate)
        ORDER BY o_orderdate_year
    """,
    # Q8-class: market share numerator/denominator via CASE over nation
    "q8": f"""
        SELECT o_orderdate_year,
               sum(CASE WHEN s_nation = 'BRAZIL'
                        THEN l_extendedprice * (1 - l_discount)
                        ELSE 0 END) AS brazil_volume,
               sum(l_extendedprice * (1 - l_discount)) AS total_volume
        FROM lineitem {_J_ORD} {_J_CUST} {_J_SUPP} {_J_PART}
        WHERE c_region = 'AMERICA' AND p_type = 'ECONOMY ANODIZED STEEL'
          AND o_orderdate >= '1995-01-01' AND o_orderdate <= '1996-12-31'
        GROUP BY o_orderdate_year
        ORDER BY o_orderdate_year
    """,
}


# ---------------------------------------------------------------------------
# pandas float64 oracle — test scales only
# ---------------------------------------------------------------------------


def flat_frame(tables):
    import pandas as pd

    li = tables["lineitem"]
    o = tables["orders"]
    okey = li["l_orderkey"]
    ckey = o["o_custkey"][okey]
    c = tables["customer"]
    s = tables["supplier"]
    p = tables["part"]
    year = (
        o["o_orderdate"].astype("datetime64[ms]").astype("datetime64[Y]")
        .astype(int) + 1970
    )
    return pd.DataFrame(
        {
            "l_orderkey": okey,
            "l_shipdate": li["l_shipdate"],
            "o_orderdate": o["o_orderdate"][okey],
            "o_orderdate_year": year[okey],
            "o_orderpriority": o["o_orderpriority"][okey],
            "c_custkey": c["c_custkey"][ckey],
            "c_name": c["c_name"][ckey],
            "c_mktsegment": c["c_mktsegment"][ckey],
            "c_nation": c["c_nation"][ckey],
            "c_region": c["c_region"][ckey],
            "s_nation": s["s_nation"][li["l_suppkey"]],
            "s_region": s["s_region"][li["l_suppkey"]],
            "p_brand": p["p_brand"][li["l_partkey"]],
            "p_type": p["p_type"][li["l_partkey"]],
            "l_returnflag": li["l_returnflag"],
            "l_linestatus": li["l_linestatus"],
            "l_shipmode": li["l_shipmode"],
            **{
                m: np.asarray(li[m], dtype=np.float64)
                for m in FLAT_METRICS
            },
        }
    )


def _ms(s: str) -> int:
    return int(np.datetime64(s, "ms").astype(np.int64))


def oracle(f, name: str):
    """float64 reference result for QUERIES[name] over flat_frame output."""
    rev = f.l_extendedprice * (1 - f.l_discount)
    if name == "q1":
        m = f.l_shipdate <= _ms("1998-09-02")
        g = f[m].assign(
            disc_price=rev[m],
            charge=rev[m] * (1 + f.l_tax[m]),
        )
        out = g.groupby(["l_returnflag", "l_linestatus"], as_index=False).agg(
            sum_qty=("l_quantity", "sum"),
            sum_base_price=("l_extendedprice", "sum"),
            sum_disc_price=("disc_price", "sum"),
            sum_charge=("charge", "sum"),
            avg_qty=("l_quantity", "mean"),
            avg_price=("l_extendedprice", "mean"),
            avg_disc=("l_discount", "mean"),
            count_order=("l_quantity", "count"),
        )
        return out.sort_values(["l_returnflag", "l_linestatus"]).reset_index(
            drop=True
        )
    if name == "q3":
        m = (
            (f.c_mktsegment == "BUILDING")
            & (f.o_orderdate < _ms("1995-03-15"))
            & (f.l_shipdate > _ms("1995-03-15"))
        )
        g = (
            f[m].assign(revenue=rev[m])
            .groupby("l_orderkey", as_index=False)["revenue"].sum()
        )
        return g.sort_values("revenue", ascending=False).head(10).reset_index(
            drop=True
        )
    if name == "q10":
        m = (
            (f.o_orderdate >= _ms("1993-10-01"))
            & (f.o_orderdate < _ms("1994-01-01"))
            & (f.l_returnflag == "R")
        )
        g = (
            f[m].assign(revenue=rev[m])
            .groupby(["c_custkey", "c_name", "c_nation"], as_index=False)[
                "revenue"
            ].sum()
        )
        return g.sort_values("revenue", ascending=False).head(20).reset_index(
            drop=True
        )
    if name == "q5":
        m = (
            (f.c_region == "ASIA") & (f.s_region == "ASIA")
            & (f.o_orderdate >= _ms("1994-01-01"))
            & (f.o_orderdate < _ms("1995-01-01"))
        )
        g = (
            f[m].assign(revenue=rev[m])
            .groupby("s_nation", as_index=False)["revenue"].sum()
        )
        return g.sort_values("revenue", ascending=False).reset_index(drop=True)
    if name == "q6":
        m = (
            (f.l_shipdate >= _ms("1994-01-01"))
            & (f.l_shipdate < _ms("1995-01-01"))
            & (f.l_discount >= 0.05) & (f.l_discount <= 0.07)
            & (f.l_quantity < 24)
        )
        return float((f.l_extendedprice[m] * f.l_discount[m]).sum())
    if name == "q12":
        m = (
            f.l_shipmode.isin(["MAIL", "SHIP"])
            & (f.l_shipdate >= _ms("1994-01-01"))
            & (f.l_shipdate < _ms("1995-01-01"))
        )
        g = f[m]
        high = g.o_orderpriority.isin(["1-URGENT", "2-HIGH"])
        out = (
            g.assign(high=high.astype(np.int64), low=(~high).astype(np.int64))
            .groupby("l_shipmode", as_index=False)
            .agg(high_line_count=("high", "sum"), low_line_count=("low", "sum"))
        )
        return out.sort_values("l_shipmode").reset_index(drop=True)
    if name == "q7":
        m = (
            (
                ((f.s_nation == "FRANCE") & (f.c_nation == "GERMANY"))
                | ((f.s_nation == "GERMANY") & (f.c_nation == "FRANCE"))
            )
            & (f.l_shipdate >= _ms("1995-01-01"))
            & (f.l_shipdate <= _ms("1996-12-31"))
        )
        g = f[m]
        l_year = (
            np.asarray(g.l_shipdate, dtype="datetime64[ms]")
            .astype("datetime64[Y]")
            .astype(int)
            + 1970
        )
        out = (
            g.assign(l_year=l_year, revenue=rev[m])
            .groupby(["s_nation", "c_nation", "l_year"], as_index=False)[
                "revenue"
            ]
            .sum()
        )
        return out.sort_values(
            ["s_nation", "c_nation", "l_year"]
        ).reset_index(drop=True)
    if name == "q14":
        m = (f.l_shipdate >= _ms("1995-09-01")) & (
            f.l_shipdate < _ms("1995-10-01")
        )
        g = f[m]
        grev = rev[m]
        promo = np.where(
            g.p_type.str.startswith("MEDIUM"), grev, 0.0
        ).sum()
        return float(100.0 * promo / grev.sum())
    if name == "q19":
        block = lambda brand, lo, hi: (
            (f.p_brand == brand)
            & (f.l_quantity >= lo)
            & (f.l_quantity <= hi)
            & f.l_shipmode.isin(["AIR", "REG AIR"])
        )
        m = block("Brand#12", 1, 11) | block("Brand#23", 10, 20) | block(
            "Brand#34", 20, 30
        )
        return float(rev[m].sum())
    if name == "q8":
        m = (
            (f.c_region == "AMERICA")
            & (f.p_type == "ECONOMY ANODIZED STEEL")
            & (f.o_orderdate >= _ms("1995-01-01"))
            & (f.o_orderdate <= _ms("1996-12-31"))
        )
        g = f[m]
        grev = rev[m]
        out = (
            g.assign(
                brazil_volume=np.where(g.s_nation == "BRAZIL", grev, 0.0),
                total_volume=grev,
            )
            .groupby("o_orderdate_year", as_index=False)
            .agg(
                brazil_volume=("brazil_volume", "sum"),
                total_volume=("total_volume", "sum"),
            )
        )
        return out.sort_values("o_orderdate_year").reset_index(drop=True)
    raise KeyError(name)
