"""Star Schema Benchmark (SSB): normalized tables, star declaration, the 13
queries Q1.1-Q4.3 in joined-SQL form, and pandas oracles.

Reference parity: the reference's test/benchmark corpus is TPC-H/SSB-style
star queries over a Druid datasource that is the *denormalized* star, with
the normalized tables + star-schema JSON declared in the DDL so JoinTransform
can eliminate the dimension joins (SURVEY.md §2 JoinTransform/StarSchema rows,
§4 TPCH suites `[U]`; BASELINE.md configs #2 and the SSB north star).  Here:

* `gen_tables(scale)` builds the normalized star (lineorder fact + dwdate /
  customer / supplier / part dims; "dwdate" because DATE is a SQL keyword —
  several SSB kits rename it the same way).
* `flat_columns(tables)` pre-joins it into the dictionary-encoded flat
  datasource (the "Druid index"): string attributes become int32 codes via
  per-attribute dictionaries built on the SMALL dim tables, then gathered
  through the fact's foreign keys — no 6M-row string materialization.
* `register(ctx, ...)` registers the flat fact (with the star schema) plus
  the four dimension tables, so joined SQL resolves and collapses.
* `QUERIES` are the 13 SSB queries written AS JOINS — executing them
  exercises parse -> star-join elimination -> filter/agg pushdown -> kernels.
  Filter constants are adapted to this generator's value domains; the query
  *shapes* (join pattern, filter arity, group-bys, ordering) follow the SSB
  spec.
"""

from __future__ import annotations

from typing import Dict, Tuple

import numpy as np

from ..catalog.segment import DimensionDict
from ..catalog.star import FunctionalDependency, StarRelationInfo, StarSchemaInfo

_MS_DAY = 86_400_000

REGIONS = np.array(["AFRICA", "AMERICA", "ASIA", "EUROPE", "MIDDLE EAST"])
NATIONS_BY_REGION = {
    "AFRICA": ["ALGERIA", "ETHIOPIA", "KENYA", "MOROCCO", "MOZAMBIQUE"],
    "AMERICA": ["ARGENTINA", "BRAZIL", "CANADA", "PERU", "UNITED STATES"],
    "ASIA": ["CHINA", "INDIA", "INDONESIA", "JAPAN", "VIETNAM"],
    "EUROPE": ["FRANCE", "GERMANY", "ROMANIA", "RUSSIA", "UNITED KINGDOM"],
    "MIDDLE EAST": ["EGYPT", "IRAN", "IRAQ", "JORDAN", "SAUDI ARABIA"],
}

# attribute -> owning dim table, foreign-key column on the fact
DIM_ATTRS = {
    "d_year": ("dwdate", "lo_orderdate"),
    "d_yearmonthnum": ("dwdate", "lo_orderdate"),
    "d_yearmonth": ("dwdate", "lo_orderdate"),
    "d_weeknuminyear": ("dwdate", "lo_orderdate"),
    "c_region": ("customer", "lo_custkey"),
    "c_nation": ("customer", "lo_custkey"),
    "c_city": ("customer", "lo_custkey"),
    "s_region": ("supplier", "lo_suppkey"),
    "s_nation": ("supplier", "lo_suppkey"),
    "s_city": ("supplier", "lo_suppkey"),
    "p_mfgr": ("part", "lo_partkey"),
    "p_category": ("part", "lo_partkey"),
    "p_brand1": ("part", "lo_partkey"),
}

FLAT_DIMS = list(DIM_ATTRS)
FLAT_METRICS = [
    "lo_quantity", "lo_extendedprice", "lo_discount", "lo_revenue",
    "lo_supplycost",
    # FK retained on the flat fact for approx-distinct workloads
    # (BASELINE configs #3/#5: HLL/theta over lo_custkey)
    "lo_custkey",
]

STAR_SCHEMA = StarSchemaInfo(
    fact_table="lineorder",
    relations=(
        StarRelationInfo("dwdate", (("lo_orderdate", "d_datekey"),)),
        StarRelationInfo("customer", (("lo_custkey", "c_custkey"),)),
        StarRelationInfo("supplier", (("lo_suppkey", "s_suppkey"),)),
        StarRelationInfo("part", (("lo_partkey", "p_partkey"),)),
    ),
    functional_dependencies=(
        FunctionalDependency("customer", "c_city", "c_nation"),
        FunctionalDependency("customer", "c_nation", "c_region"),
        FunctionalDependency("supplier", "s_city", "s_nation"),
        FunctionalDependency("supplier", "s_nation", "s_region"),
        FunctionalDependency("part", "p_brand1", "p_category"),
        FunctionalDependency("part", "p_category", "p_mfgr"),
        FunctionalDependency("dwdate", "d_datekey", "d_year"),
    ),
)


def _geo(n: int, rng) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    reg = rng.choice(REGIONS, size=n)
    nation = np.empty(n, dtype=object)
    for r in REGIONS:
        m = reg == r
        nation[m] = rng.choice(
            np.array(NATIONS_BY_REGION[r]), size=int(m.sum())
        )
    city = np.char.add(
        np.asarray(nation, dtype=str), rng.integers(0, 10, size=n).astype(str)
    )
    return reg.astype(object), nation, city.astype(object)


def gen_tables(scale: float = 0.01, seed: int = 7) -> Dict[str, Dict[str, np.ndarray]]:
    """Normalized SSB star at ~SF `scale` (SF1: 6M lineorder rows).  Keys are
    dense 0..n-1 so the pre-join is a direct gather.

    Materializes the WHOLE fact host-side — use at test scales.  Large
    scale factors go through `register_streamed`, which
    generate and encode the fact chunk-by-chunk."""
    rng = np.random.default_rng(seed)
    out = gen_dim_tables(scale, rng)
    n_c = len(out["customer"]["c_custkey"])
    n_s = len(out["supplier"]["s_suppkey"])
    n_p = len(out["part"]["p_partkey"])
    out["lineorder"] = _gen_fact(
        int(6_000_000 * scale), rng, out["dwdate"]["d_datekey"], n_c, n_s, n_p
    )
    return out


def gen_dim_tables(scale: float, rng) -> Dict[str, Dict[str, np.ndarray]]:
    """The four SSB dimension tables (small at any scale factor; SF100
    customer is 3M rows — the fact is what needs streaming)."""
    # dwdate: one row per calendar day 1992-01-01 .. 1998-12-31
    d0 = np.datetime64("1992-01-01")
    days = np.arange(d0, np.datetime64("1999-01-01"), dtype="datetime64[D]")
    years = days.astype("datetime64[Y]").astype(int) + 1970
    months = days.astype("datetime64[M]").astype(int) % 12 + 1
    day_of_year = (days - days.astype("datetime64[Y]")).astype(int) + 1
    dwdate = {
        "d_datekey": days.astype("datetime64[ms]").astype(np.int64),
        "d_year": years.astype(np.int32),
        "d_yearmonthnum": (years * 100 + months).astype(np.int32),
        "d_yearmonth": np.array(
            [f"{y}-{m:02d}" for y, m in zip(years, months)], dtype=object
        ),
        "d_weeknuminyear": ((day_of_year - 1) // 7 + 1).astype(np.int32),
    }

    n_c = max(100, int(30_000 * scale))
    c_region, c_nation, c_city = _geo(n_c, rng)
    customer = {
        "c_custkey": np.arange(n_c, dtype=np.int64),
        "c_region": c_region, "c_nation": c_nation, "c_city": c_city,
    }

    n_s = max(50, int(2_000 * scale))
    s_region, s_nation, s_city = _geo(n_s, rng)
    supplier = {
        "s_suppkey": np.arange(n_s, dtype=np.int64),
        "s_region": s_region, "s_nation": s_nation, "s_city": s_city,
    }

    n_p = max(200, int(200_000 * scale))
    mfgr = np.char.add("MFGR#", rng.integers(1, 6, size=n_p).astype(str))
    category = np.char.add(
        np.asarray(mfgr, dtype=str), rng.integers(1, 6, size=n_p).astype(str)
    )
    brand = np.char.add(
        np.asarray(category, dtype=str),
        np.char.add("-", rng.integers(1, 41, size=n_p).astype(str)),
    )
    part = {
        "p_partkey": np.arange(n_p, dtype=np.int64),
        "p_mfgr": np.asarray(mfgr, dtype=object),
        "p_category": np.asarray(category, dtype=object),
        "p_brand1": np.asarray(brand, dtype=object),
    }
    return {
        "dwdate": dwdate, "customer": customer,
        "supplier": supplier, "part": part,
    }


def _gen_fact(n: int, rng, datekeys, n_c: int, n_s: int, n_p: int,
              date_lo: int = 0, date_hi: int | None = None):
    # Dates are generated PRE-SORTED (np.sort on the small int16 draw is
    # ~2x faster than even the radix argsort it replaces, measured here),
    # and every other column is iid — so sorting only the
    # date draw yields a stream identical in distribution to
    # generate-then-timesort while eliminating the per-chunk argsort AND
    # the 17-column permutation gather that dominated the ingest profile
    # (5.2 s of a 15.2 s SF2 ingest, measured round 5).  Consumers see
    # time-sorted chunks the same as before; only the row<->value pairing
    # of the synthetic stream changed (bench.py bumps its oracle-cache
    # version for exactly this).
    date_idx = np.sort(rng.integers(
        date_lo, len(datekeys) if date_hi is None else date_hi, size=n,
        dtype=np.int16,
    ))
    quantity = rng.integers(1, 51, size=n).astype(np.float32)
    extendedprice = rng.random(n).astype(np.float32) * 55_450 + 90
    discount = rng.integers(0, 11, size=n).astype(np.float32)
    return {
        "lo_orderdate": np.asarray(datekeys)[date_idx],
        # int32 keys: segment encode casts metrics to int32 anyway, so
        # generating narrow saves a 12M-row astype + half the gather bytes
        # per chunk (values are < 2^31 at any SSB scale)
        "lo_custkey": rng.integers(0, n_c, size=n, dtype=np.int32),
        "lo_suppkey": rng.integers(0, n_s, size=n, dtype=np.int32),
        "lo_partkey": rng.integers(0, n_p, size=n, dtype=np.int32),
        "lo_quantity": quantity,
        "lo_extendedprice": extendedprice,
        "lo_discount": discount,
        "lo_revenue": extendedprice * (1 - discount / 100),
        "lo_supplycost": extendedprice * 0.6,
    }


def _fk_row_index(lo, fk_col: str, table: str, dwdate) -> np.ndarray:
    fk = lo[fk_col]
    if table == "dwdate":
        base = int(dwdate["d_datekey"][0])
        return ((fk - base) // _MS_DAY).astype(np.int64)
    return fk.astype(np.int64)  # dense 0..n-1 keys


def _dim_row_index(tables, fk_col: str, table: str) -> np.ndarray:
    return _fk_row_index(
        tables["lineorder"], fk_col, table, tables["dwdate"]
    )


def _attr_dicts(tables) -> Dict[str, Tuple[DimensionDict, np.ndarray]]:
    """Per flat attribute: (dictionary, encoded dim-table codes) — built on
    the SMALL dimension tables once; fact rows gather through the FK."""
    from ..catalog.segment import code_dtype

    out: Dict[str, Tuple[DimensionDict, np.ndarray]] = {}
    for attr, (table, _) in DIM_ATTRS.items():
        vals = tables[table][attr]
        if vals.dtype.kind in ("U", "S", "O"):
            d = DimensionDict.build(list(vals))
            dim_codes = d.encode(list(vals))
        else:
            uniq = np.unique(vals.astype(np.int64))
            d = DimensionDict(values=tuple(int(v) for v in uniq))
            dim_codes = d.encode_numeric(vals)
        # narrow at the SOURCE: every fact-row gather, time-sort shuffle,
        # and segment pad downstream then moves 1-2 byte codes instead of
        # int32 (the ingest hot loop is memory-bound numpy)
        out[attr] = (d, dim_codes.astype(code_dtype(d.cardinality)))
    return out


def _flat_chunk(lo, tables, attr_dicts) -> Dict[str, np.ndarray]:
    """One chunk of fact rows -> flat encoded columns (gathers only)."""
    cols: Dict[str, np.ndarray] = {
        "lo_orderdate": lo["lo_orderdate"],
        **{m: lo[m] for m in FLAT_METRICS},
    }
    idx_cache: Dict[str, np.ndarray] = {}
    for attr, (table, fk_col) in DIM_ATTRS.items():
        if table not in idx_cache:
            idx_cache[table] = _fk_row_index(
                lo, fk_col, table, tables["dwdate"]
            )
        cols[attr] = attr_dicts[attr][1][idx_cache[table]]
    return cols


def flat_columns(tables) -> Tuple[Dict[str, np.ndarray], Dict[str, DimensionDict]]:
    """Pre-join the star into the dictionary-encoded flat datasource.

    Per attribute: build the dictionary on the dim table (small), encode the
    dim rows, gather codes through the fact FK — the flat table never holds
    6M strings.  Returns (columns, dicts) for build_datasource; string-dict
    columns arrive pre-encoded (see the build_datasource caller contract).
    """
    ad = _attr_dicts(tables)
    cols = _flat_chunk(tables["lineorder"], tables, ad)
    return cols, {attr: d for attr, (d, _) in ad.items()}


def n_fact_chunks(scale: float, chunk_rows: int) -> int:
    return -(-int(6_000_000 * scale) // chunk_rows)


def gen_fact_chunk(ci: int, scale: float, seed: int, chunk_rows: int,
                   tables):
    """Fact chunk `ci` from its own deterministic stream
    default_rng((seed, SSB_FACT_STREAM, ci)) — reproducible given the SAME
    (scale, seed, chunk_rows), so the chunked ORACLE must iterate with the
    chunk geometry the ingest used (both bench callers do), and any chunk
    can be produced on any worker process.

    Chunk ci covers ITS slice of the date span — events arrive in time
    order, exactly how Druid ingests (segments ARE time partitions):
    date-derived predicates then prune across the WHOLE stream, not just
    within a chunk.  Slices are proportional to ROW position (not chunk
    index), so a ragged last chunk gets a proportionally narrower slice
    and per-day density stays uniform over the span.  This is the ONE
    definition of the chunk geometry — ingest (serial and parallel) and
    oracle all draw from here."""
    n = int(6_000_000 * scale)
    datekeys = tables["dwdate"]["d_datekey"]
    n_days = len(datekeys)
    start = ci * chunk_rows
    rows = min(chunk_rows, n - start)
    rng = np.random.default_rng((seed, _FACT_STREAM, ci))
    lo = (start * n_days) // n
    hi = max(lo + 1, ((start + rows) * n_days) // n)
    return _gen_fact(
        rows, rng, datekeys,
        len(tables["customer"]["c_custkey"]),
        len(tables["supplier"]["s_suppkey"]),
        len(tables["part"]["p_partkey"]),
        lo, hi,
    )


def fact_chunks(scale: float, seed: int, chunk_rows: int, tables):
    """Generator of lineorder chunks at SF `scale` without ever holding the
    full fact (one gen_fact_chunk per step)."""
    for ci in range(n_fact_chunks(scale, chunk_rows)):
        yield gen_fact_chunk(ci, scale, seed, chunk_rows, tables)


_FACT_STREAM = 90_001  # spawn-key tag separating fact chunks from dim draws


def _sorted_flat_chunk(ci, scale, seed, chunk_rows, tables, ad):
    """Chunk ci: generate -> flat-encode -> time-sort.  The one body both
    the serial and the parallel ingest paths run."""
    c = _flat_chunk(
        gen_fact_chunk(ci, scale, seed, chunk_rows, tables), tables, ad
    )
    dates = c["lo_orderdate"]
    # _gen_fact emits pre-sorted dates (see its docstring); the O(n) check
    # keeps this function correct for any other chunk source, falling back
    # to the radix argsort + permutation gather only when actually needed
    if np.all(dates[1:] >= dates[:-1]):
        return c
    day = ((dates - dates.min()) // _MS_DAY).astype(np.int16)
    order = np.argsort(day, kind="stable")
    return {k: np.asarray(v)[order] for k, v in c.items()}


def register_streamed(ctx, scale: float, seed: int = 7,
                      rows_per_segment: int = 1 << 19,
                      chunk_rows: int = 1 << 22,
                      workers: int | None = None):
    """Register the SSB star at a LARGE scale factor: the fact is
    generated, encoded, and segmented chunk-by-chunk through the SHARDED
    ingest pipeline (`ingest.shard.build_datasource_sharded`, ISSUE 8
    follow-up 2(a)) — never materialized whole.  Chunks are date-sliced
    (fact_chunks) and time-sorted before segmenting, so a segment spans
    roughly 1/(8 x n_chunks) of the date range — date-derived predicates
    prune via zone maps across the whole stream.

    Workers are THREADS (the sharded pipeline's contract): the old fork
    pool — and its fork-vs-live-JAX deadlock hazard plus the
    SD_INGEST_WORKERS opt-in gate — is retired.  `workers=None` resolves
    via `ingest.shard.sharded_ingest_workers` (SD_INGEST_WORKERS env >
    cpu count); `workers=0` forces the single-threaded inline pipeline.
    Output segments are row/code/stats-identical to the retired streamed
    path (per-shard encode through the same `build_datasource`, ordered
    reassembly).  Returns the dimension tables (for oracle use)."""
    from ..ingest.shard import build_datasource_sharded

    tables = gen_dim_tables(scale, np.random.default_rng(seed))
    ad = _attr_dicts(tables)
    dicts = {attr: d for attr, (d, _) in ad.items()}

    chunks = (
        _sorted_flat_chunk(ci, scale, seed, chunk_rows, tables, ad)
        for ci in range(n_fact_chunks(scale, chunk_rows))
    )
    ds = build_datasource_sharded(
        "lineorder", chunks,
        dimension_cols=FLAT_DIMS, metric_cols=FLAT_METRICS,
        time_col="lo_orderdate",
        rows_per_segment=rows_per_segment, dicts=dicts,
        workers=1 if workers == 0 else workers,
    )
    ctx.register_datasource(ds, star_schema=STAR_SCHEMA)
    ctx.register_table("dwdate", tables["dwdate"], time_column="d_datekey")
    for t in ("customer", "supplier", "part"):
        ctx.register_table(t, tables[t])
    return tables


def register(ctx, scale: float = 0.01, seed: int = 7,
             rows_per_segment: int = 1 << 19, tables=None,
             sort_by=("lo_orderdate",)):
    """Register the flat fact datasource (with the star schema) and the four
    normalized dimension tables into a TPUOlapContext.

    Rows are TIME-SORTED into 512K-row segments by default — exactly how
    Druid ingests (segments ARE time partitions): the date-derived SSB
    predicates (d_year, d_yearmonthnum, ...) then prune most segments via
    zone maps before any kernel runs, which is where Druid's (and the
    reference's) interactive latency comes from."""
    tables = tables if tables is not None else gen_tables(scale, seed)
    cols, dicts = flat_columns(tables)
    ctx.register_table(
        "lineorder", cols,
        dimensions=FLAT_DIMS, metrics=FLAT_METRICS,
        time_column="lo_orderdate", star_schema=STAR_SCHEMA,
        rows_per_segment=rows_per_segment, dicts=dicts,
        sort_by=list(sort_by),
    )
    ctx.register_table("dwdate", tables["dwdate"], time_column="d_datekey")
    for t in ("customer", "supplier", "part"):
        ctx.register_table(t, tables[t])
    return tables


# ---------------------------------------------------------------------------
# The 13 SSB queries, joined form (constants adapted to gen_tables domains)
# ---------------------------------------------------------------------------

_J_DATE = "JOIN dwdate ON lo_orderdate = d_datekey"
_J_CUST = "JOIN customer ON lo_custkey = c_custkey"
_J_SUPP = "JOIN supplier ON lo_suppkey = s_suppkey"
_J_PART = "JOIN part ON lo_partkey = p_partkey"

QUERIES: Dict[str, str] = {
    "q1_1": f"""
        SELECT sum(lo_extendedprice * lo_discount) AS revenue
        FROM lineorder {_J_DATE}
        WHERE d_year = 1993 AND lo_discount BETWEEN 1 AND 3
          AND lo_quantity < 25
    """,
    "q1_2": f"""
        SELECT sum(lo_extendedprice * lo_discount) AS revenue
        FROM lineorder {_J_DATE}
        WHERE d_yearmonthnum = 199401 AND lo_discount BETWEEN 4 AND 6
          AND lo_quantity BETWEEN 26 AND 35
    """,
    "q1_3": f"""
        SELECT sum(lo_extendedprice * lo_discount) AS revenue
        FROM lineorder {_J_DATE}
        WHERE d_weeknuminyear = 6 AND d_year = 1994
          AND lo_discount BETWEEN 5 AND 7 AND lo_quantity BETWEEN 26 AND 35
    """,
    "q2_1": f"""
        SELECT sum(lo_revenue) AS revenue, d_year, p_brand1
        FROM lineorder {_J_DATE} {_J_PART} {_J_SUPP}
        WHERE p_category = 'MFGR#12' AND s_region = 'AMERICA'
        GROUP BY d_year, p_brand1 ORDER BY d_year, p_brand1
    """,
    "q2_2": f"""
        SELECT sum(lo_revenue) AS revenue, d_year, p_brand1
        FROM lineorder {_J_DATE} {_J_PART} {_J_SUPP}
        WHERE p_brand1 BETWEEN 'MFGR#22-1' AND 'MFGR#22-8'
          AND s_region = 'ASIA'
        GROUP BY d_year, p_brand1 ORDER BY d_year, p_brand1
    """,
    "q2_3": f"""
        SELECT sum(lo_revenue) AS revenue, d_year, p_brand1
        FROM lineorder {_J_DATE} {_J_PART} {_J_SUPP}
        WHERE p_brand1 = 'MFGR#22-9' AND s_region = 'EUROPE'
        GROUP BY d_year, p_brand1 ORDER BY d_year, p_brand1
    """,
    "q3_1": f"""
        SELECT c_nation, s_nation, d_year, sum(lo_revenue) AS revenue
        FROM lineorder {_J_CUST} {_J_SUPP} {_J_DATE}
        WHERE c_region = 'ASIA' AND s_region = 'ASIA'
          AND d_year >= 1992 AND d_year <= 1997
        GROUP BY c_nation, s_nation, d_year
        ORDER BY d_year ASC, revenue DESC
    """,
    "q3_2": f"""
        SELECT c_city, s_city, d_year, sum(lo_revenue) AS revenue
        FROM lineorder {_J_CUST} {_J_SUPP} {_J_DATE}
        WHERE c_nation = 'UNITED STATES' AND s_nation = 'UNITED STATES'
          AND d_year >= 1992 AND d_year <= 1997
        GROUP BY c_city, s_city, d_year
        ORDER BY d_year ASC, revenue DESC
    """,
    "q3_3": f"""
        SELECT c_city, s_city, d_year, sum(lo_revenue) AS revenue
        FROM lineorder {_J_CUST} {_J_SUPP} {_J_DATE}
        WHERE c_city IN ('UNITED KINGDOM1', 'UNITED KINGDOM5')
          AND s_city IN ('UNITED KINGDOM1', 'UNITED KINGDOM5')
          AND d_year >= 1992 AND d_year <= 1997
        GROUP BY c_city, s_city, d_year
        ORDER BY d_year ASC, revenue DESC
    """,
    "q3_4": f"""
        SELECT c_city, s_city, d_year, sum(lo_revenue) AS revenue
        FROM lineorder {_J_CUST} {_J_SUPP} {_J_DATE}
        WHERE c_city IN ('UNITED KINGDOM1', 'UNITED KINGDOM5')
          AND s_city IN ('UNITED KINGDOM1', 'UNITED KINGDOM5')
          AND d_yearmonth = '1997-12'
        GROUP BY c_city, s_city, d_year
        ORDER BY d_year ASC, revenue DESC
    """,
    "q4_1": f"""
        SELECT d_year, c_nation, sum(lo_revenue - lo_supplycost) AS profit
        FROM lineorder {_J_CUST} {_J_SUPP} {_J_PART} {_J_DATE}
        WHERE c_region = 'AMERICA' AND s_region = 'AMERICA'
          AND (p_mfgr = 'MFGR#1' OR p_mfgr = 'MFGR#2')
        GROUP BY d_year, c_nation ORDER BY d_year, c_nation
    """,
    "q4_2": f"""
        SELECT d_year, s_nation, p_category,
               sum(lo_revenue - lo_supplycost) AS profit
        FROM lineorder {_J_CUST} {_J_SUPP} {_J_PART} {_J_DATE}
        WHERE c_region = 'AMERICA' AND s_region = 'AMERICA'
          AND (d_year = 1997 OR d_year = 1998)
          AND (p_mfgr = 'MFGR#1' OR p_mfgr = 'MFGR#2')
        GROUP BY d_year, s_nation, p_category
        ORDER BY d_year, s_nation, p_category
    """,
    "q4_3": f"""
        SELECT d_year, s_city, p_brand1,
               sum(lo_revenue - lo_supplycost) AS profit
        FROM lineorder {_J_CUST} {_J_SUPP} {_J_PART} {_J_DATE}
        WHERE c_region = 'AMERICA' AND s_nation = 'UNITED STATES'
          AND (d_year = 1997 OR d_year = 1998) AND p_category = 'MFGR#14'
        GROUP BY d_year, s_city, p_brand1
        ORDER BY d_year, s_city, p_brand1
    """,
}


# ---------------------------------------------------------------------------
# pandas oracle (float64, flat string form) — test-scale only
# ---------------------------------------------------------------------------


def flat_frame_chunk(tables, lo):
    """Decoded flat pandas frame for ONE fact chunk (the chunked-oracle
    unit; string attrs materialize only chunk-wide)."""
    import pandas as pd

    data = {
        "lo_orderdate": lo["lo_orderdate"],
        **{m: np.asarray(lo[m], dtype=np.float64) for m in FLAT_METRICS},
    }
    idx_cache: Dict[str, np.ndarray] = {}
    for attr, (table, fk_col) in DIM_ATTRS.items():
        if table not in idx_cache:
            idx_cache[table] = _fk_row_index(
                lo, fk_col, table, tables["dwdate"]
            )
        data[attr] = np.asarray(tables[table][attr])[idx_cache[table]]
    return pd.DataFrame(data)


def flat_frame(tables):
    """Decoded flat pandas DataFrame for oracle computation (string attrs
    materialized — use at test scales only)."""
    return flat_frame_chunk(tables, tables["lineorder"])


def merge_oracle_parts(parts):
    """Merge per-chunk `oracle` results into the full-table result.  Sound
    because every SSB aggregate is a SUM (scalar or grouped): partials
    concatenate and re-sum by the group columns."""
    import pandas as pd

    if isinstance(parts[0], float):
        return float(sum(parts))
    # drop EMPTY partials before concat: date-sliced chunks make filtered
    # queries miss whole chunks, and concat with empties promotes int
    # group columns to float
    nonempty = [p for p in parts if len(p)]
    if not nonempty:
        return parts[0]
    df = pd.concat(nonempty, ignore_index=True)
    vcol = df.columns[-1]  # oracle puts the measure last
    g = [c for c in df.columns if c != vcol]
    return df.groupby(g, as_index=False)[vcol].sum()


def oracle(f, name: str):
    """Reference result for QUERIES[name] over flat_frame output, grouped
    results sorted by their group columns (callers re-sort `got` the same
    way before comparing)."""
    q = np.asarray(f.lo_quantity)
    dc = np.asarray(f.lo_discount)
    if name == "q1_1":
        m = (f.d_year == 1993) & (dc >= 1) & (dc <= 3) & (q < 25)
        return float((f.lo_extendedprice[m] * dc[m]).sum())
    if name == "q1_2":
        m = (f.d_yearmonthnum == 199401) & (dc >= 4) & (dc <= 6) & (q >= 26) & (q <= 35)
        return float((f.lo_extendedprice[m] * dc[m]).sum())
    if name == "q1_3":
        m = ((f.d_weeknuminyear == 6) & (f.d_year == 1994)
             & (dc >= 5) & (dc <= 7) & (q >= 26) & (q <= 35))
        return float((f.lo_extendedprice[m] * dc[m]).sum())
    if name in ("q2_1", "q2_2", "q2_3"):
        if name == "q2_1":
            m = (f.p_category == "MFGR#12") & (f.s_region == "AMERICA")
        elif name == "q2_2":
            b = f.p_brand1.astype(str)
            m = (b >= "MFGR#22-1") & (b <= "MFGR#22-8") & (f.s_region == "ASIA")
        else:
            m = (f.p_brand1 == "MFGR#22-9") & (f.s_region == "EUROPE")
        return (
            f[m].groupby(["d_year", "p_brand1"]).lo_revenue.sum()
            .reset_index().rename(columns={"lo_revenue": "revenue"})
        )
    if name in ("q3_1", "q3_2", "q3_3", "q3_4"):
        yr = (f.d_year >= 1992) & (f.d_year <= 1997)
        if name == "q3_1":
            m = (f.c_region == "ASIA") & (f.s_region == "ASIA") & yr
            g = ["c_nation", "s_nation", "d_year"]
        elif name == "q3_2":
            m = ((f.c_nation == "UNITED STATES")
                 & (f.s_nation == "UNITED STATES") & yr)
            g = ["c_city", "s_city", "d_year"]
        else:
            cities = ["UNITED KINGDOM1", "UNITED KINGDOM5"]
            m = f.c_city.isin(cities) & f.s_city.isin(cities)
            m &= yr if name == "q3_3" else (f.d_yearmonth == "1997-12")
            g = ["c_city", "s_city", "d_year"]
        return (
            f[m].groupby(g).lo_revenue.sum()
            .reset_index().rename(columns={"lo_revenue": "revenue"})
        )
    if name in ("q4_1", "q4_2", "q4_3"):
        prof = f.lo_revenue - f.lo_supplycost
        if name == "q4_1":
            m = ((f.c_region == "AMERICA") & (f.s_region == "AMERICA")
                 & f.p_mfgr.isin(["MFGR#1", "MFGR#2"]))
            g = ["d_year", "c_nation"]
        elif name == "q4_2":
            m = ((f.c_region == "AMERICA") & (f.s_region == "AMERICA")
                 & f.d_year.isin([1997, 1998])
                 & f.p_mfgr.isin(["MFGR#1", "MFGR#2"]))
            g = ["d_year", "s_nation", "p_category"]
        else:
            m = ((f.c_region == "AMERICA") & (f.s_nation == "UNITED STATES")
                 & f.d_year.isin([1997, 1998]) & (f.p_category == "MFGR#14"))
            g = ["d_year", "s_city", "p_brand1"]
        return (
            f[m].assign(profit=prof).groupby(g).profit.sum().reset_index()
        )
    raise KeyError(name)
