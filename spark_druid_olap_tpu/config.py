"""Two-tier configuration: session flags + per-table options.

Reference parity (SURVEY.md §5 config row `[U]`): the reference has (1)
per-table options in `CREATE TABLE ... USING ... OPTIONS(...)` (DefaultSource
row of SURVEY.md §2) and (2) session flags registered by `DruidPlanner` under
SQLConf keys `spark.sparklinedata.druid.*` (rewrite enables, cost-model
constants, max cardinality, smile encoding, historical-query toggles).  We
mirror both tiers with dataclasses; option names keep the reference's
vocabulary where a TPU equivalent exists, and each field documents the
mapping.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple


def _log():
    from .utils.log import get_logger

    return get_logger("config")


def _current_platform() -> Optional[str]:
    """Live backend platform ("cpu"/"tpu"/...), None if jax is unavailable."""
    try:
        import jax

        return jax.devices()[0].platform
    except Exception:
        return None


def _current_device_str() -> Optional[str]:
    try:
        import jax

        return str(jax.devices()[0])
    except Exception:
        return None


@dataclasses.dataclass
class SessionConfig:
    """Session-wide planner/engine flags (the SQLConf analog)."""

    # rewrite enables (reference: per-transform enable flags)
    enable_rewrites: bool = True
    enable_topn_rewrite: bool = True  # Sort+Limit -> TopN
    enable_timeseries_rewrite: bool = True  # time-only groupby -> Timeseries
    enable_join_collapse: bool = True  # star-schema join elimination

    # approx-distinct mapping (reference: pushHLLTODruid / useApproxCountDistinct)
    approx_count_distinct_sketch: str = "hll"  # "hll" | "theta"
    hll_precision: int = 11
    theta_size: int = 4096
    # COUNT(DISTINCT x) handling: "approx" rewrites to a sketch (Druid
    # default); "exact" uses the exact distinct path; "error" rejects.
    count_distinct_mode: str = "approx"
    # APPROX_QUANTILE sample size K (quantilesDoublesSketch k analog):
    # rank error ~ O(sqrt(p(1-p)/K)), ~±1.5% at the median for 1024
    quantiles_k: int = 1024
    # When the planner cannot rewrite a query (unconforming join, an
    # expression no transform covers), interpret the logical plan over
    # decoded host frames instead of erroring — the reference's vanilla-
    # Spark fallback (SURVEY.md §3.2).  False surfaces RewriteError
    # (useful for asserting pushdown coverage).
    fallback_execution: bool = True
    # ceiling on the SUMMED base-table rows a host-fallback query may touch:
    # the fallback is single-threaded pandas with full materialization, and
    # silently grinding through an arbitrarily large input is worse than a
    # clear error telling the user they left the accelerated path.
    # 0 disables the guard.
    fallback_max_rows: int = 50_000_000
    # device-assist inside the fallback (Aggregate subtrees run on the
    # engine, only the aggregated frame is interpreted host-side) engages
    # above this input-row count.  Below it the host interpreter is
    # instant anyway AND float64-exact — rank/comparison windows over
    # f32-accumulated device sums could tie differently on tiny frames.
    device_assist_min_rows: int = 1 << 18
    # Assist decision constants (see api._run_fallback.device_subplan).
    # cost_per_row_interp: ONE vectorized pandas grouped-agg pass over the
    # subtree's base (~0.1 us/row measured on this container — NOT the
    # whole fallback query, which runs several passes).  A deliberate
    # under-estimate: assist engages only when the modelled engine side
    # wins 2x (never-slower bar).  cost_per_group_decode: host cost per
    # RESULT group the assisted path re-pays (dictionary decode + frame
    # build + downstream interpretation) — this is what makes
    # G ~ rows/4-shaped subtrees (TPC-H q18) a wash that assist must
    # decline, while G << rows shapes (q2's rank base) win 15-100x.
    # Both run on the HOST on every backend, so neither flips with the
    # device platform.
    cost_per_row_interp: float = 0.1
    cost_per_group_decode: float = 1.0
    # bypass the assist cost gate (row floor still applies): the bench's
    # crossover probe needs to MEASURE the losing regimes the gate exists
    # to avoid; not a user knob
    device_assist_force: bool = False

    # cost model (reference: DruidQueryCostModel constants via SQLConf).
    # Units are MICROSECONDS so the constants are physically measurable:
    # `plan/calibrate.py` measures them on the live backend and
    # `SessionConfig.load_calibrated()` picks up the saved values; the
    # defaults below are v5e-flavoured estimates used until calibration runs.
    cost_model_enabled: bool = True
    dense_max_groups: int = 1 << 17  # dense one-hot vs scatter cutover
    onehot_vmem_budget_mb: int = 32
    # device VMEM capacity class, MiB: the budget kernel tile sets must
    # fit (double-buffered) — ~16 MiB/core on v5e-class parts.  The
    # calibrated files carry the authoritative per-platform figure as
    # `vmem_budget_bytes`; this default is the fallback graftlint's
    # resource-budget pass (GL12xx) and future tile autotuning read when
    # no calibration exists for the target platform
    vmem_budget_mb: int = 16
    # us per row per 128-wide group tile for the dense one-hot kernel (MXU)
    cost_per_row_dense: float = 1e-4
    # us per row for the scatter (segment-sum) kernel — serializes on TPU
    cost_per_row_scatter: float = 0.05
    # us per row for scatter at a LARGE group domain (state no longer fits
    # cache: random writes miss).  The model interpolates per-row scatter
    # cost log-linearly in G between (scatter_lo_groups, cost_per_row_
    # scatter) and (scatter_hi_groups, cost_per_row_scatter_hi) — measured
    # on CPU: 0.0015us/row at G=1K vs 0.0071us/row at G=2M, a 5x cliff the
    # flat model missed (it routed SSB q3_2 SF100 to scatter: 12.1s, losing
    # to pandas).  On TPU scatter serializes regardless, so the default is
    # flat until hardware calibration says otherwise.
    cost_per_row_scatter_hi: float = 0.05
    scatter_lo_groups: int = 1024
    scatter_hi_groups: int = 1 << 21
    # us per row for the sort-compaction (sparse) path
    cost_per_row_sparse: float = 5e-3
    # us per row for the FILTER-COMPACTION pass (mask -> survivor slots):
    # the linear scan sparse pays over ALL rows before sorting only the
    # survivors.  Estimate until calibrated; the dense/scatter/compact
    # ratio is what routes selective high-cardinality queries
    cost_per_row_compact: float = 2e-3
    # us per group of dense scatter state (alloc + merge traffic)
    cost_per_group_state: float = 2e-5
    # merge-collective throughput, bytes per us (ICI ring allreduce)
    collective_bytes_per_us: float = 40_000.0
    # cross-slice merge throughput, bytes per us (DCN allreduce between
    # slices).  ~25 GB/s per-host DCN vs ~100+ GB/s ICI: the gap is what
    # makes the hierarchical merge tree (slice-local psum first, then one
    # small state over DCN) win once state_bytes is large enough —
    # plan/cost.choose_merge_tree prices both trees with this constant
    dcn_bytes_per_us: float = 25_000.0
    # fixed overhead of one SPMD dispatch + multi-device host gather, us
    cost_dispatch_us: float = 300.0
    # host->device transfer bandwidth, bytes/s.  Default is PCIe-class;
    # calibration measures the real link (the round-5 tunneled chip: 46
    # MB/s, 300x below PCIe — the constant that decides whether shipping a
    # fallback subtree's base to the device can ever pay for itself)
    h2d_bytes_per_s: float = 1e10

    # result guards (reference: maxCardinality / maxResultCardinality)
    max_result_cardinality: int = 1 << 22
    # non-aggregate queries (reference: nonAggregateQueryHandling = push/scan)
    non_aggregate_query_handling: str = "scan"  # "scan" | "error"

    # distributed execution (reference: queryHistoricalServers,
    # numSegmentsPerHistoricalQuery -> mesh shape decisions).  With
    # prefer_distributed=True (default) the cost model picks the mesh
    # whenever the modelled distributed cost beats single-device cost.
    prefer_distributed: bool = True
    mesh_data_axis: Optional[int] = None
    mesh_groups_axis: int = 1

    # result-level cache (the Druid broker's result cache analog: repeated
    # dashboard queries skip execution entirely).  Entries key on query JSON
    # + datasource schema signature, so re-ingestion can never serve stale
    # rows.  0 disables.
    result_cache_entries: int = 64
    # delta-aware result-cache reuse (serve/result_cache.py, ISSUE 8): on
    # a streamed append the cache serves `(cached historical partial) ⊕
    # (fresh delta partials)` instead of invalidating outright — the
    # refresh scans ONLY the appended segments.  Requires the cached
    # entry's dictionaries to be unchanged (a dictionary extension remaps
    # code spaces and is a full miss).  False restores version-exact
    # hits only.
    result_cache_delta_reuse: bool = True

    # -- async serving core (serve/, ISSUE 8) -------------------------------
    # micro-batch query fusion: compatible concurrent queries (same
    # datasource + segment-set signature) queue for this many ms and
    # execute as ONE fused device program, amortizing the per-dispatch
    # round trip N ways.  0 disables (every query dispatches solo —
    # the right default for single-client sessions; the server/bench
    # enable it for concurrent dashboard traffic).
    fusion_window_ms: float = 0.0
    # ceiling on queries fused into one device program (compile time and
    # demux cost grow with the batch)
    fusion_max_batch: int = 16
    # priority lanes (serve/lanes.py): separate admission slot pools so
    # cheap dashboard queries (TopN/timeseries/small groupBys) are never
    # queued behind SF100-scale scans.  A query routes to the heavy lane
    # when its in-scope row count exceeds lane_heavy_rows (scans and
    # groupBys); TopN/timeseries/metadata queries stay interactive.
    lane_interactive_slots: int = 6
    lane_heavy_slots: int = 2
    lane_heavy_rows: int = 4 << 20

    # -- query-lifecycle resilience (resilience.py) -------------------------
    # wall-clock budget per query; 0 = unbounded.  The wire path's
    # Druid-native `context.timeout` (ms) overrides it per request.
    query_timeout_ms: int = 0
    # serving admission control: bounded slot pool + queue-wait timeout;
    # a full pool answers 503 + Retry-After instead of piling handler
    # threads behind a slow device
    max_concurrent_queries: int = 8
    admission_queue_timeout_ms: int = 2000
    # device circuit breaker: consecutive TRANSIENT failures before queries
    # route straight to the host fallback, and how long the breaker stays
    # open before a half-open probe may try the device again
    breaker_failure_threshold: int = 3
    breaker_cooldown_ms: int = 2000
    # transient-failure retry budget for one device execution (attempts
    # TOTAL, so 2 = one retry — the historical behavior) and the base
    # backoff between attempts (doubles per retry, clipped to the active
    # deadline's remaining budget)
    retry_max_attempts: int = 2
    retry_backoff_ms: float = 25.0
    # deadline-bounded PARTIAL answers (ISSUE 7): when a deadline expires
    # at an executor checkpoint, merge the per-segment partials
    # accumulated so far and return them stamped partial=True with a
    # coverage fraction, instead of erroring.  Every aggregate state in
    # the engine is mergeable, so "the rows seen so far" is a safe
    # answer (Partial Partial Aggregates).  False restores hard
    # DeadlineExceeded errors; the wire context key `partialResults`
    # overrides per request.
    partial_results: bool = True

    # -- real-time ingestion tier (ingest/) ---------------------------------
    # rows per published delta segment before an append batch splits; the
    # floor is catalog.segment.ROW_PAD (padding granularity)
    delta_seal_rows: int = 1 << 16
    # background compaction: sweep period and the delta-row backlog below
    # which a datasource is left alone (compacting single tiny deltas
    # would churn versions — and result caches — for nothing)
    compaction_interval_s: float = 5.0
    compaction_min_delta_rows: int = 1 << 15
    # rows per historical segment compaction emits
    compaction_rows_per_segment: int = 1 << 19
    # ingest admission: a SEPARATE small slot pool so streamed appends
    # (encode + possible dictionary-extension remap) can't starve query
    # slots, and a query burst can't starve ingest
    max_concurrent_ingests: int = 2
    ingest_queue_timeout_ms: int = 2000

    # -- durable storage tier (storage.py / ingest/wal.py, ISSUE 13) --------
    # root directory of the persistent tier: per-datasource append WALs +
    # versioned columnar snapshots.  None (default) keeps the catalog
    # purely in-process — nothing survives a restart, exactly the
    # pre-ISSUE-13 behavior.  When set, a context constructor RECOVERS:
    # snapshot mmap-load + WAL replay, zero re-ingest/re-encode.
    storage_dir: Optional[str] = None
    # fsync each WAL record before the publish/ack (the durability
    # contract).  False trades the acked-append-survives-crash guarantee
    # for append latency — tests and bulk loads only.
    storage_fsync: bool = True
    # background snapshot-flush sweep (ISSUE 14 satellite): every
    # `snapshot_flush_s` seconds a daemon thread flushes any datasource
    # whose published version moved past its on-disk snapshot, so dirty
    # delta segments reach disk without waiting for the next
    # registration or compaction (a restart then mmaps instead of
    # replaying them from the WAL).  0 (default) disables the timer;
    # appends stay durable either way via the WAL.
    snapshot_flush_s: float = 0.0

    # -- cluster tier (cluster/, ISSUE 16) ----------------------------------
    # replicas per segment in the broker's assignment map (rendezvous
    # hashing over historical node ids); clamped to the live node count
    cluster_replication: int = 2
    # per-replica RPC budget: one scatter attempt must answer within
    # this or the broker fails over to the next replica in the chain
    cluster_rpc_timeout_ms: float = 5000.0
    # extra attempts across the replica chain after the first failure
    # (the chain is bounded by replication anyway; this caps re-walks)
    cluster_rpc_retries: int = 1
    # tail-latency hedging: if the primary replica hasn't answered
    # within this, the broker issues the same fetch to the next replica
    # and takes whichever returns first.  0 disables hedging.
    cluster_hedge_ms: float = 0.0
    # per-historical circuit breaker (generalizes the device/mesh
    # breakers): consecutive scatter failures to one node before its
    # breaker opens, and how long it cools before a probe
    cluster_breaker_failures: int = 3
    cluster_breaker_cooldown_ms: float = 2000.0
    # federated observability scrape (ISSUE 19): per-node budget for the
    # broker's /status/metrics?cluster=1 and /status/profile?cluster=1
    # fan-out — a node slower than this is stamped stale for the scrape
    cluster_scrape_timeout_ms: float = 2000.0

    # -- observability (obs/) -----------------------------------------------
    # slow-query log: a finished query whose span-tree total exceeds this
    # logs the rendered tree at WARNING through utils/log.py; 0 disables
    slow_query_ms: float = 0.0
    # finished span trees retained for GET /druid/v2/trace/{query_id}
    # (FIFO eviction past the capacity)
    trace_ring_capacity: int = 64
    # emit-only OTLP export (ROADMAP obs follow-up (d)): when set, every
    # finished trace appends one OTLP/JSON ResourceSpans line to this
    # file (obs/otlp.py) — no collector or network dependency; None
    # disables
    otlp_export_path: Optional[str] = None
    # self-hosted telemetry (obs/telemetry.py, ISSUE 19): when > 0, a
    # daemon sampler flushes the metrics registry into the `__sys`
    # datasource every this-many seconds (ingest/WAL tier, rollup at
    # `second` granularity) so QPS/p99/breaker history is SQL-queryable.
    # 0 (default) never registers `__sys` and starts no thread.
    sys_sampler_s: float = 0.0
    # per-tick series cap for the `__sys` sampler (cardinality guard)
    sys_sampler_max_series: int = 512
    # age-based `__sys` retention: a second-granularity telemetry
    # segment whose NEWEST row is older than this many seconds is
    # dropped by the background compaction sweep (whole segments only —
    # never a partial rewrite), so self-hosted telemetry is a ring, not
    # a leak.  0 (default) retains everything.
    sys_retention_s: float = 0.0

    # -- performance attribution (obs/prof.py, ISSUE 9) ---------------------
    # fraction of queries sampled for HONEST device timing: a sampled
    # query pays sync points (block_until_ready) at its dispatch/fetch
    # sites so the segment_dispatch/device_fetch spans split into
    # enqueue vs device-complete time.  0 (default) adds ZERO syncs —
    # the dispatch overlap the executors engineered is never destroyed
    # by default; 1.0 profiles every query (bench receipt reps).
    prof_sample_rate: float = 0.0
    # GET /status/profile rolling window + top-K size
    profile_window_s: float = 300.0
    profile_top_k: int = 10
    # per-lane latency targets the profiler burns SLO against: the
    # fraction of a lane's queries whose wall exceeded its target is
    # that lane's burn rate.  0 disables the burn computation for a lane.
    lane_interactive_slo_ms: float = 250.0
    lane_heavy_slo_ms: float = 30_000.0
    # -- overlapped h2d transfer pipeline (exec/pipeline.py, ISSUE 10) ------
    # double-buffered segment streaming: the engine issues async
    # device placement of the NEXT dispatch batches' cold columns while
    # the current batch's program runs, and dispatches already-resident
    # batches first so cold segments stream behind live compute instead
    # of in front of it.  Results are byte-identical either way (the
    # partial-state fold order is pinned); False restores fully
    # synchronous per-batch transfers.
    transfer_pipeline: bool = True
    # prefetch lookahead, in dispatch batches
    prefetch_depth: int = 2
    # byte cap (MiB) for SPECULATIVE prefetch of next-interval segments
    # OUTSIDE the query's pruned scope (a dashboard scanning [t0, t1)
    # usually asks for the adjacent interval next).  0 disables
    # speculation; in-scope prefetch is unaffected.
    prefetch_speculative_mb: int = 0
    # -- one-dispatch arena execution (exec/arena.py, ISSUE 14) -------------
    # segment-stacked resident arena: in-scope segments of equal padded
    # shape stack into one device-resident [B, R] layout and the whole
    # scope lowers as ONE lax.scan program (partial fold inside the trace
    # in canonical batch order, donated fold-state carry, one fetch) —
    # dispatches-per-query drop from O(segments) to O(1).  Results are
    # byte-identical to the per-batch dispatch loop (the scan replicates
    # the exact f32 fold association); scopes the arena cannot host
    # (sketch aggs, non-uniform segment shapes, sparse/adaptive routes)
    # fall back to the loop path per query.  False disables globally.
    arena_execution: bool = True

    # adaptive micro-batch fusion window (ROADMAP 1(b)): when True the
    # scheduler arms the window from the observed arrival rate — no wait
    # on an idle queue, up to fusion_window_max_ms under bursts — and
    # records the decision as a `fusion_window` span event.  False keeps
    # the static fusion_window_ms.
    fusion_adaptive_window: bool = False
    # burst ceiling for the adaptive window; 0 = 4x fusion_window_ms
    fusion_window_max_ms: float = 0.0

    # provenance of the cost constants (set by load_calibrated): {path,
    # device, partial, applied, mismatch?} or None when never loaded from
    # a file — artifacts record it so "which platform routed this" is
    # always answerable (VERDICT r4 weak #5)
    calibration_meta: Optional[dict] = None

    @classmethod
    def load_calibrated(
        cls,
        path: Optional[str] = None,
        strict_device: bool = False,
        root: Optional[str] = None,
    ) -> "SessionConfig":
        """SessionConfig with measured cost constants, when a calibration
        file (plan/calibrate.py) exists AND was measured on the current
        backend device; platform-profile defaults otherwise.

        The stale-device check matters: constants measured on a TPU applied
        to the CPU backend (or vice versa) route kernels pathologically —
        the dense/scatter ratio inverts between the two backends.  With
        `strict_device=True` a mismatched file RAISES instead of warning
        (bench.py uses it so an artifact can never quietly carry
        wrong-platform routing; VERDICT r4 #8).

        The returned config carries `calibration_meta` — {path, device,
        partial, applied} — so artifacts can record where their cost
        constants came from."""
        import json
        import os

        cfg = cls()
        # `root` overrides the repo-root discovery (tests point it at a
        # tmp dir so the sidecar fallback is pinned without touching the
        # real calibration files)
        if root is None:
            root = os.path.dirname(
                os.path.dirname(os.path.abspath(__file__))
            )
        p = path or os.path.join(root, "calibration.json")

        def _read(fp):
            try:
                with open(fp) as f:
                    d = json.load(f)
            except (OSError, ValueError):
                return None
            return d if isinstance(d, dict) else None

        data = None
        primary_unreadable = False
        if os.path.exists(p):
            data = _read(p)
            primary_unreadable = data is None
        # A CPU bench run and a TPU window alternate on this host, each
        # overwriting calibration.json; plan/calibrate.py therefore also
        # saves calibration.<platform>.json (plan.calibrate.sidecar_path
        # owns the naming).  Whenever the primary file cannot serve this
        # backend — measured elsewhere, unreadable, or missing — prefer
        # the platform-matching sidecar over falling all the way back to
        # profile guesses (the round-5 TPU constants exist precisely so a
        # later CPU run cannot erase them).
        if path is None:
            cur = _current_device_str()
            if data is None or data.get("device") not in (None, cur):
                from .plan.calibrate import sidecar_path

                alt = sidecar_path(_current_platform() or "unknown", root)
                alt_data = _read(alt) if os.path.exists(alt) else None
                if alt_data is not None and alt_data.get("device") == cur:
                    p, data, primary_unreadable = alt, alt_data, False
        if primary_unreadable:
            # only warn once the sidecar fallback ALSO failed — an
            # operator reading "using the platform cost profile" must be
            # able to trust that profile guesses are really in effect
            _log().warning(
                "ignoring unreadable calibration file %s; using the "
                "platform cost profile", p,
            )
        if data is not None and data.get("device") not in (
            None,
            _current_device_str(),
        ):
            if strict_device:
                raise RuntimeError(
                    f"calibration file {p} was measured on "
                    f"{data.get('device')} but the execution backend is "
                    f"{_current_device_str()}; rerun plan/calibrate.py on "
                    "this backend (strict_device=True refuses the "
                    "platform-profile fallback)"
                )
            _log().warning(
                "ignoring calibration file %s measured on %s (current "
                "backend device is %s); using the platform cost profile — "
                "rerun plan/calibrate.py on this backend",
                p, data.get("device"), _current_device_str(),
            )
            cfg.calibration_meta = {
                "path": p,
                "device": data.get("device"),
                "partial": data.get("partial"),
                "applied": False,
                "mismatch": True,
            }
            data = None  # measured on a different backend: do not apply
        if data is not None:
            # platform profile FIRST, measured keys on top: a PARTIAL
            # calibration file (budget-clipped sweep) must fall back to
            # platform-correct values for its missing keys, not the class's
            # v5e-flavoured defaults.  Round 3's SF100 q3_2 regression came
            # from exactly this mix: measured CPU scatter cost + v5e
            # cost_per_group_state routed a 504K-group query to scatter.
            cfg.apply_platform_profile()
            for k in (
                "cost_per_row_dense",
                "cost_per_row_scatter",
                "cost_per_row_scatter_hi",
                "cost_per_row_sparse",
                "cost_per_row_compact",
                "cost_per_group_state",
                "collective_bytes_per_us",
                "dcn_bytes_per_us",
                "cost_dispatch_us",
                "h2d_bytes_per_s",
            ):
                if k in data and data[k] is not None and data[k] > 0:
                    setattr(cfg, k, float(data[k]))
            for k in ("scatter_lo_groups", "scatter_hi_groups"):
                if k in data and data[k] is not None and data[k] > 0:
                    setattr(cfg, k, int(data[k]))
            vb = data.get("vmem_budget_bytes")
            if vb is not None and vb > 0:
                cfg.vmem_budget_mb = max(1, int(vb) >> 20)
            cfg.calibration_meta = {
                "path": p,
                "device": data.get("device"),
                "partial": data.get("partial"),
                "applied": True,
            }
            return cfg
        return cfg.apply_platform_profile()

    def apply_platform_profile(self) -> "SessionConfig":
        """Overwrite (in place) the v5e-flavoured default cost constants with
        a profile matching the live backend when that backend is CPU.

        The class defaults model an MXU: dense one-hot nearly free per lane
        tile, scatter expensive (serialized updates).  XLA:CPU is the
        opposite — segment_sum streams at memory bandwidth for any G while
        the one-hot materializes B x G blocks (measured: scatter ~flat
        450 Mrows/s from G=1 to G=8008; dense 42 Mrows/s at G=8, 7 Mrows/s
        at G=64).  Without this, a fresh uncalibrated CPU session routes a
        G=8008 GroupBy to dense: ~65 s instead of ~0.3 s at SF1.  Values
        are a committed CPU calibration snapshot (plan/calibrate.py on
        TFRT_CPU; see the round-3 session notes) — a real calibration run
        still refines them."""
        if _current_platform() != "cpu":
            return self
        self.cost_per_row_dense = 0.58
        self.cost_per_row_scatter = 0.0012
        # measured on this container (8M rows, segment_sum): 0.00145us/row
        # at G=1024 rising to 0.00707us/row at G=2M as the state outgrows
        # cache — the G-dependence that routes huge-domain GroupBys off
        # raw scatter
        self.cost_per_row_scatter_hi = 0.0071
        self.scatter_lo_groups = 1024
        self.scatter_hi_groups = 1 << 21
        self.cost_per_row_sparse = 0.49
        self.cost_per_row_compact = 0.0065
        self.cost_per_group_state = 0.0023
        # "collective" on a CPU mesh is shared-memory copies and a local
        # dispatch is function-call cheap — the ICI/RPC-flavoured defaults
        # would misprice the distributed-vs-local choice
        self.collective_bytes_per_us = 10_000.0
        # a virtual slice boundary on CPU is still shared memory, but the
        # modelled DCN gap must survive so the merge-tree choice exercises
        # the same decision the pod makes
        self.dcn_bytes_per_us = 2_500.0
        self.cost_dispatch_us = 100.0
        # "h2d" on CPU is a memcpy into the runtime's buffer
        self.h2d_bytes_per_s = 2e10
        # small-frame floor only: the COST MODEL now makes the real
        # assist decision per subtree (api._run_fallback compares the
        # modelled engine kernel cost at the subtree's G against
        # rows x cost_per_row_interp).  The r4 blunt 8.4M-row threshold
        # blocked q2-class subtrees the engine wins 15-100x (tiny G over
        # a big base) to protect against q18-class losses (G ~ rows/4);
        # the model separates the two shapes directly.
        self.device_assist_min_rows = 1 << 18
        return self


@dataclasses.dataclass
class TableOptions:
    """Per-table registration options (the OPTIONS(...) map analog).

    Reference option -> field mapping:
      timeDimensionColumn      -> time_column
      druidDatasource          -> (the registered name)
      columnMapping            -> column_mapping
      functionalDependencies   -> functional_dependencies (catalog/star.py)
      starSchema               -> star_schema (catalog/star.py)
      rows per segment/historical -> rows_per_segment
      loadMetadataFromAllSegments -> eager_stats
    """

    time_column: Optional[str] = None
    dimensions: Tuple[str, ...] = ()
    metrics: Tuple[str, ...] = ()
    column_mapping: Optional[dict] = None  # source col -> datasource col
    rows_per_segment: int = 1 << 22
    eager_stats: bool = True
    star_schema: Optional[object] = None  # catalog.star.StarSchemaInfo
    functional_dependencies: Tuple = ()


DEFAULT_SESSION = SessionConfig()
