"""Two-tier configuration: session flags + per-table options.

Reference parity (SURVEY.md §5 config row `[U]`): the reference has (1)
per-table options in `CREATE TABLE ... USING ... OPTIONS(...)` (DefaultSource
row of SURVEY.md §2) and (2) session flags registered by `DruidPlanner` under
SQLConf keys `spark.sparklinedata.druid.*` (rewrite enables, cost-model
constants, max cardinality, smile encoding, historical-query toggles).  We
mirror both tiers with dataclasses; option names keep the reference's
vocabulary where a TPU equivalent exists, and each field documents the
mapping.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple


@dataclasses.dataclass
class SessionConfig:
    """Session-wide planner/engine flags (the SQLConf analog)."""

    # rewrite enables (reference: per-transform enable flags)
    enable_rewrites: bool = True
    enable_topn_rewrite: bool = True  # Sort+Limit -> TopN
    enable_timeseries_rewrite: bool = True  # time-only groupby -> Timeseries
    enable_join_collapse: bool = True  # star-schema join elimination

    # approx-distinct mapping (reference: pushHLLTODruid / useApproxCountDistinct)
    approx_count_distinct_sketch: str = "hll"  # "hll" | "theta"
    hll_precision: int = 11
    theta_size: int = 4096
    # COUNT(DISTINCT x) handling: "approx" rewrites to a sketch (Druid
    # default); "exact" uses the exact distinct path; "error" rejects.
    count_distinct_mode: str = "approx"

    # cost model (reference: DruidQueryCostModel constants via SQLConf)
    cost_model_enabled: bool = True
    dense_max_groups: int = 1 << 17  # dense one-hot vs scatter cutover
    onehot_vmem_budget_mb: int = 32
    cost_per_row_dense: float = 1.0  # relative per-row cost constants
    cost_per_row_scatter: float = 8.0
    cost_per_group_state: float = 0.5
    collective_bytes_per_us: float = 100.0  # ICI bandwidth guess for planning

    # result guards (reference: maxCardinality / maxResultCardinality)
    max_result_cardinality: int = 1 << 22
    # non-aggregate queries (reference: nonAggregateQueryHandling = push/scan)
    non_aggregate_query_handling: str = "scan"  # "scan" | "error"

    # distributed execution (reference: queryHistoricalServers,
    # numSegmentsPerHistoricalQuery -> mesh shape decisions)
    prefer_distributed: bool = False
    mesh_data_axis: Optional[int] = None
    mesh_groups_axis: int = 1


@dataclasses.dataclass
class TableOptions:
    """Per-table registration options (the OPTIONS(...) map analog).

    Reference option -> field mapping:
      timeDimensionColumn      -> time_column
      druidDatasource          -> (the registered name)
      columnMapping            -> column_mapping
      functionalDependencies   -> functional_dependencies (catalog/star.py)
      starSchema               -> star_schema (catalog/star.py)
      rows per segment/historical -> rows_per_segment
      loadMetadataFromAllSegments -> eager_stats
    """

    time_column: Optional[str] = None
    dimensions: Tuple[str, ...] = ()
    metrics: Tuple[str, ...] = ()
    column_mapping: Optional[dict] = None  # source col -> datasource col
    rows_per_segment: int = 1 << 22
    eager_stats: bool = True
    star_schema: Optional[object] = None  # catalog.star.StarSchemaInfo
    functional_dependencies: Tuple = ()


DEFAULT_SESSION = SessionConfig()
