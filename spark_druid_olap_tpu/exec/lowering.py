"""Query lowering: dimensions, aggregations, and the row-kernel ABI.

Split out of exec/engine.py (it had become a god-module — VERDICT r1 weak
#8).  This module holds everything that turns a QuerySpec x DataSource into
device-executable pieces, shared by the local engine (exec/engine.py), the
distributed engine (parallel/distributed.py), and the streaming executor
(exec/streaming.py):

* dimension resolution (dictionary remaps, time bucketing, extractions),
* aggregation lowering into the kernel ABI merge classes,
* `GroupByLowering` (columns, row_arrays, filter mask),
* query-shape rewrites (Timeseries/TopN -> GroupBy, implicit granularity),
* program/state cache identity (`_query_key`, `schema_signature`).

Reference parity: the planning-side counterpart of Druid's per-segment query
engine setup (SURVEY.md §3.3 `[U]`): what the reference serializes into query
JSON for Druid to interpret, we lower into jit-traceable closures.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Mapping, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from ..catalog.segment import DataSource
from ..models import aggregations as A
from ..models import query as Q
from ..models.dimensions import DimensionSpec
from ..models.filters import Filter
from ..ops.filters import DecodedView, compile_filter
from ..ops.groupby import combine_group_ids
from ..plan.expr import compile_expr
from ..utils.granularity import bucket_starts

# ---------------------------------------------------------------------------
# Dimension resolution
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class ResolvedDim:
    """A dimension lowered to: device code producer + cardinality + decoder."""

    spec: DimensionSpec
    cardinality: int  # including the null slot when present
    codes_fn: Callable[[Mapping[str, jnp.ndarray]], jnp.ndarray]
    decode: Callable[[np.ndarray], np.ndarray]  # codes -> python values


def _resolve_dims(
    dims: Sequence[DimensionSpec],
    ds: DataSource,
    intervals: Tuple[Tuple[int, int], ...],
) -> List[ResolvedDim]:
    out: List[ResolvedDim] = []
    for spec in dims:
        if spec.dimension == "__time" or spec.granularity is not None:
            out.append(_resolve_time_dim(spec, ds, intervals))
            continue
        d = ds.dicts[spec.dimension]
        if spec.extraction is not None:
            # Host-side dictionary rewrite: apply fn to each dict value once,
            # build remap table code -> new code (SURVEY.md dimension-spec row).
            # Extraction fns are string fns; numeric dictionaries stringify.
            extracted = spec.extraction.apply_to_dict(
                [v if isinstance(v, str) else str(v) for v in d.values]
            )
            # extraction fns may emit None (lookup with no retain/replace):
            # those values fold into the null slot
            new_vals = sorted({v for v in extracted if v is not None})
            index = {v: i for i, v in enumerate(new_vals)}
            card = len(new_vals) + 1  # + null slot
            remap = np.array(
                [
                    index[v] if v is not None else card - 1
                    for v in extracted
                ],
                dtype=np.int32,
            )
            remap_dev = jnp.asarray(remap)
            name = spec.dimension

            def codes_fn(cols, remap_dev=remap_dev, name=name, card=card):
                c = cols[name]
                return jnp.where(c >= 0, remap_dev[jnp.maximum(c, 0)],
                                 jnp.int32(card - 1))

            vals_arr = np.asarray(new_vals, dtype=object)

            def decode(codes, vals_arr=vals_arr, card=card):
                o = np.empty(len(codes), dtype=object)
                isnull = codes == card - 1
                o[~isnull] = vals_arr[codes[~isnull]]
                o[isnull] = None
                return o

            out.append(ResolvedDim(spec, card, codes_fn, decode))
        else:
            card = d.cardinality + 1  # last slot = null
            name = spec.dimension

            def codes_fn(cols, name=name, card=card):
                c = cols[name]
                return jnp.where(c >= 0, c, jnp.int32(card - 1))

            vals_arr = np.asarray(d.values, dtype=object)

            def decode(codes, vals_arr=vals_arr, card=card):
                o = np.empty(len(codes), dtype=object)
                isnull = codes == card - 1
                o[~isnull] = vals_arr[codes[~isnull]]
                o[isnull] = None
                return o

            out.append(ResolvedDim(spec, card, codes_fn, decode))
    return out


def _resolve_time_dim(
    spec: DimensionSpec, ds: DataSource, intervals
) -> ResolvedDim:
    gran = spec.granularity or "all"
    iv = intervals[0] if intervals else ds.interval()
    if iv is None:
        raise ValueError("time-bucketed dimension requires a time column")
    lo, hi = iv
    if intervals:
        lo = min(a for a, _ in intervals)
        hi = max(b for _, b in intervals)
        # open-ended predicate intervals (t >= x -> hi = 2^62) would expand
        # the bucket table unboundedly; the data's own range bounds it
        dsiv = ds.interval()
        if dsiv is not None:
            lo = max(lo, dsiv[0])
            hi = max(lo, min(hi, dsiv[1]))
    starts = bucket_starts(lo, hi, gran)  # host-computed bucket boundaries
    card = len(starts)
    starts_dev = jnp.asarray(starts)

    from ..utils.granularity import granularity_period_ms

    period = granularity_period_ms(gran) if gran.lower() != "all" else None

    def bucket_idx(t, first=int(starts[0]), period=period,
                   starts_dev=starts_dev, card=card):
        if period is not None:
            # FIXED-period granularity (minute/hour/day/week): plain
            # integer arithmetic — one fused op instead of searchsorted's
            # log-N scan passes (~135 ms per 2M-row chunk on CPU).
            # Out-of-range rows clip into the edge buckets; the interval
            # row-mask already excludes them.
            return jnp.clip((t - first) // period, 0, card - 1).astype(
                jnp.int32
            )
        # calendar granularities (month/quarter/year): boundaries are
        # irregular — searchsorted over the host-computed starts
        return (
            jnp.searchsorted(starts_dev, t, side="right").astype(jnp.int32)
            - 1
        )

    if spec.extraction is not None:
        # EXTRACT-style dims: many buckets fold to one extracted value
        # (e.g. MONTH over 3 years: 36 buckets -> 12 groups).  Host-side
        # remap over bucket starts; the kernel adds one tiny gather.
        extracted = spec.extraction.apply_to_dict([int(s) for s in starts])
        new_vals = sorted(set(extracted))
        index = {v: i for i, v in enumerate(new_vals)}
        remap_dev = jnp.asarray(
            np.array([index[v] for v in extracted], dtype=np.int32)
        )

        def codes_fn(cols, remap_dev=remap_dev):
            b = bucket_idx(cols["__time"])
            return remap_dev[jnp.clip(b, 0, remap_dev.shape[0] - 1)]

        vals_arr = np.asarray(new_vals, dtype=object)

        def decode(codes, vals_arr=vals_arr):
            return vals_arr[np.clip(codes, 0, len(vals_arr) - 1)]

        return ResolvedDim(spec, len(new_vals), codes_fn, decode)

    def codes_fn(cols):
        return bucket_idx(cols["__time"])

    starts_np = np.asarray(starts)

    def decode(codes, starts_np=starts_np):
        ms = starts_np[np.clip(codes, 0, len(starts_np) - 1)]
        return ms.astype("datetime64[ms]")

    return ResolvedDim(spec, card, codes_fn, decode)


# ---------------------------------------------------------------------------
# Aggregation lowering
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class LoweredAggs:
    """Aggregations split by merge class for the kernel ABI.

    Layout contract with ops/groupby.py: sum-class aggs (psum merges) are the
    columns of `sum_values`; min-class then max-class are the columns of
    `minmax_values`.  Column 0 of sum_values is always the hidden `__rows`
    presence counter."""

    sum_names: List[str]
    min_names: List[str]
    max_names: List[str]
    sketch_aggs: List[A.Aggregation]
    long_valued: Dict[str, bool]
    value_fns: Dict[str, Callable]  # name -> fn(cols) -> f32[R]
    mask_fns: Dict[str, Optional[Callable]]  # name -> extra-mask fn or None
    count_like: set = dataclasses.field(default_factory=set)  # COUNT aggs
    # agg name -> existing sum column it READS instead of owning one: an
    # unfiltered COUNT(*) is exactly the hidden __rows presence counter,
    # and a duplicate all-ones scatter column is pure waste (the scatter
    # cost scales with the column count)
    aliased: Dict[str, str] = dataclasses.field(default_factory=dict)


def _lower_aggs(
    aggs: Sequence[A.Aggregation], ds: DataSource
) -> LoweredAggs:
    la = LoweredAggs(["__rows"], [], [], [], {"__rows": True}, {}, {})
    la.value_fns["__rows"] = lambda cols: None  # ones; handled specially
    la.mask_fns["__rows"] = None

    def add(agg: A.Aggregation, extra_filter: Optional[Filter]):
        mask_fn = (
            compile_filter(extra_filter, ds) if extra_filter is not None else None
        )
        if isinstance(agg, A.FilteredAgg):
            inner_mask = compile_filter(agg.filter, ds)
            if mask_fn is None:
                combined = inner_mask
            else:
                outer = mask_fn
                combined = lambda cols: outer(cols) & inner_mask(cols)
            _add_base(agg.aggregator, combined)
            return
        _add_base(agg, mask_fn)

    def _add_base(agg: A.Aggregation, mask_fn):
        name = agg.name
        la.mask_fns[name] = mask_fn
        if isinstance(agg, A.Count):
            la.long_valued[name] = True
            la.count_like.add(name)
            if mask_fn is None:
                la.aliased[name] = "__rows"  # reuse the presence counter
                return
            la.sum_names.append(name)
            la.value_fns[name] = lambda cols: None  # ones
        elif isinstance(agg, (A.LongSum, A.DoubleSum)):
            field = agg.field_name
            la.sum_names.append(name)
            la.long_valued[name] = isinstance(agg, A.LongSum)
            la.value_fns[name] = _field_value_fn(field, ds)
            _add_null_skip(la, name, field, ds)
        elif isinstance(agg, (A.LongMin, A.DoubleMin)):
            field = agg.field_name
            la.min_names.append(name)
            la.long_valued[name] = isinstance(agg, A.LongMin)
            la.value_fns[name] = _field_value_fn(field, ds)
            _add_null_skip(la, name, field, ds)
        elif isinstance(agg, (A.LongMax, A.DoubleMax)):
            field = agg.field_name
            la.max_names.append(name)
            la.long_valued[name] = isinstance(agg, A.LongMax)
            la.value_fns[name] = _field_value_fn(field, ds)
            _add_null_skip(la, name, field, ds)
        elif isinstance(agg, A.DimCodeMax):
            # FD grouping pruning: max over raw dictionary codes (all rows
            # of a group share one code by the declared FD); decoded back
            # to the value at the API layer.  Codes < 2^24 represent
            # exactly in f32; null rows carry -1 and never win the max
            # unless the whole group is null (-1 decodes back to null)
            field = agg.field_name
            la.max_names.append(name)
            la.long_valued[name] = True
            la.value_fns[name] = lambda cols, f=field: jnp.asarray(
                cols[f]
            ).astype(jnp.float32)
        elif isinstance(agg, A.ExpressionAgg):
            fn = compile_expr(agg.expression, ds.dicts)
            target = {
                "doubleSum": la.sum_names,
                "longSum": la.sum_names,
                "doubleMin": la.min_names,
                "doubleMax": la.max_names,
            }[agg.base]
            target.append(name)
            la.long_valued[name] = agg.base == "longSum"
            dicts = ds.dicts
            la.value_fns[name] = lambda cols, fn=fn, dicts=dicts: jnp.asarray(
                fn(DecodedView(cols, dicts))
            ).astype(jnp.float32)
        elif isinstance(
            agg,
            (A.HyperUnique, A.CardinalityAgg, A.ThetaSketch, A.QuantilesSketch),
        ):
            la.sketch_aggs.append(agg)
            la.long_valued[name] = True
        else:
            raise NotImplementedError(f"aggregation {type(agg).__name__}")

    for agg in aggs:
        add(agg, None)
    return la


def _field_value_fn(field: str, ds: DataSource):
    """Value reader for sum/min/max: metric columns pass through; numeric-
    dictionary dimension columns decode rank codes back to values (so
    sum(d_year)-style aggregates see years, not ranks)."""
    d = ds.dicts.get(field) if hasattr(ds.dicts, "get") else None
    if d is not None and d.numeric_values is not None:
        dicts = ds.dicts
        return lambda cols, field=field, dicts=dicts: DecodedView(cols, dicts)[
            field
        ].astype(jnp.float32)
    return lambda cols, field=field: cols[field].astype(jnp.float32)


def _add_null_skip(la: LoweredAggs, name: str, field: str, ds: DataSource):
    """SQL aggregates skip NULLs: for a dictionary-dimension field, rows with
    a null code (-1) must not contribute (they'd otherwise decode to -1 and
    poison SUM/MIN/MAX).  Metrics have no null representation — no-op."""
    d = ds.dicts.get(field) if hasattr(ds.dicts, "get") else None
    if d is None:
        return
    nm = lambda cols, field=field: cols[field] >= 0
    prev = la.mask_fns.get(name)
    la.mask_fns[name] = (
        nm if prev is None else lambda cols, p=prev, nm=nm: p(cols) & nm(cols)
    )


# ---------------------------------------------------------------------------
# Query lowering (shared by the local engine and parallel/distributed.py)
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class GroupByLowering:
    """A GroupByQuery lowered to device-executable pieces:

    * `columns` — physical columns to fetch per segment
    * `row_arrays(cols)` — pure, jit/shard_map-traceable row-wise kernel
      producing (gid, mask, sum_values, minmax_values, minmax_masks)
    * `dims` / `la` / `num_groups` — the finalization contract
    """

    query: Q.GroupByQuery
    dims: List[ResolvedDim]
    la: LoweredAggs
    num_groups: int
    columns: List[str]
    filter_fn: Optional[Callable]
    vcol_fns: Dict[str, Callable]
    # vcol names that are ALSO read by a vcol expression (physical shadow)
    shadowed_inputs: frozenset = frozenset()

    def add_virtual(self, cols: Dict[str, jnp.ndarray]) -> Dict[str, jnp.ndarray]:
        """Compute virtual columns from the PHYSICAL inputs.  Idempotent:
        a virtual column that shadows a physical column it reads saves the
        physical values under __phys__<name>, so a second application (the
        engine calls this once for sketches and once in row_arrays)
        recomputes from the same inputs instead of compounding."""
        if not self.vcol_fns:
            return cols
        inputs = dict(cols)
        # restore/save ALL physical shadows before any compute: a vcol
        # declared before a later-declared shadow still reads the
        # physical values on a second application
        for name in self.shadowed_inputs:
            phys = "__phys__" + name
            if phys in cols:
                inputs[name] = cols[phys]
            elif name in cols:
                cols[phys] = cols[name]
        for name, fn in self.vcol_fns.items():  # declaration order
            out = jnp.asarray(fn(inputs))
            cols[name] = out
            if name not in self.shadowed_inputs:
                # chained vcols: a LATER vcol may read this output; a
                # shadowed name keeps exposing its physical values to
                # vcol expressions instead
                inputs[name] = out
        return cols

    def row_mask(self, cols) -> jnp.ndarray:
        mask = cols["__valid"]
        q = self.query
        if q.intervals:
            t = cols["__time"]
            im = jnp.zeros(t.shape, jnp.bool_)
            for a, b in q.intervals:
                im = im | ((t >= a) & (t < b))
            mask = mask & im
        if self.filter_fn is not None:
            mask = mask & self.filter_fn(cols)
        return mask

    def row_arrays(
        self,
        cols: Dict[str, jnp.ndarray],
        mask: Optional[jnp.ndarray] = None,
        gid: Optional[jnp.ndarray] = None,
    ):
        """cols: name -> row-aligned device array (must include "__valid",
        and "__time" when the query touches time).  Returns the kernel ABI
        tuple for ops/groupby.py.

        `mask`/`gid` accept PRECOMPUTED row pipelines: the fused-batch
        common-subexpression pass (serve/fusion.shared_row_plan) computes
        the filter mask / group-id pipeline once per segment for members
        whose (virtualColumns, filter, intervals) / (virtualColumns,
        dimensions) sub-lowerings are identical, instead of re-tracing
        them per member inside the fused program."""
        cols = dict(cols)
        self.add_virtual(cols)
        if mask is None:
            mask = self.row_mask(cols)
        la = self.la
        if gid is None:
            gid, _ = combine_group_ids(
                [d.codes_fn(cols) for d in self.dims],
                [d.cardinality for d in self.dims],
            )
            if not self.dims:
                gid = jnp.zeros(mask.shape, jnp.int32)
        R = mask.shape[0]
        maskf = mask.astype(jnp.float32)
        sum_cols = []
        for n in la.sum_names:
            base = la.value_fns[n](cols) if la.value_fns[n] is not None else None
            v = maskf if base is None else base * maskf
            mfn = la.mask_fns.get(n)
            if mfn is not None:
                v = v * mfn(cols).astype(jnp.float32)
            sum_cols.append(v)
        sum_values = jnp.stack(sum_cols, axis=1)
        mm_names = la.min_names + la.max_names
        if mm_names:
            mm_vals, mm_masks = [], []
            for n in mm_names:
                mm_vals.append(la.value_fns[n](cols))
                mfn = la.mask_fns.get(n)
                mm_masks.append(
                    mfn(cols) if mfn is not None else jnp.ones((R,), jnp.bool_)
                )
            minmax_values = jnp.stack(mm_vals, axis=1)
            minmax_masks = jnp.stack(mm_masks, axis=1)
        else:
            minmax_values = jnp.zeros((R, 0), jnp.float32)
            minmax_masks = jnp.zeros((R, 0), jnp.bool_)
        return gid, mask, sum_values, minmax_values, minmax_masks


def _query_key(q: Q.QuerySpec, ds: DataSource) -> Tuple:
    """Identity of (query, datasource-schema) for program/state caches —
    single definition so every cache keys the same way."""
    import json as _json

    return (
        _json.dumps(q.to_druid(), sort_keys=True, default=str),
        schema_signature(ds),
    )


def schema_signature(ds: DataSource) -> Tuple:
    """Identity of a datasource's schema for program caches: name + per-column
    kind/cardinality + dictionary content + segment ids.  Dictionary content
    matters because rank codes are data-dependent: re-ingesting a same-name
    datasource with an equal-cardinality but different value domain must MISS
    the cache (compiled filters bake in literal->code translations)."""
    return (
        ds.name,
        _dict_signature(ds),
        tuple(s.uid for s in ds.segments),
    )


def _dict_signature(ds: DataSource) -> Tuple:
    return tuple(
        (
            c.name,
            c.kind,
            c.cardinality,
            ds.dicts[c.name].content_key if c.name in ds.dicts else None,
        )
        for c in ds.columns
    )


def memo_key(q: Q.QuerySpec, ds: DataSource) -> Tuple:
    """Segment-set-INDEPENDENT identity of (query, datasource schema) for
    the engine's LEARNED memos (sparse capacity rungs, adaptive kept
    sets, sparse-overflow pins).  Unlike `_query_key`, the segment uid
    tuple is excluded: a streamed append publishes a new segment set
    every batch, and keying memos on uids would (a) forget every learned
    rung per append and (b) grow the memo dicts without bound under
    continuous ingest.  Dictionary content stays in the key — a
    dictionary extension changes cardinalities/code meanings, which is
    exactly when a learned rung goes stale."""
    import json as _json

    return (
        _json.dumps(q.to_druid(), sort_keys=True, default=str),
        ds.name,
        _dict_signature(ds),
    )


def timeseries_to_groupby(q: Q.TimeseriesQuery) -> Q.GroupByQuery:
    """Shared Timeseries->GroupBy rewrite (a Timeseries is a GroupBy whose
    only dimension is the time bucket) — used by both engines so semantics
    cannot drift."""
    return Q.GroupByQuery(
        datasource=q.datasource,
        dimensions=(
            DimensionSpec(
                "__time", q.output_name, granularity=q.granularity
            ),
        ),
        aggregations=q.aggregations,
        post_aggregations=q.post_aggregations,
        filter=q.filter,
        intervals=q.intervals,
        virtual_columns=q.virtual_columns,
    )


def topn_to_groupby(q: Q.TopNQuery) -> Q.GroupByQuery:
    """Shared TopN->GroupBy rewrite (exact TopN: full groupby then rank;
    Druid's native TopN is approximate — ours is exact and still one kernel)."""
    return Q.GroupByQuery(
        datasource=q.datasource,
        dimensions=(q.dimension,),
        aggregations=q.aggregations,
        post_aggregations=q.post_aggregations,
        filter=q.filter,
        intervals=q.intervals,
        granularity=q.granularity,
        virtual_columns=q.virtual_columns,
    )


def cached_lowering(cache, q: Q.GroupByQuery, ds: DataSource) -> "GroupByLowering":
    """Shared lowering-cache lookup (local + distributed engines): lowering
    stages device constants, so rebuilding it per execution pays one blocking
    H2D transfer per constant."""
    key = _query_key(q, ds)
    lowering = cache.get(key)
    if lowering is None:
        lowering = lower_groupby(q, ds)
        cache[key] = lowering
    return lowering


def lower_groupby(q: Q.GroupByQuery, ds: DataSource) -> GroupByLowering:
    dims = _resolve_dims(q.dimensions, ds, q.intervals)
    la = _lower_aggs(q.aggregations, ds)
    G = 1
    for d in dims:
        G *= d.cardinality
    if G > (1 << 26):
        raise ValueError(
            f"combined group cardinality {G} too large for dense domain; "
            "sort-based path not yet wired for this size"
        )
    filter_fn = compile_filter(q.filter, ds) if q.filter is not None else None
    vcol_fns = {
        v.name: _decoded_expr_fn(v.expression, ds) for v in q.virtual_columns
    }
    # Shadowing a VALUE-SPACE (metric/numeric) column is supported: every
    # consumer reads plain values.  Shadowing a dictionary-encoded
    # dimension is REFUSED: filters/aggs/dims on dictionary names compile
    # into code space, and a value-space virtual array under that name
    # would be silently mis-evaluated (refuse rather than be wrong).
    for v in q.virtual_columns:
        if v.name in ds.dicts:
            raise ValueError(
                f"virtual column {v.name!r} shadows dictionary-encoded "
                f"dimension {v.name!r} of {ds.name!r}: filters and "
                "groupings on dictionary dimensions evaluate in code "
                "space, so the shadow cannot be honored soundly.  Name "
                "the virtual column differently."
            )
    vcol_inputs = {
        c for v in q.virtual_columns for c in v.expression.columns()
    }
    phys_names = {c.name for c in ds.columns}
    return GroupByLowering(
        q,
        dims,
        la,
        G,
        _needed_columns(q, ds, dims),
        filter_fn,
        vcol_fns,
        shadowed_inputs=frozenset(vcol_fns) & vcol_inputs & phys_names,
    )


def _decoded_expr_fn(expression, ds: DataSource):
    """Compile an expression so dimension references read decoded values."""
    fn = compile_expr(expression, ds.dicts)
    dicts = ds.dicts
    return lambda cols, fn=fn, dicts=dicts: fn(DecodedView(cols, dicts))


def _needed_columns(q, ds: DataSource, dims) -> List[str]:
    names: List[str] = []
    for d in dims:
        if d.spec.dimension != "__time" and d.spec.granularity is None:
            names.append(d.spec.dimension)
    for a in q.aggregations:
        names.extend(_agg_columns(a))
    if q.filter is not None:
        names.extend(_filter_columns(q.filter))
    for v in q.virtual_columns:
        names.extend(v.expression.columns())
    virt = {v.name for v in q.virtual_columns}
    # A name produced by a virtual column is not fetched — UNLESS it is a
    # SHADOW: a physical column that a vcol expression also reads (the vcol
    # computes from the physical values, every other consumer reads the
    # virtual ones).  A vcol name read only by ANOTHER vcol (chained
    # virtual columns) is not physical and must not be fetched.
    phys = {c.name for c in ds.columns}
    vcol_inputs = {
        c for v in q.virtual_columns for c in v.expression.columns()
    }
    shadows = virt & vcol_inputs & phys
    need = [
        n
        for n in dict.fromkeys(names)
        if (n not in virt or n in shadows) and n != "__time"
    ]
    if ds.time_column and (
        any(d.spec.dimension == "__time" or d.spec.granularity for d in dims)
        or q.intervals
        or "__time" in names
    ):
        need.append(ds.time_column)
    return need


def empty_partials(la: LoweredAggs, G: int):
    """Zero-row partial state (identity of every merge class) — shared by
    the segment-pruned-to-nothing path and the empty-stream path."""
    sums = jnp.zeros((G, len(la.sum_names)), jnp.float32)
    mins = jnp.full((G, len(la.min_names)), jnp.inf, jnp.float32)
    maxs = jnp.full((G, len(la.max_names)), -jnp.inf, jnp.float32)
    sketch_states: Dict[str, jnp.ndarray] = {}
    for agg in la.sketch_aggs:
        if isinstance(agg, (A.HyperUnique, A.CardinalityAgg)):
            sketch_states[agg.name] = jnp.zeros(
                (G, 1 << agg.precision), jnp.int32
            )
        elif isinstance(agg, A.QuantilesSketch):
            from ..ops.quantiles import SENTINEL_P

            # [G, K+1, 2]: K empty sample slots + the zero N-counter row
            pr = jnp.full((G, agg.size), SENTINEL_P, jnp.int32)
            vb = jnp.zeros((G, agg.size), jnp.int32)
            sample = jnp.stack([pr, vb], axis=-1)
            extra = jnp.zeros((G, 1, 2), jnp.int32)
            sketch_states[agg.name] = jnp.concatenate(
                [sample, extra], axis=1
            )
        else:
            from ..ops.theta import SENTINEL

            sketch_states[agg.name] = jnp.full(
                (G, agg.size), SENTINEL, jnp.uint32
            )
    return sums, mins, maxs, sketch_states


def groupby_with_time_granularity(q: Q.GroupByQuery) -> Q.GroupByQuery:
    """Druid semantics shared by all executors: a non-'all' granularity on
    GroupBy adds an implicit leading time-bucket dimension (one result row
    per bucket per group)."""
    if q.granularity in ("all", None) or any(
        d.dimension == "__time" or d.granularity for d in q.dimensions
    ):
        return q
    return dataclasses.replace(
        q,
        dimensions=(
            DimensionSpec("__time", "timestamp", granularity=q.granularity),
        )
        + tuple(q.dimensions),
        granularity="all",
    )


def _agg_columns(a: A.Aggregation) -> List[str]:
    if isinstance(a, A.FilteredAgg):
        return _filter_columns(a.filter) + _agg_columns(a.aggregator)
    if isinstance(a, A.ExpressionAgg):
        return list(a.expression.columns())
    if isinstance(a, A.Count):
        return []
    if isinstance(a, A.CardinalityAgg):
        return list(a.field_names)
    return [a.field_name]  # type: ignore[attr-defined]


def _filter_columns(f: Filter) -> List[str]:
    from ..models import filters as F

    if isinstance(f, (F.Selector, F.InFilter, F.Bound, F.Regex, F.LikeFilter)):
        return [f.dimension]
    if isinstance(f, (F.And, F.Or)):
        out: List[str] = []
        for x in f.fields:
            out.extend(_filter_columns(x))
        return out
    if isinstance(f, F.Not):
        return _filter_columns(f.field)
    if isinstance(f, F.IntervalFilter):
        return ["__time"] if f.dimension == "__time" else [f.dimension]
    if isinstance(f, F.ExpressionFilter):
        return list(f.expression.columns())
    return []
