"""Native-query host fallback: wire QuerySpec -> logical plan (ISSUE 7
tentpole (c)).

The degradation matrix had one hole: SQL queries degrade to the host
interpreter when the device path is sick (`api._run_fallback`), but a
wire-native query arriving at `POST /druid/v2` had no logical plan to
degrade with — an open breaker 503'd it.  This module closes the hole by
DECODING a QuerySpec back into the same `plan.logical` language the
fallback interpreter executes:

  * every aggregate query type routes through its GroupBy form (the
    engines' own `timeseries_to_groupby` / `topn_to_groupby` rewrites,
    so semantics cannot drift between the healthy and degraded paths),
  * aggregators translate through the `WIRE_AGG_FALLBACK` registry
    (exec/fallback.py) — the wire-parity lint pass (GL10xx) already
    guarantees every wire-decodable aggregator has a host function,
  * Druid filters become `plan.expr` predicates evaluated over decoded
    frames; query intervals become time-column range predicates,
  * results re-shape through the engines' own finalizers
    (`finalize_timeseries` bucket fill, `finalize_topn` ranking,
    `apply_limit_spec`), so the degraded wire response has the same
    shape the device path would have produced.

Specs outside the interpreter's coverage (extraction dimensions,
virtual columns, sketch post-agg set operations, week-aligned
granularities) raise `WireFallbackUnsupported` — the server then falls
back to the previous fail-fast 503 rather than risking a silently-wrong
degraded answer.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from ..models import aggregations as A
from ..models import filters as F
from ..models import query as Q
from ..plan import expr as E
from ..plan import logical as L
from ..utils.log import get_logger
from .engine import timeseries_to_groupby, topn_to_groupby
from .fallback import fallback_agg_fn

log = get_logger("exec.wire_fallback")


class WireFallbackUnsupported(NotImplementedError):
    """The native spec is outside the host interpreter's coverage; the
    serving layer keeps the fail-fast 503 for it."""


# ExpressionAgg base -> host aggregate function
_EXPR_AGG_BASE = {
    "doubleSum": "sum",
    "longSum": "sum",
    "doubleMin": "min",
    "doubleMax": "max",
}

_HAVING_OPS = {
    ">": ">", "<": "<", "==": "==", ">=": ">=", "<=": "<=", "!=": "!=",
}


def _col(name: str) -> E.Expr:
    return E.Col(name)


def _lit(v) -> E.Expr:
    return E.Literal(v)


def filter_to_expr(f: F.Filter, ds) -> E.Expr:
    """Druid filter tree -> a host-evaluable predicate over DECODED
    values.  Every branch mirrors the device filter compiler's semantics
    (ops/filters.py) over the decoded domain; anything that cannot be
    mirrored soundly raises rather than approximating."""
    if isinstance(f, F.Selector):
        return E.Comparison("==", _col(f.dimension), _lit(f.value))
    if isinstance(f, F.InFilter):
        # x IN (..., NULL) needs no special casing: non-members are
        # UNKNOWN, which a WHERE treats as false — the positive set
        # alone is equivalent, so null_in_values never changes the plan
        return E.InExpr(_col(f.dimension), tuple(f.values))
    if isinstance(f, F.Bound):
        terms: List[E.Expr] = []
        numeric = f.ordering == "numeric"

        def _bound_lit(s: str):
            if not numeric:
                return _lit(s)
            try:
                return _lit(float(s))
            except (TypeError, ValueError):
                raise WireFallbackUnsupported(
                    f"numeric bound with non-numeric literal {s!r}"
                )

        if f.lower is not None:
            terms.append(
                E.Comparison(
                    ">" if f.lower_strict else ">=",
                    _col(f.dimension), _bound_lit(f.lower),
                )
            )
        if f.upper is not None:
            terms.append(
                E.Comparison(
                    "<" if f.upper_strict else "<=",
                    _col(f.dimension), _bound_lit(f.upper),
                )
            )
        if not terms:
            return _lit(True)
        return terms[0] if len(terms) == 1 else E.BoolOp(
            "and", tuple(terms)
        )
    if isinstance(f, F.LikeFilter):
        return E.LikeExpr(_col(f.dimension), f.pattern)
    if isinstance(f, F.And):
        return E.BoolOp(
            "and", tuple(filter_to_expr(x, ds) for x in f.fields)
        )
    if isinstance(f, F.Or):
        return E.BoolOp(
            "or", tuple(filter_to_expr(x, ds) for x in f.fields)
        )
    if isinstance(f, F.Not):
        return E.BoolOp("not", (filter_to_expr(f.field, ds),))
    if isinstance(f, F.ExpressionFilter):
        return f.expression
    if isinstance(f, F.IntervalFilter):
        return _intervals_expr(f.intervals, ds)
    raise WireFallbackUnsupported(
        f"filter type {type(f).__name__} has no host interpretation"
    )


def _time_col(ds) -> str:
    tc = getattr(ds, "time_column", None)
    if not tc:
        raise WireFallbackUnsupported(
            f"time-scoped native query over timeless datasource "
            f"{ds.name!r}"
        )
    return tc


def _intervals_expr(intervals, ds) -> E.Expr:
    tc = _time_col(ds)
    terms = tuple(
        E.BoolOp(
            "and",
            (
                E.Comparison(">=", _col(tc), _lit(int(a))),
                E.Comparison("<", _col(tc), _lit(int(b))),
            ),
        )
        for a, b in intervals
    )
    if not terms:
        return _lit(True)
    return terms[0] if len(terms) == 1 else E.BoolOp("or", terms)


def _agg_to_aggexpr(
    a: A.Aggregation, quantile_posts, ds=None
) -> Optional[L.AggExpr]:
    """One wire aggregator -> the interpreter's AggExpr, via the
    WIRE_AGG_FALLBACK registry (fallback_agg_fn raises loudly for
    classes outside it).  Quantile sketches return None here — they are
    materialized by their consuming post-agg (quantile_posts)."""
    if isinstance(a, A.FilteredAgg):
        inner = _agg_to_aggexpr(a.aggregator, quantile_posts, ds)
        if inner is None:
            raise WireFallbackUnsupported(
                "filtered quantile sketches are not interpretable"
            )
        import dataclasses

        return dataclasses.replace(
            inner, filter=filter_to_expr(a.filter, ds)
        )
    fn = fallback_agg_fn(a)  # raises NotImplementedError off-registry
    if isinstance(a, A.Count):
        return L.AggExpr(a.name, "count", None)
    if isinstance(a, A.ExpressionAgg):
        base_fn = _EXPR_AGG_BASE.get(a.base)
        if base_fn is None:
            raise WireFallbackUnsupported(
                f"expression aggregator base {a.base!r}"
            )
        return L.AggExpr(a.name, base_fn, a.expression)
    if isinstance(a, A.CardinalityAgg):
        if a.by_row or len(a.field_names) != 1:
            raise WireFallbackUnsupported(
                "multi-field/by-row cardinality aggregator"
            )
        return L.AggExpr(a.name, fn, _col(a.field_names[0]))
    if isinstance(a, A.QuantilesSketch):
        # consumed by quantilesDoublesSketchToQuantile post-aggs; a bare
        # sketch output has no scalar host representation
        return None
    field = getattr(a, "field_name", None)
    if field is None:
        raise WireFallbackUnsupported(
            f"aggregator {type(a).__name__} without a fieldName"
        )
    return L.AggExpr(a.name, fn, _col(field))


_ARITH_OPS = {"+": "+", "-": "-", "*": "*", "/": "/", "quotient": "/"}


def _post_to_expr(p: A.PostAggregation, agg_names) -> E.Expr:
    if isinstance(p, A.FieldAccess):
        return E.AggRef(p.field_name)
    if isinstance(p, A.ConstantPost):
        return E.Literal(p.value)
    if isinstance(p, A.Arithmetic):
        op = _ARITH_OPS.get(p.fn)
        if op is None:
            raise WireFallbackUnsupported(
                f"arithmetic post-aggregation fn {p.fn!r}"
            )
        out = _post_to_expr(p.fields[0], agg_names)
        for x in p.fields[1:]:
            out = E.BinaryOp(op, out, _post_to_expr(x, agg_names))
        return out
    if isinstance(p, A.HyperUniqueCardinality):
        return E.AggRef(p.field_name)
    if isinstance(p, A.ThetaSketchEstimate):
        return E.AggRef(p.field_name)
    if isinstance(p, A.ExpressionPost):
        # agg-output references arrive as Cols from the wire expression
        # grammar; rebind them to AggRefs (SQL alias semantics)
        return E.map_expr(
            p.expression,
            lambda x: E.AggRef(x.name)
            if isinstance(x, E.Col) and x.name in agg_names
            else x,
        )
    raise WireFallbackUnsupported(
        f"post-aggregation {type(p).__name__} has no host interpretation"
    )


def _having_to_expr(h: Q.Having) -> E.Expr:
    if isinstance(h, Q.HavingCompare):
        op = _HAVING_OPS.get(h.op)
        if op is None:
            raise WireFallbackUnsupported(f"having op {h.op!r}")
        return E.Comparison(op, E.AggRef(h.aggregation), _lit(h.value))
    if isinstance(h, Q.HavingAnd):
        return E.BoolOp(
            "and", tuple(_having_to_expr(x) for x in h.specs)
        )
    if isinstance(h, Q.HavingOr):
        return E.BoolOp("or", tuple(_having_to_expr(x) for x in h.specs))
    if isinstance(h, Q.HavingNot):
        return E.BoolOp("not", (_having_to_expr(h.spec),))
    raise WireFallbackUnsupported(
        f"havingSpec {type(h).__name__} has no host interpretation"
    )


def _groupby_to_logical(q: Q.GroupByQuery, ds) -> L.LogicalPlan:
    if q.virtual_columns:
        raise WireFallbackUnsupported(
            "virtual columns in a native fallback query"
        )
    if q.subtotals:
        raise WireFallbackUnsupported(
            "subtotalsSpec in a native fallback query"
        )
    # grouping expressions
    group_exprs: List[Tuple[str, E.Expr]] = []
    for d in q.dimensions:
        if getattr(d, "extraction", None) is not None:
            raise WireFallbackUnsupported(
                f"extraction dimension {d.name!r}"
            )
        if d.dimension == "__time" or d.granularity:
            gran = d.granularity or "all"
            if gran.lower() == "all":
                continue  # a single all-time bucket adds no grouping key
            from ..utils.granularity import granularity_period_ms

            period = granularity_period_ms(gran)
            if period == 7 * 86_400_000:
                # Druid aligns weeks to Monday; the row-path TimeBucket
                # truncates from epoch — refusing beats a silent
                # misalignment
                raise WireFallbackUnsupported(
                    "week granularity in a native fallback query"
                )
            group_exprs.append(
                (d.name, E.TimeBucket(_col(_time_col(ds)), gran))
            )
        else:
            group_exprs.append((d.name, _col(d.dimension)))
    # aggregators; quantile sketches materialize via their consuming
    # post-aggs (fraction lives on the post-agg, not the sketch)
    quantile_sketches = {
        a.name: a
        for a in q.aggregations
        if isinstance(a, A.QuantilesSketch)
    }
    agg_exprs: List[L.AggExpr] = []
    for a in q.aggregations:
        ae = _agg_to_aggexpr(a, quantile_sketches, ds)
        if ae is not None:
            agg_exprs.append(ae)
    consumed_quantiles = set()
    for p in q.post_aggregations:
        if isinstance(p, A.QuantileFromSketch):
            sk = quantile_sketches.get(p.field_name)
            if sk is None:
                raise WireFallbackUnsupported(
                    f"quantile post-agg over unknown sketch "
                    f"{p.field_name!r}"
                )
            agg_exprs.append(
                L.AggExpr(
                    p.name, "approx_quantile", _col(sk.field_name),
                    args=(float(p.fraction),),
                )
            )
            consumed_quantiles.add(p.field_name)
    for name in quantile_sketches:
        if name not in consumed_quantiles:
            raise WireFallbackUnsupported(
                f"bare quantiles sketch {name!r} (no consuming post-agg)"
            )
    agg_names = {ae.name for ae in agg_exprs}
    # output projection: dims + aggs + post-aggs (quantile posts became
    # aggs above and project under their own names already)
    post: List[Tuple[str, E.Expr]] = [
        (n, _col(n)) for n, _ in group_exprs
    ] + [(ae.name, E.AggRef(ae.name)) for ae in agg_exprs]
    for p in q.post_aggregations:
        if isinstance(p, A.QuantileFromSketch):
            continue
        post.append((p.name, _post_to_expr(p, agg_names)))
    # predicate: filter AND query intervals
    pred: Optional[E.Expr] = None
    if q.filter is not None:
        pred = filter_to_expr(q.filter, ds)
    if q.intervals:
        iv = _intervals_expr(q.intervals, ds)
        pred = iv if pred is None else E.BoolOp("and", (pred, iv))
    base: L.LogicalPlan = L.Scan(q.datasource)
    if pred is not None:
        base = L.Filter(pred, base)
    plan: L.LogicalPlan = L.Aggregate(
        tuple(group_exprs), tuple(agg_exprs), base,
        post_exprs=tuple(post),
    )
    if q.having is not None:
        plan = L.Having(_having_to_expr(q.having), plan)
    return plan


def _scan_to_logical(q: Q.ScanQuery, ds) -> L.LogicalPlan:
    if q.virtual_columns:
        raise WireFallbackUnsupported(
            "virtual columns in a native fallback scan"
        )

    def resolve(name: str) -> E.Expr:
        if name == "__time":
            return _col(_time_col(ds))
        return _col(name)

    pred: Optional[E.Expr] = None
    if q.filter is not None:
        pred = filter_to_expr(q.filter, ds)
    if q.intervals:
        iv = _intervals_expr(q.intervals, ds)
        pred = iv if pred is None else E.BoolOp("and", (pred, iv))
    base: L.LogicalPlan = L.Scan(q.datasource)
    if pred is not None:
        base = L.Filter(pred, base)
    plan: L.LogicalPlan = L.Project(
        tuple((c, resolve(c)) for c in q.columns), base
    )
    if q.order_by:
        # the Sort sits ABOVE the Project, so keys must reference the
        # PROJECTED names — resolve() would re-resolve "__time" to the
        # raw time column the projection just renamed away
        for o in q.order_by:
            if o.dimension not in q.columns:
                raise WireFallbackUnsupported(
                    f"scan order-by {o.dimension!r} outside the "
                    "selected columns"
                )
        plan = L.Sort(
            tuple(
                L.SortKey(
                    _col(o.dimension), o.direction != "descending"
                )
                for o in q.order_by
            ),
            plan,
        )
    if q.limit is not None or q.offset:
        plan = L.Limit(
            q.limit if q.limit is not None else (1 << 62), plan, q.offset
        )
    return plan


def native_to_logical(q: Q.QuerySpec, ds) -> L.LogicalPlan:
    """QuerySpec -> logical plan for `execute_fallback`.  Aggregate
    types route through their GroupBy form (the engines' own rewrites);
    scan becomes Project/Filter/Sort/Limit.  Raises
    WireFallbackUnsupported outside the covered surface."""
    # Druid semantics shared by all executors: a non-'all' QUERY-level
    # granularity on groupBy/topN adds an implicit leading time-bucket
    # dimension (engine.execute applies the same rewrite) — without it
    # the degraded answer would silently collapse every time bucket
    from .lowering import groupby_with_time_granularity

    if isinstance(q, Q.TimeseriesQuery):
        return _groupby_to_logical(timeseries_to_groupby(q), ds)
    if isinstance(q, Q.TopNQuery):
        return _groupby_to_logical(
            groupby_with_time_granularity(topn_to_groupby(q)), ds
        )
    if isinstance(q, Q.GroupByQuery):
        return _groupby_to_logical(groupby_with_time_granularity(q), ds)
    if isinstance(q, Q.ScanQuery):
        return _scan_to_logical(q, ds)
    raise WireFallbackUnsupported(
        f"{type(q).__name__} has no host-fallback interpretation"
    )


def shape_native_result(q: Q.QuerySpec, ds, df):
    """Re-shape the interpreter's grouped frame to what the DEVICE path
    would have produced, using the engines' own finalizers — the
    degraded wire response must be indistinguishable in shape from the
    healthy one."""
    import pandas as pd

    from .finalize import apply_limit_spec, finalize_timeseries, finalize_topn

    if isinstance(q, Q.TimeseriesQuery):
        out = df.copy()
        tcol = q.output_name
        if tcol not in out.columns:
            # granularity "all": one all-time bucket anchored at the
            # scope start, exactly like the engine's time lowering
            iv = q.intervals[0] if q.intervals else ds.interval()
            lo = (
                min(a for a, _ in q.intervals) if q.intervals
                else (iv[0] if iv is not None else 0)
            )
            out.insert(0, tcol, np.int64(lo))
        out[tcol] = np.asarray(out[tcol], dtype=np.int64).astype(
            "datetime64[ms]"
        )
        return finalize_timeseries(out, q, ds)
    if isinstance(q, Q.TopNQuery):
        from .lowering import groupby_with_time_granularity

        # non-'all' granularity: the interpreter ran the same implicit
        # time-bucket rewrite the engine does — re-type its ms ints to
        # timestamps before the topN finalizer renders per-bucket rows
        gq = groupby_with_time_granularity(topn_to_groupby(q))
        for d in gq.dimensions:
            if (
                (d.dimension == "__time" or d.granularity)
                and d.name in df.columns
            ):
                df = df.copy()
                df[d.name] = np.asarray(
                    df[d.name], dtype=np.int64
                ).astype("datetime64[ms]")
        return finalize_topn(df, q)
    if isinstance(q, Q.GroupByQuery):
        from .lowering import groupby_with_time_granularity

        # see native_to_logical: the interpreter ran the granularity
        # rewrite, so the shaper must walk the SAME dimension list to
        # find (and re-type) the implicit leading time bucket
        q = groupby_with_time_granularity(q)
        out = df
        if q.dimensions and any(
            d.dimension == "__time" or d.granularity for d in q.dimensions
        ):
            for pos, d in enumerate(q.dimensions):
                if not (d.dimension == "__time" or d.granularity):
                    continue
                if d.name not in out.columns:
                    # granularity "all": the logical plan dropped the
                    # single all-time bucket from the grouping key; the
                    # device path still EMITS the column, anchored at the
                    # scope start — same contract as the timeseries
                    # branch above
                    iv = q.intervals[0] if q.intervals else ds.interval()
                    lo = (
                        min(a for a, _ in q.intervals) if q.intervals
                        else (iv[0] if iv is not None else 0)
                    )
                    out = out.copy()
                    out.insert(min(pos, len(out.columns)), d.name,
                               np.int64(lo))
                else:
                    out = out.copy()
                out[d.name] = np.asarray(
                    out[d.name], dtype=np.int64
                ).astype("datetime64[ms]")
        if q.limit_spec is not None:
            out = apply_limit_spec(out, q.limit_spec).reset_index(
                drop=True
            )
        return out
    return df
