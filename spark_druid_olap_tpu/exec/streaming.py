"""Streaming execution: aggregate row chunks that never fit in HBM (or host
RAM) at once.

Reference parity: the reference streams Druid results row-by-row precisely so
nothing materializes in full (`DruidRDD` streaming JSON parse, SURVEY.md §3.3
`[U]`); the analogous scale problem here is on the *input* side — BASELINE
config #4 is an hourly rollup over a 1B-row event stream, far beyond one
chip's HBM.  The streaming executor holds only O(chunk) rows on device at any
moment:

  * chunks are produced on a background prefetch thread (host-side decode /
    datagen overlaps device compute),
  * every chunk is padded to one static shape, so the engine's cached
    per-query XLA program is compiled exactly once,
  * `jax.device_put` + the async dispatch queue overlap H2D transfer of
    chunk k+1 with compute of chunk k — the Python loop never blocks,
  * only the tiny [G, M] partial-aggregate state persists across chunks
    (summed / min-maxed / sketch-merged on device).

Multichip streaming (BASELINE config #4 at v5e-8 scale): pass a `mesh` and
every chunk is sharded over the mesh's data axis (`jax.device_put` with a
NamedSharding), the per-chunk program is the DistributedEngine's SPMD
shard_map (dense partials + psum/pmin/pmax/sketch merges over ICI), and only
the tiny replicated [G, M] state accumulates across chunks.  Chunk k+1's H2D
scatter overlaps chunk k's compute exactly as in the single-chip path.
"""

from __future__ import annotations

import dataclasses
import queue
import threading
from typing import Callable, Dict, Iterable, Iterator, Mapping, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..catalog.segment import NULL_ID, ROW_PAD, DataSource
from ..models import query as Q
from .engine import (
    Engine,
    _merge_sketch_states,
    empty_partials,
    finalize_groupby,
    finalize_timeseries,
    finalize_topn,
    groupby_with_time_granularity,
    lower_groupby,
    timeseries_to_groupby,
    topn_to_groupby,
)

_STOP = object()


@dataclasses.dataclass
class StreamStats:
    rows: int = 0
    chunks: int = 0
    # pipeline-stage seconds (BASELINE config #4 observability): normalize
    # runs on the producer thread (overlapped with compute); put/dispatch
    # are consumer-side walls.  dispatch_s is async-dispatch time, NOT
    # device occupancy — the final block shows up in total wall time.
    normalize_s: float = 0.0
    put_s: float = 0.0
    dispatch_s: float = 0.0
    # bytes actually shipped host->device (post-normalization dtypes), so
    # consumers can bound throughput by the measured link rate instead of
    # guessing a bytes/row layout
    h2d_bytes: int = 0

    def to_dict(self):
        return {
            "rows": self.rows,
            "chunks": self.chunks,
            "normalize_s": round(self.normalize_s, 3),
            "put_s": round(self.put_s, 3),
            "dispatch_s": round(self.dispatch_s, 3),
            "h2d_bytes": self.h2d_bytes,
        }


class StreamExecutor:
    """Executes GroupBy/Timeseries/TopN over an iterator of host row-chunks.

    `chunks` yields dicts mapping column name -> numpy array (row-aligned;
    dimension columns already dictionary-encoded as int32 codes per the
    datasource's dictionaries — the contract native ingest and datagen both
    produce).  All chunks must have <= `chunk_rows` rows; shorter chunks are
    padded (a validity mask keeps padding out of every aggregate).
    """

    def __init__(
        self,
        engine: Optional[Engine] = None,
        prefetch: int = 2,
        mesh=None,
    ):
        self.engine = engine or Engine()
        self.prefetch = prefetch
        self.mesh = mesh  # jax.sharding.Mesh -> multichip streaming
        self.stats = StreamStats()
        self._narrow_time = jax.default_backend() != "cpu"
        # compiled chunk-reconstruction programs keyed on (time_col,
        # chunk_rows): jit caches on callable identity, so rebuilding the
        # closure per stream would re-trace/compile every execution (the
        # same convention as DistributedEngine._spmd_fn)
        self._prep_cache: Dict = {}

    def _prep_fn(self, time_col, chunk_rows: int):
        key = (time_col, chunk_rows)
        fn = self._prep_cache.get(key)
        if fn is not None:
            return fn

        @jax.jit
        def prep(dev, base, nrows):
            """Device-side chunk reconstruction: int64 time from int32
            offsets + base, validity mask from the row count.  One tiny
            extra async dispatch per chunk; the H2D savings dominate."""
            cols = dict(dev)
            off = cols.pop("__time_off", None)
            if off is not None:
                # graftlint: disable=dtype-x64 -- time is int64 ms by engine contract
                t = base + off.astype(jnp.int64)
                cols[time_col] = t
                cols["__time"] = t
            elif time_col and time_col in cols:
                cols["__time"] = cols[time_col]
            cols["__valid"] = (
                jnp.arange(chunk_rows, dtype=jnp.int32) < nrows
            )
            return cols

        self._prep_cache[key] = prep
        return prep

    # -- public entry points -------------------------------------------------

    def execute(
        self,
        q: Q.QuerySpec,
        ds: DataSource,
        chunks: Iterable[Mapping[str, np.ndarray]],
        chunk_rows: int,
    ):
        if isinstance(q, Q.TimeseriesQuery):
            df = self._execute_groupby(
                timeseries_to_groupby(q), ds, chunks, chunk_rows
            )
            return finalize_timeseries(df, q, ds)
        if isinstance(q, Q.TopNQuery):
            df = self._execute_groupby(
                topn_to_groupby(q), ds, chunks, chunk_rows
            )
            return finalize_topn(df, q)
        if isinstance(q, Q.GroupByQuery):
            return self._execute_groupby(q, ds, chunks, chunk_rows)
        raise NotImplementedError(
            f"streaming {type(q).__name__} (scan/search need no aggregation "
            "state — iterate chunks host-side instead)"
        )

    # -- core ----------------------------------------------------------------

    def _execute_groupby(
        self,
        q: Q.GroupByQuery,
        ds: DataSource,
        chunks: Iterable[Mapping[str, np.ndarray]],
        chunk_rows: int,
    ):
        q = groupby_with_time_granularity(q)
        pad_unit = ROW_PAD
        if self.mesh is not None:
            from ..parallel.mesh import DATA_AXIS

            pad_unit = ROW_PAD * self.mesh.shape[DATA_AXIS]
        if chunk_rows % pad_unit:
            chunk_rows = -(-chunk_rows // pad_unit) * pad_unit
        if (
            any(d.dimension == "__time" or d.granularity for d in q.dimensions)
            and not q.intervals
            and ds.interval() is None
        ):
            raise ValueError(
                "streaming time-bucketed queries need explicit intervals "
                "(a schema-only datasource has no segment time range to "
                "derive buckets from)"
            )
        lowering = lower_groupby(q, ds)
        la, G = lowering.la, lowering.num_groups
        need = list(lowering.columns)
        eng = self.engine

        prep = self._prep_fn(ds.time_column, chunk_rows)
        build_mesh_run = None
        dist_run = None
        if self.mesh is not None:
            # per-chunk SPMD program shared with DistributedEngine:
            # partials on each device's row shard (kernel routed by the
            # calibrated model AT THE PER-DEVICE SHAPE, same as every
            # other executor — round 4 hard-coded dense here, which at
            # high G cannot execute), psum/pmin/pmax + sketch merges over
            # ICI, replicated [G, M] state back
            from ..parallel.distributed import DistributedEngine
            from ..parallel.mesh import DATA_AXIS

            nd = self.mesh.shape[DATA_AXIS]
            strat = self._stream_strategy(G, chunk_rows // nd)
            dist = DistributedEngine(mesh=self.mesh)
            col_keys = list(need) + ["__valid"]
            if ds.time_column and ds.time_column in need:
                col_keys.append("__time")

            def build_mesh_run(strategy):
                return dist._spmd_fn(
                    lowering, chunk_rows // nd, ds, tuple(col_keys),
                    strategy=strategy,
                )

            dist_run = build_mesh_run(strat)
            run = lambda dev, base, nrows: dist_run(prep(dev, base, nrows))
        else:
            # prep (time reconstruction + validity) FUSED into the chunk
            # program: two back-to-back jits materialized a 16 MB int64
            # time column per 2M-row chunk between them (~30 ms/chunk on
            # CPU, measured) that XLA folds away entirely once fused
            strat = self._stream_strategy(G, chunk_rows)
            run = self._fused_local_fn(q, ds, lowering, prep, strat)

        sums = mins = maxs = None
        sketch_states: Dict[str, jnp.ndarray] = {}
        self.stats = StreamStats()
        t_disp = 0.0

        import time as _time

        from ..obs import SPAN_STREAM_CHUNK, span
        from ..resilience import checkpoint_partial, current_partial, fire

        pc = current_partial()
        if pc is not None:
            # an unbounded stream has no knowable denominator: the
            # collector records rows seen (coverage None) so a partial
            # answer still says HOW MUCH it aggregated
            pc.begin_pass()
        for dev, base, nrows in self._prefetched_device_chunks(
            chunks, need, ds, chunk_rows
        ):
            # cooperative deadline checkpoint + device-dispatch fault site:
            # a budgeted 1B-row stream cancels between chunks (or, with a
            # partial collector armed, stops consuming and answers with
            # the chunk partials merged so far), and injected device
            # faults hit the streaming path like every other executor
            if checkpoint_partial("streaming.chunk_loop"):
                break
            fire("device_dispatch")
            t0 = _time.perf_counter()
            with span(SPAN_STREAM_CHUNK, chunk=self.stats.chunks):
                from ..obs import prof

                try:
                    s, mn, mx, sk = run(dev, base, nrows)
                except Exception:  # fault-ok: _downgrade_pallas re-raises non-Pallas errors
                    run = self._downgrade_pallas(
                        q, ds, lowering, prep, build_mesh_run, strat
                    )
                    s, mn, mx, sk = run(dev, base, nrows)
                # sampled query: honest device split on the chunk span
                # (obs/prof.py; a strict no-op at the default rate)
                s = prof.dispatch_sync(s, t0)
            sums = s if sums is None else sums + s
            mins = mn if mins is None else jnp.minimum(mins, mn)
            maxs = mx if maxs is None else jnp.maximum(maxs, mx)
            _merge_sketch_states(la, sketch_states, sk)
            self.stats.chunks += 1
            if pc is not None:
                pc.add_seen(1, int(nrows))
            t_disp += _time.perf_counter() - t0
        self.stats.dispatch_s = t_disp

        if sums is None:  # empty stream
            sums, mins, maxs, sketch_states = empty_partials(la, G)

        from ..obs import SPAN_DEVICE_FETCH, SPAN_FINALIZE

        with span(SPAN_DEVICE_FETCH):
            sums, mins, maxs, sketch_states = jax.device_get(
                (sums, mins, maxs, sketch_states)
            )
        with span(SPAN_FINALIZE):
            return finalize_groupby(
                q, lowering.dims, la,
                np.asarray(sums), np.asarray(mins), np.asarray(maxs),
                {k: np.asarray(v) for k, v in sketch_states.items()},
            )

    def _stream_strategy(self, G: int, rows_per_dispatch: int) -> str:
        """Per-dispatch kernel class.  An engine constructed with an
        explicit strategy is honored through its own resolver (the local
        and mesh paths agree); "auto" routes through the CALIBRATED model
        at (rows_per_dispatch, G) — the shape each dispatch actually runs
        (per-device shard rows on a mesh).  Streaming accumulates dense
        [G, M] states across chunks, so only the dense-state classes
        apply: dense/Pallas one-hot or segment scatter.  This is the
        engine-level rule from the round-4 postmortems: every NEW
        execution path routes through the calibrated constants, never the
        static resolver (CPU and TPU invert dense-vs-scatter by ~200x)."""
        eng = self.engine
        if eng.strategy != "auto":
            return eng._resolve_strategy(G)
        from ..config import SessionConfig
        from ..plan.cost import choose_kernel_strategy

        cfg = getattr(eng, "_calibrated_cfg", None)
        if cfg is None:
            cfg = SessionConfig.load_calibrated()
            eng._calibrated_cfg = cfg
        strat = choose_kernel_strategy(rows_per_dispatch, G, cfg)
        if strat == "dense":
            from ..ops.groupby import SCATTER_CUTOVER
            from ..ops.pallas_groupby import pallas_available

            if (
                G <= SCATTER_CUTOVER
                and pallas_available()
                and not eng._pallas_broken
            ):
                strat = "pallas"
        return strat

    def _fused_local_fn(self, q, ds, lowering, prep, strat=None):
        """One jitted program per (query, chunk shape): prep + partial
        aggregation, cached on the engine's program cache so repeats and
        shape-identical streams reuse the compile."""
        eng = self.engine
        from .lowering import _query_key

        key = _query_key(q, ds) + (
            "stream-fused",
            prep,  # carries (time_col, chunk_rows) identity
            strat or eng._resolve_strategy(lowering.num_groups),
        )
        from ..obs import prof

        cached = eng._query_fn_cache.get(key)
        if cached is not None:
            prof.note_program_cache("stream-fused", hit=True)
            return cached
        prof.note_program_cache("stream-fused", hit=False)
        seg_fn = eng._segment_program(q, ds, lowering, strategy_override=strat)

        @jax.jit
        def fused(dev, base, nrows):
            return seg_fn([prep(dev, base, nrows)])

        eng._query_fn_cache[key] = fused
        return fused

    def _downgrade_pallas(
        self, q, ds, lowering, prep, build_mesh_run, strat
    ):
        """Mirror Engine._call_segment_program's Mosaic-failure downgrade
        for the streaming program (local AND mesh): flag Pallas broken,
        evict, rebuild on the XLA dense kernel — the same class — and let
        the retry surface real errors."""
        from ..ops.pallas_groupby import pallas_available

        eng = self.engine
        if eng._pallas_broken or not pallas_available() or strat != "pallas":
            raise  # re-raise the active exception: not a Pallas downgrade
        eng._pallas_broken = True
        for k in [
            k
            for k in eng._query_fn_cache
            if any("pallas" in str(p) for p in k[2:]) or "stream-fused" in k
        ]:
            eng._query_fn_cache.pop(k)
        if build_mesh_run is not None:
            fresh = build_mesh_run("dense")
            return lambda dev, base, nrows: fresh(prep(dev, base, nrows))
        return self._fused_local_fn(q, ds, lowering, prep, "dense")

    # -- chunk plumbing ------------------------------------------------------

    def _normalize_chunk(
        self,
        chunk: Mapping[str, np.ndarray],
        need,
        ds: DataSource,
        chunk_rows: int,
    ) -> Dict[str, np.ndarray]:
        """Host-side: select needed columns, cast to device dtypes, pad to
        the static chunk shape, add validity + __time."""
        first = next(iter(chunk.values()))
        rows = len(first)
        if rows > chunk_rows:
            raise ValueError(f"chunk has {rows} rows > chunk_rows={chunk_rows}")
        out: Dict[str, np.ndarray] = {}
        for n in need:
            a = np.asarray(chunk[n])
            if n in ds.dicts:
                a = a.astype(np.int32, copy=False)
                fill = NULL_ID
            elif ds.time_column and n == ds.time_column:
                # H2D narrowing: the stream is the H2D-bound path (BASELINE
                # config #4), and a chunk's time span virtually always fits
                # int32 ms (~24 days) — ship base + offsets, reconstruct
                # int64 on device.  Halves the widest column's bytes.
                # Skipped on the CPU backend: device_put there is a local
                # memcpy, so the narrowing's three extra host passes
                # (min/max/subtract) are pure loss (~30% of normalize time
                # at 1B rows, measured).
                a = a.astype(np.int64, copy=False)
                base = int(a[:rows].min()) if rows and self._narrow_time else 0
                span = (
                    int(a[:rows].max()) - base
                    if rows and self._narrow_time
                    else 1 << 31
                )
                if span < (1 << 31):
                    off = (a - base).astype(np.int32)
                    if rows < chunk_rows:
                        off = np.concatenate(
                            [off[:rows],
                             np.zeros(chunk_rows - rows, np.int32)]
                        )
                    out["__time_off"] = off
                    out["__time_base"] = np.int64(base)
                    continue
                fill = 0
            elif a.dtype.kind in ("i", "u", "b"):
                a = a.astype(np.int32, copy=False)
                fill = 0
            else:
                a = a.astype(np.float32, copy=False)
                fill = 0
            if rows < chunk_rows:
                pad = np.full(chunk_rows - rows, fill, dtype=a.dtype)
                a = np.concatenate([a, pad])
            out[n] = a
        # validity travels as the scalar row count (1 byte/row saved); the
        # device rebuilds the mask with one iota compare
        out["__rows"] = rows
        return out

    def _prefetched_device_chunks(
        self, chunks, need, ds: DataSource, chunk_rows: int
    ) -> Iterator[Dict[str, jnp.ndarray]]:
        """Background thread normalizes host chunks; the consumer side does
        the (async) device_put so all JAX interaction stays on one thread."""
        q: "queue.Queue" = queue.Queue(maxsize=self.prefetch)
        cancelled = threading.Event()

        def _put(item) -> bool:
            # bounded put that gives up when the consumer is gone, so a
            # failing query never leaves the producer parked in q.put
            # pinning chunk buffers and the source iterator
            while not cancelled.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        import time as _time

        def produce():
            try:
                # graftlint: disable=checkpoint-coverage -- producer THREAD: the deadline contextvar lives on the query thread; cancellation reaches this loop via cancelled.set() in the consumer's finally, and the consumer's chunk loop checkpoints
                for chunk in chunks:
                    t0 = _time.perf_counter()
                    item = self._normalize_chunk(chunk, need, ds, chunk_rows)
                    self.stats.normalize_s += _time.perf_counter() - t0
                    if not _put(item):
                        return
                _put(_STOP)
            except BaseException as e:  # fault-ok: surfaced to (re-raised by) consumer
                _put(e)

        sharding = None
        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            from ..parallel.mesh import DATA_AXIS

            sharding = NamedSharding(self.mesh, P(DATA_AXIS))

        from ..obs import SPAN_PREFETCH, span
        from .pipeline import pipelined_put

        # double buffering (exec/pipeline.py, ISSUE 10): hold ONE chunk
        # back so chunk k+1's h2d issue lands in the dispatch queue
        # BEFORE chunk k's compute program — the link streams behind the
        # device instead of serializing in front of it.  Disabled with
        # the engine's transfer pipeline (the bench's off-counterfactual).
        double_buffer = self.engine._pipeline.enabled
        held = None
        t = threading.Thread(target=produce, daemon=True)
        t.start()
        try:
            while True:
                item = q.get()
                if item is _STOP:
                    break
                if isinstance(item, BaseException):
                    raise item
                rows = item.pop("__rows")
                base = item.pop("__time_base", np.int64(0))
                t0 = _time.perf_counter()
                dev: Dict[str, jnp.ndarray] = {}
                nbytes = 0

                def put_all(item=item, dev=dev):
                    n = 0
                    for k, v in item.items():
                        dev[k], _dt, nb = pipelined_put(
                            v, sharding, prefetched=double_buffer
                        )
                        n += nb
                    return n

                if double_buffer:
                    # issue overlapped behind the previous chunk's compute
                    with span(
                        SPAN_PREFETCH, chunk=self.stats.chunks,
                        rows=int(rows),
                    ):
                        nbytes = put_all()
                else:
                    # pipeline off: this put is a foreground transfer the
                    # dispatch waits behind — honest receipt bucket is h2d
                    from ..obs import SPAN_H2D

                    with span(
                        SPAN_H2D, chunk=self.stats.chunks, rows=int(rows)
                    ):
                        nbytes = put_all()
                self.stats.put_s += _time.perf_counter() - t0
                self.stats.h2d_bytes += nbytes
                self.stats.rows += int(rows)
                if not double_buffer:
                    yield dev, base, np.int32(rows)
                    continue
                held, out = (dev, base, np.int32(rows)), held
                if out is not None:
                    yield out
            if held is not None:
                yield held
        finally:
            cancelled.set()
            while True:  # unblock a producer stuck on a full queue
                try:
                    q.get_nowait()
                except queue.Empty:
                    break
            t.join(timeout=5.0)
