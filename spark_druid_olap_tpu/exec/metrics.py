"""Per-query execution metrics (the observability subsystem).

Reference parity: the reference leans on Spark SQL scan-node metrics plus
Druid's server-side query metrics and has no dedicated tracer (SURVEY.md §5);
the TPU build owes the BASELINE metric set — rows/sec/chip, HBM bytes
streamed, kernel vs collective time.  `QueryMetrics` is populated by the
engines on every execution and surfaced via `TPUOlapContext.last_metrics`,
`explain_analyze()`, and bench detail JSON.

Phase semantics (wall-clock, single process):
  * `h2d_ms` / `h2d_bytes` — host->device column transfers this query caused
    (zero on residency-cache hits: the streamed-bytes metric).
  * `compile_ms` — time of the first program invocation when the XLA program
    for this (query, shape) was not yet compiled; includes that first
    execution (JAX jit compiles lazily; isolating pure-compile would need
    AOT shape pinning the segment loop doesn't want).  0 on warm paths.
  * `device_ms` — dispatch + block time of the remaining (steady-state)
    program calls plus the result fetch.
  * `est_collective_ms` — modelled ICI merge time for distributed runs
    (state bytes x ring factor / configured bandwidth); measured split of
    kernel-vs-collective inside one fused SPMD program is profiler
    territory: use `trace()` below.
  * `finalize_ms` — host-side result materialization.

`trace(logdir)` wraps `jax.profiler.trace` for the deep-dive path
(tensorboard-viewable device timelines incl. per-collective timing).
"""

from __future__ import annotations

import contextlib
import dataclasses
from typing import Optional


@dataclasses.dataclass
class QueryMetrics:
    query_type: str = ""
    strategy: str = ""
    # datasource the query scanned — labels the per-datasource traffic
    # counters (obs/registry.py, behind the label-cardinality guard)
    datasource: str = ""
    # the query's end-to-end id (obs/trace.py): set by the server boundary
    # (Druid's context.queryId when the client sent one) or generated at
    # the api layer; correlates this snapshot with its span tree in the
    # trace ring buffer and the slow-query log
    query_id: str = ""
    # which executor answered: "device" (local/distributed engine) or
    # "fallback" (host pandas interpreter, exec/fallback.py) — a user must
    # be able to SEE that a query left the accelerated path
    executor: str = "device"
    distributed: bool = False
    mesh_shape: Optional[tuple] = None
    rows_scanned: int = 0
    # bytes of segment data the query's kernel actually reads (needed
    # columns x rows, incl. validity/time) — the roofline numerator:
    # bytes_scanned / total_s vs the backend's measured streaming
    # bandwidth (plan/calibrate.py `stream_bytes_per_s`) says how close
    # the scan is to the memory-bound ceiling
    bytes_scanned: int = 0
    segments: int = 0
    num_groups: int = 0
    h2d_bytes: int = 0
    h2d_ms: float = 0.0
    compile_ms: float = 0.0
    device_ms: float = 0.0
    est_collective_ms: float = 0.0
    finalize_ms: float = 0.0
    total_ms: float = 0.0
    bytes_resident: int = 0
    program_cache_hit: bool = False
    # fallback observability (ADVICE r4): how many Aggregate subtrees the
    # host interpreter offloaded to the device engine this query.  Assisted
    # subtrees accumulate in f32 (vs the interpreter's float64) — rank/
    # comparison windows over near-ties can order differently; non-zero
    # here is the flag to check when chasing such a divergence
    assist_subplans: int = 0
    # query-lifecycle resilience (resilience.py): transient-failure
    # re-dispatches this query paid; whether it answered DEGRADED (device
    # path failed or breaker open -> host fallback); whether it died on its
    # deadline; the breaker state observed when the query was routed; and
    # the exception class when the query ultimately failed
    retries: int = 0
    degraded: bool = False
    deadline_exceeded: bool = False
    circuit_state: str = ""
    error_class: Optional[str] = None
    # deadline-bounded partial answers (ISSUE 7): True when the result is
    # best-effort (deadline expired mid-scan and the merged partials were
    # returned); `coverage` is the fraction of in-scope rows the answer
    # saw (None when the denominator is unknowable, e.g. an unbounded
    # stream), with the seen/total row counts and their delta-vs-
    # historical split alongside
    partial: bool = False
    coverage: Optional[float] = None
    rows_seen: int = 0
    delta_rows_seen: int = 0
    # performance attribution (obs/prof.py, ISSUE 9): the per-query cost
    # receipt — device/host/transfer split from the span tree, transfer
    # bytes, compile counts, and cache-tier outcomes (result cache,
    # fusion, residency, program cache).  Stamped by the api layer from
    # the live trace; None for direct engine use outside a trace.
    receipt: Optional[dict] = None
    # micro-batch fusion (serve/, ISSUE 8): when > 0, this query executed
    # as one member of an N-query fused device program — its dispatch
    # round trip was amortized N ways.  h2d/compile on a fused member are
    # the batch totals split evenly across members (the batch moves one
    # shared column set).
    fused_batch: int = 0

    @property
    def rows_per_sec(self) -> float:
        if self.total_ms <= 0:
            return 0.0
        return self.rows_scanned / (self.total_ms / 1e3)

    @property
    def scan_bytes_per_sec(self) -> float:
        if self.total_ms <= 0:
            return 0.0
        return self.bytes_scanned / (self.total_ms / 1e3)

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["rows_per_sec"] = round(self.rows_per_sec)
        d["scan_bytes_per_sec"] = round(self.scan_bytes_per_sec)
        for k, v in list(d.items()):
            if isinstance(v, float):
                d[k] = round(v, 3)
        return d

    def describe(self) -> str:
        tgt = (
            f"mesh{self.mesh_shape}" if self.distributed else "single-device"
        )
        return (
            f"QueryMetrics[{self.query_type} strategy={self.strategy} "
            f"executor={self.executor} "
            f"target={tgt} rows={self.rows_scanned} segments={self.segments} "
            f"groups={self.num_groups} total={self.total_ms:.2f}ms "
            f"(h2d={self.h2d_ms:.2f}ms/{self.h2d_bytes}B "
            f"compile={self.compile_ms:.2f}ms device={self.device_ms:.2f}ms "
            f"est_collective={self.est_collective_ms:.2f}ms "
            f"finalize={self.finalize_ms:.2f}ms) "
            f"rows/s={self.rows_per_sec:,.0f} "
            f"resident={self.bytes_resident}B "
            f"cache_hit={self.program_cache_hit}"
            + (f" retries={self.retries}" if self.retries else "")
            + (" DEGRADED" if self.degraded else "")
            + (" DEADLINE-EXCEEDED" if self.deadline_exceeded else "")
            + (
                f" PARTIAL(coverage="
                f"{'?' if self.coverage is None else round(self.coverage, 4)})"
                if self.partial
                else ""
            )
            + (
                f" circuit={self.circuit_state}"
                if self.circuit_state and self.circuit_state != "closed"
                else ""
            )
            + "]"
        )


@contextlib.contextmanager
def trace(logdir: str):
    """jax.profiler trace context for deep dives (kernel + collective
    timelines in tensorboard); no-op if the profiler is unavailable.

    Only PROFILER STARTUP is guarded: the old `try: with ...: yield`
    shape swallowed exceptions raised by the BODY and then yielded a
    second time — `RuntimeError: generator didn't stop after throw` —
    so a failing profiled query crashed with the wrong error (ISSUE 4
    satellite).  Body errors now propagate untouched; only a broken
    profiler start/stop degrades to a no-op."""
    prof = None
    try:
        import jax

        prof = jax.profiler.trace(logdir)
        prof.__enter__()
    except Exception:  # fault-ok: profiler is optional; trace degrades to no-op
        prof = None
    try:
        yield
    finally:
        if prof is not None:
            try:
                prof.__exit__(None, None, None)
            except Exception:  # fault-ok: profiler teardown must not mask body errors
                pass
