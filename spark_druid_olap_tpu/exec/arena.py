"""Segment-stacked resident arena: one-dispatch execution (ISSUE 14).

The receipts say the warm single-query floor is the dispatch loop: one
host->device program launch per segment batch, O(segments/unroll) round
trips per query even when every column is already resident.  The partial
-aggregate fold composes freely (arXiv:2603.26698's merge-tree algebra),
so the entire in-scope fold can run as ONE traced program — this module
is that program.

* **Stacked layout** — in-scope segments of EQUAL padded row count stack
  into one device-resident `[B, R]` array per column (plus the stacked
  `[B, R]` validity masks: each segment's existing ROW_PAD tail is the
  padding, so the stack adds zero pad waste).  The stack is placed
  through `Engine._put_device_col` under an `(("arena", *uids), ...)`
  key, so the residency byte budget, LRU eviction, h2d fault site, link
  accounting, and prefetch poisoning all hold unchanged.
* **One traced program** — `lax.scan` over the segment blocks with the
  partial fold INSIDE the trace.  The scan carry replicates the dispatch
  loop's exact fold tree (per-batch in-trace left fold, then cross-batch
  fold in canonical batch order) via boundary flags and live-flag
  selects, so results are BYTE-identical to the loop path: f32 partial
  sums are not reassociation-safe, and `jnp.where` is an exact bitwise
  select.  Fold-state carries are donated on backends that support
  aliasing (TPU/GPU), so the chunked scan never holds two copies of the
  `[G, M]` state.
* **Shape discipline** — `partial_aggregate`'s row-block partitioning
  depends on the segment's padded row count, so stacking UNEQUAL shapes
  to a common max would change the fold tree and break byte identity.
  The arena therefore covers the longest PREFIX of whole dispatch
  batches whose segments share one shape (the common case: uniform
  historicals, then a short tail / delta suffix); the remainder runs
  through the existing loop path and the cross-batch fold continues in
  canonical order.  Sketch aggregations (no exact in-carry identities)
  and sparse/adaptive routes decline the arena entirely.
* **Anytime answers** — with a deadline or partial collector armed the
  scan dispatches in per-batch chunks, carry threaded through, with
  `checkpoint_partial` between chunks: truncation lands exactly on the
  loop path's batch boundaries, so the coverage contract (seen segments
  / rows) is unchanged.
* **Fusion** — a fused micro-batch executes against ONE arena: members
  share the stacked columns and the scan computes every member's fold in
  the same dispatch, with per-block membership flags as DATA (not trace
  constants — one compiled program serves any member->segment mapping of
  the same shape).
* **Unified executor core** — `parallel/spmd_arena.py` shard_maps this
  exact fold (`_member_init` / `_fold_block` / `finish_member`) over a
  device-major permutation of the same stacked layout, with a psum/pmin/
  pmax boundary merge, so the single-device and mesh paths lower the ONE
  program; changes to the fold semantics here propagate to the SPMD path
  by construction.
"""

from __future__ import annotations

import contextlib
import contextvars
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..obs import SPAN_ARENA_BUILD, SPAN_SEGMENT_DISPATCH, prof, span
from ..resilience import checkpoint_partial, current_deadline, fire
from ..utils.log import get_logger

log = get_logger("exec.arena")

# the arena pins every covered batch's columns resident SIMULTANEOUSLY
# (the loop path pages batches through the LRU window); cap coverage at
# this fraction of the residency byte budget so one query cannot evict
# the whole working set behind itself
ARENA_BUDGET_FRACTION = 0.5

# kernel strategies whose per-segment partial program is shape-uniform
# and scannable.  sparse/adaptive never reach here (they route before
# the dense partials path); anything unrecognized declines to the loop.
_SCANNABLE = frozenset({"dense", "scatter", "pallas"})

# per-query opt-out (SessionConfig.arena_execution is the session-wide
# gate; this contextvar scopes a single execution)
_disabled: contextvars.ContextVar[bool] = contextvars.ContextVar(
    "arena_disabled", default=False
)


@contextlib.contextmanager
def arena_disabled():
    """Opt the enclosed executions out of the arena (per-query escape
    hatch: the dispatch loop is the reference path; the counterfactual
    bench and the byte-identity tests run both sides through this)."""
    tok = _disabled.set(True)
    try:
        yield
    finally:
        _disabled.reset(tok)


def query_disabled() -> bool:
    return _disabled.get()


def arena_key(uids: Sequence, kind: str, name: Optional[str] = None):
    """Residency-cache key of one stacked arena buffer.  The leading
    element is the `("arena", *uids)` tuple — never a bare segment uid,
    so `Engine.evict_segments`' per-segment pops cannot alias it, and
    the arena-slice invalidation scan can intersect on the uid tail."""
    head = ("arena",) + tuple(uids)
    return (head, kind, name) if name is not None else (head, kind)


def is_arena_key(key) -> bool:
    return (
        isinstance(key, tuple)
        and len(key) >= 2
        and isinstance(key[0], tuple)
        and len(key[0]) >= 1
        and key[0][0] == "arena"
    )


class ArenaPlan:
    """One query scope's arena coverage: which whole dispatch batches
    stack (uniform shape, within the byte-budget fraction), the per-block
    batch-start flags that replicate the loop path's fold tree, and the
    remainder batches the loop path still owns."""

    __slots__ = (
        "segs", "uids", "batches", "start", "remainder", "rows", "nbytes",
        "folded",
    )

    def __init__(self, batches_covered, remainder, nbytes):
        self.batches = [list(b) for b in batches_covered]
        self.segs = [s for b in self.batches for s in b]
        self.uids = tuple(s.uid for s in self.segs)
        start = np.zeros(len(self.segs), dtype=bool)
        pos = 0
        for b in self.batches:
            start[pos] = True
            pos += len(b)
        self.start = start
        self.remainder = list(remainder)
        self.rows = sum(s.num_rows for s in self.segs)
        self.nbytes = int(nbytes)
        # covered batches actually folded so far (run_plan updates it
        # per chunk): the caller's fallback/truncation decisions key off
        # whether any state exists yet
        self.folded = 0


def plan_for(engine, batches, names) -> Optional[ArenaPlan]:
    """Coverage decision for one scope's dispatch batches, or None when
    the arena cannot beat the loop (fewer than two coverable batches:
    the loop path is already one dispatch, and stacking would only add
    a host copy)."""
    batches = [list(b) for b in batches]
    if len(batches) < 2:
        return None
    shape0 = batches[0][0].num_rows_padded
    budget = int(
        engine._device_cache.budget_bytes * ARENA_BUDGET_FRACTION
    )
    covered: List[List] = []
    nbytes = 0
    for b in batches:
        if any(s.num_rows_padded != shape0 for s in b):
            break
        est = sum(
            int(s.valid.nbytes)
            + sum(int(s.column(n).nbytes) for n in names)
            for s in b
        )
        if covered and nbytes + est > budget:
            break
        covered.append(b)
        nbytes += est
    if len(covered) < 2:
        return None
    return ArenaPlan(covered, batches[len(covered):], nbytes)


def stacked_cols(engine, ds, plan: ArenaPlan, names) -> Dict[str, Any]:
    """Fetch (or build and place) the plan's stacked `[B, R]` columns.

    Every placement goes through `Engine._put_device_col` (transfer-
    discipline GL19xx): residency accounting, the byte-budget LRU, the
    h2d fault site, and link attribution all see the stack exactly like
    any segment column.  Retired-uid poisoning is handled upstream —
    `Engine.evict_segments` drops intersecting arena slices, and a plan
    is built from a consistent datasource snapshot."""
    cols: Dict[str, Any] = {}

    def lookup(key, host_fn):
        arr = engine._device_cache.get(key)
        if arr is not None:
            prof.note_residency(hit=True)
            return arr
        exc = engine._pipeline.take_poison(key)
        if exc is not None:
            raise exc
        prof.note_residency(hit=False)
        return engine._put_device_col(key, host_fn(), ds.name)

    for n in names:
        cols[n] = lookup(
            arena_key(plan.uids, "col", n),
            lambda n=n: np.stack(
                [np.asarray(s.column(n)) for s in plan.segs]
            ),
        )
    cols["__valid"] = lookup(
        arena_key(plan.uids, "valid"),
        lambda: np.stack([np.asarray(s.valid) for s in plan.segs]),
    )
    if ds.time_column and ds.time_column in cols:
        cols["__time"] = cols[ds.time_column]
    return cols


# ---------------------------------------------------------------------------
# the one traced program
# ---------------------------------------------------------------------------


def _donate_carry() -> bool:
    """Donate the fold-state carry across chunk dispatches?  Buffer
    aliasing is implemented on TPU/GPU; the CPU backend ignores the
    request with a warning per compile, so stay quiet there."""
    import jax

    return jax.default_backend() != "cpu"


def _select(flag, a, b):
    """Exact bitwise per-leaf select (jnp.where never reassociates)."""
    import jax.numpy as jnp

    return jnp.where(flag, a, b)


def _member_init(lowering):
    """Zero-seeded carry for one member: (total, batch) x (sums, mins,
    maxs, live).  Values behind a False live flag are dead by
    construction (every read is select-guarded), so zeros are safe —
    no -0.0 / identity-element hazards can reach a live lane."""
    import jax.numpy as jnp

    la, G = lowering.la, lowering.num_groups
    z = (
        jnp.zeros((G, len(la.sum_names)), jnp.float32),
        jnp.zeros((G, len(la.min_names)), jnp.float32),
        jnp.zeros((G, len(la.max_names)), jnp.float32),
        jnp.asarray(False),
    )
    return z + z  # (t_s, t_mn, t_mx, t_live, b_s, b_mn, b_mx, b_live)


def _fold_block(carry_i, block_state, start_b, memb_b):
    """One member's carry update for one segment block — the loop path's
    exact fold tree, replayed with live-flag selects:

      * at a batch START, the accumulated batch state flushes into the
        total (the loop path's host-side cross-batch fold), but only if
        the member accumulated anything in that batch (the fused loop's
        None-skip);
      * then the block's partial folds into the (possibly fresh) batch
        accumulator, gated on the member's block membership."""
    import jax.numpy as jnp

    t_s, t_mn, t_mx, t_live, b_s, b_mn, b_mx, b_live = carry_i
    s, mn, mx = block_state
    flush = jnp.logical_and(start_b, b_live)
    t_s = _select(flush, _select(t_live, t_s + b_s, b_s), t_s)
    t_mn = _select(flush, _select(t_live, jnp.minimum(t_mn, b_mn), b_mn), t_mn)
    t_mx = _select(flush, _select(t_live, jnp.maximum(t_mx, b_mx), b_mx), t_mx)
    t_live = jnp.logical_or(t_live, flush)
    b_live = jnp.logical_and(b_live, jnp.logical_not(start_b))
    b_s2 = _select(memb_b, _select(b_live, b_s + s, s), b_s)
    b_mn2 = _select(
        memb_b, _select(b_live, jnp.minimum(b_mn, mn), mn), b_mn
    )
    b_mx2 = _select(
        memb_b, _select(b_live, jnp.maximum(b_mx, mx), mx), b_mx
    )
    b_live = jnp.logical_or(b_live, memb_b)
    return (t_s, t_mn, t_mx, t_live, b_s2, b_mn2, b_mx2, b_live)


def build_arena_program(lowerings, strategies, share=None):
    """The ONE traced scan over stacked segment blocks, computing every
    member's partial fold in a single dispatch.  Signature:

        fn(carry, cols, start, memb) -> carry

    `cols` maps column name -> [Bc, R]; `start` is the [Bc] batch-start
    flag vector; `memb` is [Bc, n_members] block membership.  Flags are
    DATA, not trace constants: one compiled program (per chunk shape)
    serves any membership pattern.  Chunking threads the carry through
    repeated calls — the op sequence (hence byte identity) is invariant
    to where the chunk boundaries fall."""
    import functools

    import jax
    from jax import lax

    from .engine import _segment_partials

    n = len(lowerings)

    def fn(carry, cols, start, memb):
        def body(c, xs):
            cols_b, start_b, memb_b = xs
            memo: Dict[Any, Any] = {}
            out = []
            for i in range(n):
                s, mn, mx, _sk = _segment_partials(
                    lowerings[i],
                    strategies[i],
                    dict(cols_b),
                    memo=memo if share is not None else None,
                    share=share[i] + (0,) if share is not None else None,
                )
                out.append(
                    _fold_block(
                        c[i], (s, mn, mx), start_b, memb_b[i]
                    )
                )
            return tuple(out), None
        c2, _ = lax.scan(body, carry, (cols, start, memb))
        return c2

    # pure builder: every caller (Engine._arena_program /
    # _arena_fused_program) stores the result in the engine program
    # cache under a structured query key
    donate = {"donate_argnums": (0,)} if _donate_carry() else {}
    # graftlint: disable=jit-cache -- caller caches under a query key
    return jax.jit(fn, **donate)


def finish_member(carry_i):
    """Final batch->total flush of one member's carry (the loop path's
    last host-side fold).  Returns (sums, mins, maxs, live) — `live` is
    False when the member touched no block (empty scope: the caller
    substitutes `empty_partials`, exactly like the loop path)."""
    import jax.numpy as jnp

    t_s, t_mn, t_mx, t_live, b_s, b_mn, b_mx, b_live = carry_i
    s = _select(b_live, _select(t_live, t_s + b_s, b_s), t_s)
    mn = _select(
        b_live, _select(t_live, jnp.minimum(t_mn, b_mn), b_mn), t_mn
    )
    mx = _select(
        b_live, _select(t_live, jnp.maximum(t_mx, b_mx), b_mx), t_mx
    )
    return s, mn, mx, jnp.logical_or(t_live, b_live)


def _site_armed(site: str) -> bool:
    """Is fault injection armed at `site`?  Lock-free when the injector
    singleton was never constructed (the production fast path)."""
    if not site:
        return False
    from .. import resilience as _res

    inj = _res._injector
    return inj is not None and inj.armed(site)


def _chunk_bounds(plan: ArenaPlan, site: str = "") -> List[Tuple[int, int, int]]:
    """(block_lo, block_hi, batch_index) per dispatch chunk.  One chunk
    per BATCH when a wall-clock deadline is armed — or fault injection
    targets the checkpoint site — so truncation lands exactly on the
    loop path's batch boundaries, keeping the anytime-answer coverage
    contract.  One chunk for the whole plan otherwise — the O(1)
    -dispatch fast path.  A partial collector ALONE does not chunk: the
    served default arms one on every query, but without a deadline the
    loop path's checkpoints never truncate either, so the single-chunk
    scan honors the same contract for free."""
    if current_deadline() is None and not _site_armed(site):
        return [(0, len(plan.segs), len(plan.batches) - 1)]
    out = []
    pos = 0
    for bi, b in enumerate(plan.batches):
        out.append((pos, pos + len(b), bi))
        pos += len(b)
    return out


def run_plan(
    engine, ds, plan: ArenaPlan, names, program, lowerings,
    memb: Optional[np.ndarray] = None, pc=None, checkpoint_site="",
    single_chunk: bool = False,
):
    """Build/fetch the stacked columns, then dispatch the scan program
    over the plan's chunks.  Returns (carries, batches_folded) — the
    final per-member carry tuple plus how many covered batches actually
    folded (fewer than planned on a deadline/partial truncation).

    The stack build lives under the `arena_build` receipt bucket; each
    chunk dispatch is a `segment_dispatch` span, so `dispatch_count`
    and the device/transfer attribution stay honest."""
    import time as _time

    import jax.numpy as jnp

    from .engine import _row_counts

    # the FIRST chunk's deadline checkpoint runs before the stack build
    # (the chunk-0 check in the loop below is skipped): an already-gone
    # deadline skips the H2D work entirely and hands the caller zero
    # folded batches.  Hoisting (not adding) the call keeps the site's
    # call count identical to the loop path's one-per-batch cadence, so
    # skip=K fault injection truncates both paths at the same boundary.
    if checkpoint_site and checkpoint_partial(checkpoint_site):
        return tuple(_member_init(lw) for lw in lowerings), 0
    with span(
        SPAN_ARENA_BUILD, blocks=len(plan.segs), batches=len(plan.batches),
    ):
        cols = stacked_cols(engine, ds, plan, names)
    start = jnp.asarray(plan.start)
    if memb is None:
        memb_arr = jnp.ones((len(plan.segs), 1), dtype=bool)
    else:
        memb_arr = jnp.asarray(memb)
    carries = tuple(_member_init(lw) for lw in lowerings)
    # the fused path forces one chunk: its deadline contract is checked
    # once up front by the caller and an expiry re-routes members to
    # their serial partial-capable paths — no mid-scan truncation
    chunks = (
        [(0, len(plan.segs), len(plan.batches) - 1)]
        if single_chunk
        else _chunk_bounds(plan, checkpoint_site)
    )
    done = 0
    for ci, (lo, hi, last_bi) in enumerate(chunks):
        # ci == 0 was checkpointed above, before the build
        if ci and checkpoint_site and checkpoint_partial(checkpoint_site):
            break
        xs_cols = {n: a[lo:hi] for n, a in cols.items()}
        # the same fault-injection site every loop-path dispatch fires:
        # an injected (or real pre-dispatch) transient fault walks the
        # retry/breaker machinery whether or not the arena is on
        fire("device_dispatch")
        m = engine._m
        with span(
            SPAN_SEGMENT_DISPATCH,
            arena=hi - lo,
            chunk=f"{ci + 1}/{len(chunks)}",
        ):
            # first call of a newly-built program = trace+compile:
            # attribute it exactly like _call_segment_program does
            t0 = (
                _time.perf_counter()
                if ci == 0
                and m is not None
                and not m.program_cache_hit
                and m.compile_ms == 0
                else None
            )
            t_call = _time.perf_counter()
            carries = program(
                carries, xs_cols, start[lo:hi], memb_arr[lo:hi]
            )
            carries = prof.dispatch_sync(carries, t_call)
            if t0 is not None:
                m.compile_ms = (_time.perf_counter() - t0) * 1e3
                prof.note_compile(m.compile_ms)
        if pc is not None:
            for bi in range(done, last_bi + 1):
                b = plan.batches[bi]
                pc.add_seen(len(b), *_row_counts(b))
        done = last_bi + 1
        plan.folded = done
    return carries, done
