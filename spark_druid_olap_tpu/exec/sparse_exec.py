"""Sparse (sort-compaction) execution orchestration.

The capacity-ladder dispatch/evict/fetch machinery for the high-cardinality
GroupBy path — an engine-within-the-engine that round 2's review flagged for
extraction (VERDICT r2 #9).  `Engine` mixes this in; every attribute it
touches (`_query_fn_cache`, `_pallas_broken`, `_sparse_row_capacity`,
segment iteration, metrics) lives on the engine instance, so this is purely
a file split: same methods, same behavior, pinned by the existing
tests/test_sparse_groupby.py suite.

Reference parity: the reference has no analog (Druid's own scan does the
high-cardinality work server-side); this is TPU-native machinery for keeping
huge group domains on the accelerator (SURVEY.md §2 native-components row).
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import numpy as np

from ..catalog.segment import DataSource
from ..models import query as Q
from ..resilience import DeadlineExceeded
from ..utils.log import get_logger
from .finalize import finalize_groupby
from .lowering import GroupByLowering, _query_key, memo_key

log = get_logger("exec.sparse")


class SparseExecMixin:

    def _sparse_eligible(self, lowering: "GroupByLowering") -> bool:
        """Sparse applies when the scatter path would otherwise run: huge
        combined domain, plain (non-sketch) aggregates, and real dimensions.
        Sketch states are [G, registers] dense — compaction would have to
        re-key them too; at high G those queries stay on scatter."""
        from ..ops.groupby import SCATTER_CUTOVER

        # explicit strategy='segment' is the raw-scatter escape hatch and is
        # honored as such (ADVICE r1: the sparse accelerator must not hijack
        # an explicitly requested kernel).  The cost model emits 'sparse'
        # when compaction should run; 'auto'/'dense' only self-upgrade on a
        # TPU backend — measured on CPU, raw scatter beats sort-compaction
        # at every domain size, so auto-sparse there is a pure loss.
        from ..ops.pallas_groupby import pallas_available

        auto_upgrade = (
            self.strategy in ("auto", "dense")
            and pallas_available()
            and not self._pallas_broken
        )
        return (
            lowering.num_groups > SCATTER_CUTOVER
            and not lowering.la.sketch_aggs
            and bool(lowering.dims)
            # 'adaptive' falls through here when per-dim marginals didn't
            # shrink: jointly-sparse domains are exactly the sparse tier's
            # case
            and (auto_upgrade or self.strategy in ("sparse", "adaptive"))
        )

    def _sparse_program(
        self,
        q: Q.GroupByQuery,
        ds: DataSource,
        lowering: "GroupByLowering",
        row_capacity: Optional[int] = None,
        slots: Optional[int] = None,
    ) -> Callable:
        from ..ops.pallas_groupby import pallas_available
        from ..ops.sparse_groupby import (
            SPARSE_SLOTS,
            sparse_partial_aggregate,
        )

        la = lowering.la
        slots = slots or SPARSE_SLOTS
        # inner kernel over the compacted slots: the Pallas one-hot on TPU;
        # scatter on CPU backends (4096-slot one-hot matmuls starve a CPU,
        # and at `slots` segments CPU scatter is cheap).  Past SPARSE_SLOTS
        # a non-scatter inner routes to the segmented-reduce-over-ranks
        # kernel inside sparse_partial_aggregate (the sort-agg tier).
        inner = (
            "pallas"
            if not self._pallas_broken and pallas_available()
            else "segment"
        )
        # structured key, NOT an f-string: interpolation collapses distinct
        # identities (None vs "None") and the pallas-eviction scan matches
        # on the rendered tuple (graftlint jit-cache/GL103)
        key = _query_key(q, ds) + ("sparse", inner, row_capacity, slots)
        from ..obs import prof

        cached = self._query_fn_cache.get(key)
        if cached is not None:
            if self._m is not None:
                self._m.program_cache_hit = True
            prof.note_program_cache("sparse", hit=True)
            return cached
        prof.note_program_cache("sparse", hit=False)

        from ..ops.sparse_groupby import merge_sparse_states

        def one_segment(cols):
            gid, mask, sv, mmv, mmm = lowering.row_arrays(dict(cols))
            return sparse_partial_aggregate(
                gid, mask, sv, mmv, mmm,
                num_groups=lowering.num_groups,
                num_min=len(la.min_names),
                num_max=len(la.max_names),
                slots=slots,
                inner_strategy=inner,
                row_capacity=row_capacity,
            )

        @jax.jit
        def seg_fn(cols_list):
            state = None
            for cols in cols_list:
                st = one_segment(cols)
                state = (
                    st
                    if state is None
                    else merge_sparse_states(
                        state, st, num_groups=lowering.num_groups
                    )
                )
            return state

        self._query_fn_cache[key] = seg_fn
        return seg_fn

    def _dispatch_groupby_sparse(
        self, q: Q.GroupByQuery, ds: DataSource, lowering: "GroupByLowering"
    ):
        """Sparse execution attempt over the (non-empty) segment scope,
        split into an eager dispatch phase and a deferred fetch so N queries
        (a grouping-set expansion) can overlap their device round trips.

        Dispatches the tier-1 program asynchronously and returns
        `resolve() -> (df, reason)`: df is None when declining, with reason
        "overflow" (deterministic — more distinct groups than slots: the
        caller pins the query off this path) or "error" (sparse program
        failed even after the Pallas-inner retry: fall back this execution
        only; correctness never depends on this path).  A trace/compile
        failure at dispatch time is carried into resolve() and handled by
        the same downgrade path as an execution failure."""
        from ..ops.sparse_groupby import merge_sparse_states

        segs = self._segments_in_scope(q, ds)
        G = lowering.num_groups
        # The selective-filter fast path only makes sense when rows can
        # actually be masked out (a filter or time intervals); an unfiltered
        # segment would overflow the capacity by construction.
        selective = q.filter is not None or bool(q.intervals)

        def dispatch(row_capacity=None, slots=None):
            from ..obs import SPAN_SPARSE_DISPATCH, span
            from ..resilience import checkpoint_partial, current_partial, fire
            from .engine import _row_counts

            # fault-injection site: the sparse tier IS a device dispatch,
            # so "100% device failure" (`device_dispatch` armed) must take
            # it down exactly like the dense engine's — otherwise a
            # breaker half-open probe routed to a sparse-strategy query
            # succeeds and closes the breaker while the device is dead.
            # Placed OUTSIDE resolve()'s Mosaic-downgrade retry so the
            # injected transient declines this execution only and never
            # pins _pallas_broken (same contract as engine.py's site).
            fire("device_dispatch")
            seg_fn = self._sparse_program(
                q, ds, lowering, row_capacity=row_capacity, slots=slots
            )
            pc = current_partial()
            if pc is not None:
                pc.begin_pass()
                pc.add_scope(len(segs), *_row_counts(segs))
            state = None
            from .pipeline import CanonicalFold

            batches = list(self._segment_batches(segs, lowering.columns))
            # transfer pipeline (exec/pipeline.py): resident batches
            # dispatch first, the next cold batches' columns stream
            # behind the sparse compute.  The merge below is a scatter
            # (order-sensitive in float), so CanonicalFold pins it to
            # canonical batch order regardless of dispatch order —
            # pipeline-on stays byte-identical to pipeline-off.
            run = self._pipeline.start(ds, batches, lowering.columns)

            def fold_one(st):
                nonlocal state
                state = (
                    st
                    if state is None
                    else merge_sparse_states(state, st, num_groups=G)
                )

            folder = CanonicalFold(fold_one)
            for pos, bi in enumerate(run.order):
                # cooperative deadline checkpoint between batch
                # dispatches — same lifecycle contract as the dense
                # engine's segment loop (checkpoint-coverage/GL901);
                # with a partial collector armed, expiry stops the loop
                # (and any pending prefetch) and the merged sparse state
                # so far becomes the answer
                if checkpoint_partial("sparse.segment_loop"):
                    run.cancel()
                    break
                batch = batches[bi]
                with span(SPAN_SPARSE_DISPATCH, batch=bi, segments=len(batch)):
                    import time as _time

                    from ..obs import prof

                    cols_list = [
                        self._cols_for_segment(seg, ds, lowering.columns)
                        for seg in batch
                    ]
                    run.advance(pos)
                    t_call = _time.perf_counter()
                    st = seg_fn(cols_list)
                    # sampled query: honest enqueue-vs-device split on
                    # the sparse dispatch span (obs/prof.py; no-op off)
                    st = prof.dispatch_sync(st, t_call)
                    folder.add(bi, st)
                if pc is not None:
                    pc.add_seen(len(batch), *_row_counts(batch))
            folder.drain()
            return state

        def evict():
            # only THIS query's sparse programs — other queries' compiled
            # sparse programs are fine and expensive to rebuild
            base = _query_key(q, ds)
            for k in [
                k
                for k in self._query_fn_cache
                if k[:2] == base and str(k[2]).startswith("sparse")
            ]:
                self._query_fn_cache.pop(k)

        # learned rungs key segment-set-independently (see lowering.memo_key):
        # appends must not forget them or leak one entry per delta publish
        qkey = memo_key(q, ds)
        from ..ops import sparse_groupby as _sg

        # tier 1: filter-compacted sort.  The initial capacity rung comes
        # from the planner's selectivity estimate with 2x headroom (the
        # remembered rung from a previous overflow wins when present) —
        # sorting a fixed 128K slots per segment regardless of survivors
        # was round 3's hidden per-segment cost.  A None rung = full sort.
        if not selective:
            cap = None
        elif qkey in self._sparse_row_capacity:
            cap = self._sparse_row_capacity[qkey]
        else:
            from ..plan.cost import estimate_selectivity

            sel = (
                estimate_selectivity(q.filter, ds)
                if q.filter is not None
                else 1.0
            )
            if sel >= 1.0:
                # unmodeled filter or interval-only scope: no estimate to
                # act on — keep the historical default rung (the overflow
                # ladder corrects upward, never a full-segment sort here)
                cap = _sg.ROW_CAPACITY
            else:
                seg_rows = max((s.num_rows for s in segs), default=1)
                need = 2.0 * sel * seg_rows
                cap = next(
                    (c for c in _sg.ROW_CAPACITY_LADDER if c >= need), None
                )
        # slot capacity: SPARSE_SLOTS one-hot by default, or the remembered
        # SLOTS_LADDER rung (segmented-reduce tier) from a prior overflow
        slots0 = self._sparse_slots.get(qkey, _sg.SPARSE_SLOTS)

        def fetch_tiered(state, row_capacity, slots):
            # On row overflow the kernel's exact survivor count picks the
            # smallest adequate ROW_CAPACITY_LADDER rung (full-R sort only
            # past the top rung) — sort cost grows ~linearly with capacity,
            # so q3_1-class queries (180K survivors of 6M rows) stay 3-4x
            # off the full sort.  The rung is deterministic per (query,
            # data) and remembered.  Slot overflow is handled by the
            # caller's SLOTS_LADDER loop.
            from ..obs import SPAN_DEVICE_FETCH, span
            from ..resilience import current_partial

            with span(SPAN_DEVICE_FETCH):
                host = jax.device_get(state)
            if row_capacity is not None and bool(host["row_overflow"]):
                pc = current_partial()
                if pc is not None and pc.triggered:
                    # partial drain: a ladder rerun would re-dispatch an
                    # already-stopped scope (dispatch() breaks at its
                    # first checkpoint and returns None) — decline this
                    # execution instead; the dense drain answers
                    return None
                n = int(host["n_rows"])
                new_cap = next(
                    (
                        c
                        for c in _sg.ROW_CAPACITY_LADDER
                        if c >= n and c > row_capacity
                    ),
                    None,
                )
                self._sparse_row_capacity[qkey] = new_cap
                log.info(
                    "sparse row compaction overflowed %d of capacity %d; "
                    "rerunning at %s (remembered for repeats)",
                    n, row_capacity,
                    "full-segment sort" if new_cap is None else new_cap,
                )
                host = jax.device_get(
                    dispatch(row_capacity=new_cap, slots=slots)
                )
            return host

        def fetch_slot_laddered(state, row_capacity, slots):
            # Slot-capacity ladder (VERDICT r3 #2): when more groups are
            # GENUINELY populated than the one-hot slot tier holds, rung up
            # through the segmented-reduce capacities instead of abandoning
            # the device path.  The kernel's exact distinct-present count
            # (`n_real`) picks the smallest adequate rung; only past the
            # ladder top does the query fall back to raw scatter.
            from ..resilience import checkpoint, current_partial

            host = fetch_tiered(state, row_capacity, slots)
            while host is not None and bool(host["overflow"]):
                pc = current_partial()
                if pc is not None and pc.triggered:
                    # partial drain: no rung rerun (see fetch_tiered)
                    return None, slots
                # every ladder rung re-dispatches the whole segment
                # scope — a deadlined query must cancel between rungs,
                # not after the ladder converges
                checkpoint("sparse.slots_ladder")
                n_est = int(host["n_real"])
                new_slots = next(
                    (
                        s
                        for s in _sg.SLOTS_LADDER
                        if s >= n_est and s > slots
                    ),
                    None,
                )
                if new_slots is None:
                    # an overflowed merge reports max-per-state n_real — a
                    # LOWER bound (ADVICE r4) — so a bound past the ladder
                    # top does not prove the true count is: ladder up one
                    # rung at a time and let the rerun's exact count decide.
                    new_slots = next(
                        (s for s in _sg.SLOTS_LADDER if s > slots), None
                    )
                if new_slots is None:
                    return host, slots  # beyond the ladder: caller declines
                self._sparse_slots[qkey] = new_slots
                log.info(
                    "sparse slots overflowed (~%d distinct present > %d); "
                    "rerunning on the segmented-reduce tier at %d slots "
                    "(remembered for repeats)",
                    n_est, slots, new_slots,
                )
                slots = new_slots
                row_capacity = self._sparse_row_capacity.get(
                    qkey, row_capacity
                )
                host = fetch_tiered(
                    dispatch(row_capacity=row_capacity, slots=slots),
                    row_capacity,
                    slots,
                )
            return host, slots

        # phase 1: dispatch (async — no fetch).  Exceptions are deferred
        # into resolve() so batch callers see the same decline protocol as
        # execution failures.  Record which inner kernel THIS dispatch used:
        # in batch mode an earlier query's resolve may flip _pallas_broken
        # between our dispatch and our resolve, and the downgrade retry must
        # key on what we actually ran, not the current flag.
        from ..ops.pallas_groupby import pallas_available

        used_pallas_inner = not self._pallas_broken and pallas_available()
        state = dispatch_exc = None
        try:
            state = dispatch(row_capacity=cap, slots=slots0)
        except Exception as exc:  # fault-ok: re-raised in resolve below
            dispatch_exc = exc

        def resolve():
            nonlocal state
            try:
                if dispatch_exc is not None:
                    raise dispatch_exc
                if state is None:
                    # a partial drain armed BEFORE this dispatch started:
                    # nothing was dispatched, so there is no sparse state
                    # to answer from — decline (never error-counted) and
                    # let the dense path produce the zero-coverage answer
                    return None, "declined"
                host, _ = fetch_slot_laddered(state, cap, slots0)
                state = None  # free the device partials promptly
            except DeadlineExceeded:
                # partial-result discipline (GL16xx): an expiry that the
                # partial machinery did NOT absorb (no collector armed)
                # must propagate as a deadline, never be swallowed into
                # the generic sparse-decline path — retrying the whole
                # scope on the dense engine would only time out slower
                state = None
                raise
            except Exception:  # fault-ok: returns "error"; caller logs + falls back
                state = None
                evict()
                # mirror _call_segment_program: a Mosaic failure of the
                # Pallas inner kernel downgrades to the scatter inner, not
                # to the whole-query scatter path
                if not used_pallas_inner or not pallas_available():
                    return None, "error"
                we_broke_it = not self._pallas_broken
                self._pallas_broken = True
                try:
                    # the failed attempt may already have learned the right
                    # row-capacity / slot rungs; retry there, not at the
                    # stale ones
                    retry_cap = self._sparse_row_capacity.get(qkey, cap)
                    retry_slots = self._sparse_slots.get(qkey, slots0)
                    host, _ = fetch_slot_laddered(
                        dispatch(row_capacity=retry_cap, slots=retry_slots),
                        retry_cap,
                        retry_slots,
                    )
                except DeadlineExceeded:
                    if we_broke_it:
                        self._pallas_broken = False
                    raise  # a deadline is never a Pallas verdict
                except Exception:  # fault-ok: returns "error"; caller logs + falls back
                    # only unflag if WE set the flag — an earlier query may
                    # have legitimately discovered the broken kernel
                    if we_broke_it:
                        self._pallas_broken = False
                    evict()
                    return None, "error"
            if host is None:
                # a partial drain stopped a ladder rerun mid-scope:
                # decline (never error-counted) — the dense drain
                # produces the best-effort answer
                return None, "declined"
            if bool(host["overflow"]):
                return None, "overflow"
            df = finalize_groupby(
                q,
                lowering.dims,
                lowering.la,
                np.asarray(host["sums"]),
                np.asarray(host["mins"]),
                np.asarray(host["maxs"]),
                {},
                slot_gids=np.asarray(host["gids"]),
            )
            return df, "ok"

        return resolve

