"""Host-side result finalization (the broker-merge tail of SURVEY.md §3.3).

Split out of exec/engine.py (VERDICT r1 weak #8).  Everything that turns
merged partial aggregate state into the result DataFrame — group-id decode,
post-aggregations, having, sort/limit, empty-bucket fill, TopN ranking — plus
the device-state merge helpers shared by the local, distributed, and
streaming executors (semantics cannot drift when there is one
implementation).
"""

from __future__ import annotations

from typing import Any, Dict, List, Mapping, Optional

import jax.numpy as jnp
import numpy as np

from ..catalog.segment import DataSource
from ..models import aggregations as A
from ..models import query as Q
from ..utils.granularity import bucket_starts
from .lowering import LoweredAggs, ResolvedDim

def finalize_timeseries(df, q: Q.TimeseriesQuery, ds: DataSource):
    """Shared Timeseries finalization: empty-bucket zero-fill + ordering."""
    import pandas as pd

    tcol = q.output_name
    if not q.skip_empty_buckets:
        iv = q.intervals[0] if q.intervals else ds.interval()
        if iv is not None:
            lo = min(a for a, _ in q.intervals) if q.intervals else iv[0]
            hi = max(b for _, b in q.intervals) if q.intervals else iv[1]
            # interval ends are EXCLUSIVE: a bucket starting exactly at
            # `hi` is outside the query (Druid emits no zero bucket there)
            all_buckets = bucket_starts(
                lo, max(lo, hi - 1), q.granularity
            ).astype("datetime64[ms]")
            df = (
                df.set_index(tcol)
                .reindex(pd.Index(all_buckets, name=tcol))
                .reset_index()
            )
            for a in q.aggregations:
                if a.merge_op == "psum" and a.name in df:
                    filled = df[a.name].fillna(0)
                    if df[a.name].dtype.kind in ("i", "u"):
                        filled = filled.astype(np.int64)
                    df[a.name] = filled
    df = df.sort_values(tcol, ascending=not q.descending)
    return df.reset_index(drop=True)


def finalize_topn(df, q: Q.TopNQuery):
    """Shared TopN ranking, including per-bucket ranking under a non-'all'
    granularity."""
    df = df.sort_values(q.metric, ascending=not q.descending, kind="stable")
    if q.granularity not in ("all", None):
        df = (
            df.groupby("timestamp", sort=True, group_keys=False)
            .head(q.threshold)
            .sort_values(
                ["timestamp", q.metric],
                ascending=[True, not q.descending],
                kind="stable",
            )
        )
        return df.reset_index(drop=True)
    return df.head(q.threshold).reset_index(drop=True)


# ---------------------------------------------------------------------------
# Post-aggregation / having / limit finalization (host-side, tiny)
# ---------------------------------------------------------------------------


def eval_post_agg(
    p: A.PostAggregation,
    table: Mapping[str, np.ndarray],
    states: Optional[Mapping[str, np.ndarray]] = None,
) -> np.ndarray:
    """`states` maps sketch-agg name -> raw per-group sketch state (HLL
    registers / theta hash sets); sketch post-aggs must finalize from the raw
    state, not from the already-finalized estimate column in `table`."""
    if isinstance(p, A.FieldAccess):
        return np.asarray(table[p.field_name])
    if isinstance(p, A.ConstantPost):
        return np.asarray(p.value)
    if isinstance(p, A.Arithmetic):
        vals = [eval_post_agg(f, table, states) for f in p.fields]
        acc = vals[0].astype(np.float64)
        for v in vals[1:]:
            if p.fn == "+":
                acc = acc + v
            elif p.fn == "-":
                acc = acc - v
            elif p.fn == "*":
                acc = acc * v
            elif p.fn == "pow":
                acc = acc ** v
            elif p.fn in ("/", "quotient"):
                with np.errstate(divide="ignore", invalid="ignore"):
                    # x/0 -> 0 is Druid arithmetic-post-agg behavior; but a
                    # NULL numerator stays NULL (the AVG rewrite over a
                    # zero-row group divides NaN sum by 0 count and must
                    # yield SQL NULL, not 0)
                    acc = np.where(
                        v != 0,
                        acc / np.where(v == 0, 1, v),
                        np.where(np.isnan(acc), np.nan, 0.0),
                    )
            else:
                raise ValueError(f"arithmetic fn {p.fn!r}")
        return acc
    if isinstance(p, A.HyperUniqueCardinality):
        from ..ops.hll import estimate as hll_estimate

        if states is None or p.field_name not in states:
            raise KeyError(
                f"hyperUniqueCardinality over {p.field_name!r}: no raw HLL "
                "state available (field must name a hyperUnique/cardinality "
                "aggregation in the same query)"
            )
        return hll_estimate(states[p.field_name])
    if isinstance(p, A.ExpressionPost):
        from ..plan.expr import compile_expr

        fn = compile_expr(p.expression, raw_strings=True)
        cols = {k: np.asarray(v) for k, v in table.items()}
        return np.asarray(fn(cols))
    if isinstance(p, A.QuantileFromSketch):
        from ..ops.quantiles import estimate as quantile_estimate

        if states is None or p.field_name not in states:
            raise KeyError(
                f"quantilesDoublesSketchToQuantile over {p.field_name!r}: "
                "no raw quantiles state available (field must name a "
                "quantilesDoublesSketch aggregation in the same query)"
            )
        return quantile_estimate(states[p.field_name], p.fraction)
    if isinstance(p, A.ThetaSketchEstimate):
        from ..ops.theta import estimate as theta_estimate

        if states is None or p.field_name not in states:
            raise KeyError(
                f"thetaSketchEstimate over {p.field_name!r}: no raw theta "
                "state available (field must name a thetaSketch aggregation "
                "in the same query)"
            )
        return theta_estimate(states[p.field_name])
    if isinstance(p, A.ThetaSketchSetOp):
        from ..ops.theta import set_op_estimate

        bad = [
            f
            for f in p.field_names
            if states is None
            or f not in states
            # theta KMV states are uint32 hash arrays; an HLL register
            # array here would silently produce a garbage estimate
            or np.asarray(states[f]).dtype != np.uint32
        ]
        if bad:
            raise KeyError(
                f"thetaSketchSetOp over {bad}: fields must name "
                "thetaSketch aggregations in the same query"
            )
        return set_op_estimate(p.fn, [states[f] for f in p.field_names])
    raise NotImplementedError(f"post-aggregation {type(p).__name__}")


def _eval_having(h: Q.Having, table: Mapping[str, np.ndarray]) -> np.ndarray:
    if isinstance(h, Q.HavingCompare):
        v = np.asarray(table[h.aggregation], dtype=np.float64)
        return {
            ">": v > h.value,
            "<": v < h.value,
            ">=": v >= h.value,
            "<=": v <= h.value,
            "==": v == h.value,
            "!=": v != h.value,
        }[h.op]
    if isinstance(h, Q.HavingAnd):
        m = _eval_having(h.specs[0], table)
        for s in h.specs[1:]:
            m &= _eval_having(s, table)
        return m
    if isinstance(h, Q.HavingOr):
        m = _eval_having(h.specs[0], table)
        for s in h.specs[1:]:
            m |= _eval_having(s, table)
        return m
    if isinstance(h, Q.HavingNot):
        return ~_eval_having(h.spec, table)
    raise NotImplementedError(type(h).__name__)


# ---------------------------------------------------------------------------
# Engine
# ---------------------------------------------------------------------------


def _merge_sketch_states(
    la: LoweredAggs, acc: Dict[str, Any], new: Dict[str, Any]
) -> None:
    """Merge one segment's sketch partials into the accumulator in place:
    HLL registers max-merge; theta states union (shared with streaming)."""
    from ..ops import theta as theta_ops

    for agg in la.sketch_aggs:
        st = new[agg.name]
        prev = acc.get(agg.name)
        if prev is None:
            acc[agg.name] = st
        elif isinstance(agg, (A.HyperUnique, A.CardinalityAgg)):
            acc[agg.name] = jnp.maximum(prev, st)
        elif isinstance(agg, A.QuantilesSketch):
            from ..ops import quantiles as quantiles_ops

            acc[agg.name] = quantiles_ops.merge_states(prev, st, agg.size)
        else:
            acc[agg.name] = theta_ops.merge_states(prev, st, agg.size)


# ---------------------------------------------------------------------------
# Shared finalization (also used by the distributed path)
# ---------------------------------------------------------------------------


def finalize_groupby(
    q: Q.GroupByQuery,
    dims: List[ResolvedDim],
    la: LoweredAggs,
    sums: np.ndarray,
    mins: np.ndarray,
    maxs: np.ndarray,
    sketch_states: Dict[str, np.ndarray],
    slot_gids: Optional[np.ndarray] = None,
):
    """Merged partial state -> result DataFrame (decode, post-aggs, having,
    order/limit) — the broker-side finalization of SURVEY.md §3.3.

    `slot_gids` switches to sparse-state layout (ops/sparse_groupby.py):
    arrays are slot-indexed and slot_gids maps slot -> combined gid (-1 =
    empty slot)."""
    import pandas as pd

    rows_per_group = sums[:, 0]
    if slot_gids is not None:
        present = (slot_gids >= 0) & (rows_per_group > 0)
        sel = np.nonzero(present)[0]
        idx = slot_gids[sel].astype(np.int64)  # combined gid per kept row
        empty_group = np.zeros(len(sel), dtype=bool)
    else:
        present = rows_per_group > 0
        if not dims:
            # SQL: a global aggregate always yields one row (COUNT=0, SUM/
            # MIN/MAX=NULL when nothing matched) — never an empty result
            present = np.ones_like(present, dtype=bool)
        sel = np.nonzero(present)[0]
        idx = sel.astype(np.int64)
        empty_group = rows_per_group[sel] == 0

    table: Dict[str, np.ndarray] = {}
    # decode combined gid -> per-dimension codes (row-major order)
    rem = idx
    codes_list = []
    for d in reversed(dims):
        codes_list.append((rem % d.cardinality).astype(np.int64))
        rem = rem // d.cardinality
    codes_list.reverse()
    for d, codes in zip(dims, codes_list):
        table[d.spec.name] = d.decode(codes)

    for j, n in enumerate(la.sum_names):
        if n == "__rows":
            continue
        v = sums[sel, j].astype(np.float64)
        if n in la.count_like or not empty_group.any():
            table[n] = np.rint(v).astype(np.int64) if la.long_valued[n] else v
        else:
            # SQL: SUM over zero rows is NULL; COUNT stays 0
            table[n] = np.where(empty_group, np.nan, v)
    for n, src in la.aliased.items():
        # unfiltered COUNT reads the __rows presence counter directly
        j = la.sum_names.index(src)
        table[n] = np.rint(sums[sel, j].astype(np.float64)).astype(np.int64)
    def _finalize_extremum(v: np.ndarray, long_valued: bool) -> np.ndarray:
        v = v.astype(np.float64)
        v = np.where(np.isinf(v), np.nan, v)
        if long_valued and not np.isnan(v).any():
            return np.rint(v).astype(np.int64)
        return v

    for j, n in enumerate(la.min_names):
        table[n] = _finalize_extremum(mins[sel, j], la.long_valued[n])
    for j, n in enumerate(la.max_names):
        table[n] = _finalize_extremum(maxs[sel, j], la.long_valued[n])

    raw_states: Dict[str, np.ndarray] = {}
    for agg in la.sketch_aggs:
        from ..ops import hll as hll_ops
        from ..ops import theta as theta_ops

        st = sketch_states[agg.name][sel]
        raw_states[agg.name] = st
        if isinstance(agg, (A.HyperUnique, A.CardinalityAgg)):
            table[agg.name] = np.rint(hll_ops.estimate(st)).astype(np.int64)
        elif isinstance(agg, A.QuantilesSketch):
            from ..ops import quantiles as quantiles_ops

            # Druid finalizes a quantiles sketch to its N; the state
            # carries the exact per-group row count in its trailing
            # counter row, so this is exact at any scale.  Quantile values
            # come from the QuantileFromSketch post-agg over the raw state
            table[agg.name] = quantiles_ops.count(st).astype(np.int64)
        else:
            table[agg.name] = np.rint(theta_ops.estimate(st)).astype(np.int64)

    for p in q.post_aggregations:
        table[p.name] = np.broadcast_to(
            eval_post_agg(p, table, raw_states), sel.shape
        ).copy()

    if q.having is not None:
        m = _eval_having(q.having, table)
        table = {k: np.asarray(v)[m] for k, v in table.items()}

    df = pd.DataFrame(table)

    # grouping-set subtotals (CUBE/ROLLUP) are handled by the planner issuing
    # one query per set and concatenating — see plan/transforms.py.

    if q.limit_spec is not None:
        df = apply_limit_spec(df, q.limit_spec)
    return df.reset_index(drop=True)


def apply_limit_spec(df, ls):
    """Sort/offset/limit per a LimitSpec — the ONE implementation, shared
    by groupBy finalization and grouping-set combination (api.py); null
    keys (grouping-set rows that aggregate a sort dimension away) order
    last."""
    if ls.columns:
        df = df.sort_values(
            [c.dimension for c in ls.columns],
            ascending=[c.direction == "ascending" for c in ls.columns],
            kind="stable",
            na_position="last",
        )
    if ls.offset:
        df = df.iloc[ls.offset:]
    if ls.limit is not None:
        df = df.head(ls.limit)
    return df


# ---------------------------------------------------------------------------
# Column discovery helpers
# ---------------------------------------------------------------------------


